"""CLI entrypoint: `python -m pingoo_tpu [--config PATH]`.

Reference parity (pingoo/main.rs:33-85): logging init -> config load ->
shutdown signal watch -> optional child process (sidecar mode,
main.rs:60-80) -> server run. The reference takes no CLI flags and uses
fixed /etc/pingoo paths; we accept overrides for testability but default
to the same locations.
"""

from __future__ import annotations

import argparse
import asyncio
import subprocess
import sys

from .config import DEFAULT_CONFIG_FILE, ConfigError, load_and_validate
from .logging_utils import get_logger, init_logging

log = get_logger("pingoo_tpu")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="pingoo-tpu")
    parser.add_argument("--config", default=DEFAULT_CONFIG_FILE)
    parser.add_argument("--no-device", action="store_true",
                        help="CPU-interpreter rules engine only")
    parser.add_argument("--no-docker", action="store_true")
    parser.add_argument("--cache-dir", default=None,
                        help="compiled-ruleset artifact cache directory")
    parser.add_argument("--bot-score-params", default=None,
                        help="npz of trained bot-score head weights "
                             "(models/botscore.save_params)")
    parser.add_argument("--native-plane", action="store_true",
                        help="front traffic with the C++ data plane "
                             "(epoll httpd + shared-memory verdict ring); "
                             "the Python plane moves to loopback as the "
                             "captcha/fail-open target")
    parser.add_argument("--native-workers", type=int, default=1,
                        help="SO_REUSEPORT httpd workers per listener "
                             "(one verdict ring each)")
    parser.add_argument("--state-dir", default="/var/run/pingoo",
                        help="ring files + services table directory "
                             "(native plane)")
    parser.add_argument("--upstream-ca", default=None,
                        help="PEM trust bundle for TLS upstream hops "
                             "(native plane; system roots by default)")
    args = parser.parse_args(argv)

    init_logging()
    try:
        config = load_and_validate(args.config)
    except ConfigError as exc:
        log.error(str(exc))
        return 1

    child = None
    if config.child_process is not None:
        # Sidecar mode: run the fronted app as a child (main.rs:60-80).
        child = subprocess.Popen(list(config.child_process.command))
        log.info("child process started",
                 extra={"fields": {"pid": child.pid}})

    log.info("starting pingoo-tpu", extra={"fields": {
        "config": args.config,
        "listeners": [f"{l.protocol.value}://{l.host}:{l.port}"
                      for l in config.listeners],
        "rules": len(config.rules),
        "device": not args.no_device,
        "native_plane": args.native_plane,
    }})
    try:
        if args.native_plane:
            from .host.native_plane import run_native

            asyncio.run(run_native(
                config, state_dir=args.state_dir,
                workers=args.native_workers,
                upstream_ca=args.upstream_ca,
                use_device=not args.no_device,
                enable_docker=not args.no_docker,
                cache_dir=args.cache_dir,
                bot_score_params_path=args.bot_score_params))
        else:
            from .host.server import run

            asyncio.run(run(config, use_device=not args.no_device,
                            enable_docker=not args.no_docker,
                            cache_dir=args.cache_dir,
                            bot_score_params_path=args.bot_score_params))
    except KeyboardInterrupt:
        pass
    finally:
        if child is not None:
            child.terminate()
    return 0


if __name__ == "__main__":
    sys.exit(main())
