"""Structured JSON logging to stderr.

Reference parity (pingoo/main.rs:34-44): tracing-subscriber JSON output,
flattened event fields, level from the PINGOO_LOG env var (default
info). Python logging is adapted to the same shape:
  {"timestamp": ..., "level": "INFO", "target": "pingoo_tpu.host.httpd",
   "message": ..., **fields}
Use `log = get_logger(__name__); log.info("msg", extra={"fields": {...}})`.

The sampled access log (obs/trace.AccessLogSampler) emits through the
same pipeline under the `pingoo_tpu.access` target: one line per
sampled request with `trace_id`, method/path/status, client_ip and
duration_ms — the trace id matches the response's x-pingoo-trace-id
header, so a slow response in hand joins directly against the log.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "timestamp": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        fields = getattr(record, "fields", None)
        if isinstance(fields, dict):
            payload.update(fields)
        if record.exc_info:
            payload["exception"] = self.formatException(record.exc_info)
        # default=repr: a non-JSON-safe field value (Path, bytes, an
        # exception object in access-log extras) must degrade to its
        # repr, never take down the logging pipeline mid-request.
        return json.dumps(payload, default=repr)


def init_logging(level: str | None = None) -> None:
    level_name = (level or os.environ.get("PINGOO_LOG", "info")).upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(JsonFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level_name, logging.INFO))


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
