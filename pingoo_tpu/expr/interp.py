"""Tree-walk interpreter: the CPU engine and TPU-parity oracle.

This is the reference semantics for the whole framework. The TPU compiler
(pingoo_tpu/compiler) must produce bit-exact verdicts against this
interpreter — that is the FP/FN-parity target in BASELINE.md — so every
semantic choice here is written down:

  * Logical && / || short-circuit strictly left-to-right. An error in the
    left operand is an error; an error in the right operand only matters
    if the left operand did not already decide the result.
  * Runtime errors (type mismatch, missing map key, index out of bounds,
    div-by-zero, integer overflow) raise EvalError; rule matching treats
    that as no-match (reference pingoo/rules.rs:41-44 logs and returns
    false).
  * Int is checked signed 64-bit; Int/Int division truncates toward zero
    and % takes the dividend's sign (Rust i64 semantics, since the
    reference language is implemented in Rust).
  * Numeric comparisons allow Int/Float cross-type; equality across other
    type pairs is an error (not `false`): the least surprising reading of
    docs/rules.md:37's "surprising things trimmed off".
  * String length / ordering are byte-wise over UTF-8 (Rust `str`
    semantics), which also matches the byte-tensor view the TPU engine
    has of every string.
  * Ip == String parses the string as an ip; Array<Ip>.contains(ip) is
    CIDR-aware containment (docs/rules.md:110).
"""

from __future__ import annotations

import math
from typing import Mapping

from . import ast
from .errors import EvalError
from .values import Ip, Regex, checked_i64, type_name


class Context:
    """Variable bindings for one evaluation.

    Mirrors the reference's `bel::Context` surface: `add_variable`
    (http_listener.rs:242-247 adds `http_request` and `client`) and
    `add_variable_from_value` (http_listener.rs:249 adds `lists`).
    """

    __slots__ = ("variables",)

    def __init__(self, variables: Mapping[str, object] | None = None):
        self.variables: dict[str, object] = dict(variables or {})

    def add_variable(self, name: str, value: object) -> None:
        self.variables[name] = value


def evaluate(node: ast.Node, ctx: Context) -> object:
    """Evaluate `node` against `ctx`. Raises EvalError on runtime errors."""
    return _eval(node, ctx)


def _eval(node: ast.Node, ctx: Context) -> object:
    if isinstance(node, ast.Literal):
        return node.value
    if isinstance(node, ast.Ident):
        try:
            return ctx.variables[node.name]
        except KeyError:
            raise EvalError(f"unknown variable {node.name!r}") from None
    if isinstance(node, ast.Member):
        obj = _eval(node.obj, ctx)
        if isinstance(obj, dict):
            try:
                return obj[node.attr]
            except KeyError:
                raise EvalError(f"unknown field {node.attr!r}") from None
        raise EvalError(f"cannot access field {node.attr!r} on {type_name(obj)}")
    if isinstance(node, ast.Index):
        return _index(_eval(node.obj, ctx), _eval(node.key, ctx))
    if isinstance(node, ast.Call):
        return _call(node, ctx)
    if isinstance(node, ast.Unary):
        return _unary(node, ctx)
    if isinstance(node, ast.Logical):
        return _logical(node, ctx)
    if isinstance(node, ast.Binary):
        return _binary(node.op, _eval(node.left, ctx), _eval(node.right, ctx))
    if isinstance(node, ast.ArrayLit):
        return [_eval(it, ctx) for it in node.items]
    if isinstance(node, ast.MapLit):
        out = {}
        for k, v in node.entries:
            key = _eval(k, ctx)
            if not isinstance(key, (str, int)) or isinstance(key, bool):
                raise EvalError(f"invalid map key type {type_name(key)}")
            out[key] = _eval(v, ctx)
        return out
    raise EvalError(f"cannot evaluate {type(node).__name__}")


def _index(obj: object, key: object) -> object:
    if isinstance(obj, dict):
        if isinstance(key, bool) or not isinstance(key, (str, int)):
            raise EvalError(f"invalid map key type {type_name(key)}")
        try:
            return obj[key]
        except KeyError:
            raise EvalError(f"map key not found: {key!r}") from None
    if isinstance(obj, list):
        if isinstance(key, bool) or not isinstance(key, int):
            raise EvalError("array index must be Int")
        if key < 0 or key >= len(obj):
            raise EvalError(f"array index {key} out of bounds")
        return obj[key]
    raise EvalError(f"cannot index {type_name(obj)}")


def _logical(node: ast.Logical, ctx: Context) -> bool:
    left = _eval(node.left, ctx)
    if not isinstance(left, bool):
        raise EvalError(f"{node.op} requires Bool, got {type_name(left)}")
    if node.op == "||" and left:
        return True
    if node.op == "&&" and not left:
        return False
    right = _eval(node.right, ctx)
    if not isinstance(right, bool):
        raise EvalError(f"{node.op} requires Bool, got {type_name(right)}")
    return right


def _unary(node: ast.Unary, ctx: Context) -> object:
    val = _eval(node.operand, ctx)
    if node.op == "!":
        if not isinstance(val, bool):
            raise EvalError(f"! requires Bool, got {type_name(val)}")
        return not val
    if node.op == "-":
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            raise EvalError(f"unary - requires Int or Float, got {type_name(val)}")
        if isinstance(val, int):
            return checked_i64(-val)
        return -val
    raise EvalError(f"unknown unary operator {node.op}")


def _is_num(v: object) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _binary(op: str, left: object, right: object) -> object:
    if op in ("==", "!="):
        eq = _equals(left, right)
        return eq if op == "==" else not eq
    if op in ("<", "<=", ">", ">="):
        return _ordered(op, left, right)
    return _arith(op, left, right)


def _equals(left: object, right: object) -> bool:
    if isinstance(left, bool) or isinstance(right, bool):
        if isinstance(left, bool) and isinstance(right, bool):
            return left is right
        raise EvalError(
            f"cannot compare {type_name(left)} with {type_name(right)}"
        )
    if _is_num(left) and _is_num(right):
        return float(left) == float(right) if type(left) is not type(right) else left == right
    if isinstance(left, str) and isinstance(right, str):
        return left == right
    if isinstance(left, Ip) or isinstance(right, Ip):
        return _ip_equals(left, right)
    if isinstance(left, list) and isinstance(right, list):
        if len(left) != len(right):
            return False
        return all(_equals(a, b) for a, b in zip(left, right))
    if isinstance(left, dict) and isinstance(right, dict):
        if left.keys() != right.keys():
            return False
        return all(_equals(left[k], right[k]) for k in left)
    raise EvalError(f"cannot compare {type_name(left)} with {type_name(right)}")


def _ip_equals(left: object, right: object) -> bool:
    lip = _as_ip(left)
    rip = _as_ip(right)
    return lip == rip


def _as_ip(value: object) -> Ip:
    if isinstance(value, Ip):
        return value
    if isinstance(value, str):
        return Ip(value)  # raises EvalError on bad text
    raise EvalError(f"cannot convert {type_name(value)} to Ip")


def _ordered(op: str, left: object, right: object) -> bool:
    if _is_num(left) and _is_num(right):
        pass
    elif isinstance(left, str) and isinstance(right, str):
        # Codepoint ordering == byte ordering under the latin-1 byte view
        # (see _length); nothing to convert.
        pass
    else:
        raise EvalError(f"cannot order {type_name(left)} and {type_name(right)}")
    if op == "<":
        return left < right
    if op == "<=":
        return left <= right
    if op == ">":
        return left > right
    return left >= right


def _arith(op: str, left: object, right: object) -> object:
    if op == "+" and isinstance(left, str) and isinstance(right, str):
        return left + right
    if op == "+" and isinstance(left, list) and isinstance(right, list):
        return left + right
    if not (_is_num(left) and _is_num(right)):
        raise EvalError(
            f"operator {op} requires numeric operands, got "
            f"{type_name(left)} and {type_name(right)}"
        )
    both_int = isinstance(left, int) and isinstance(right, int)
    if op == "+":
        return checked_i64(left + right) if both_int else float(left) + float(right)
    if op == "-":
        return checked_i64(left - right) if both_int else float(left) - float(right)
    if op == "*":
        return checked_i64(left * right) if both_int else float(left) * float(right)
    if op == "/":
        if both_int:
            if right == 0:
                raise EvalError("division by zero")
            # Rust i64 division truncates toward zero.
            return checked_i64(_trunc_div(left, right))
        lf, rf = float(left), float(right)
        if rf == 0.0:
            # IEEE float semantics (Rust f64): inf/nan, not an error.
            if lf == 0.0 or math.isnan(lf):
                return math.nan
            return math.inf * math.copysign(1.0, lf) * math.copysign(1.0, rf)
        return lf / rf
    if op == "%":
        if both_int:
            if right == 0:
                raise EvalError("division by zero")
            # Rust % takes the dividend's sign.
            return checked_i64(left - _trunc_div(left, right) * right)
        lf, rf = float(left), float(right)
        if rf == 0.0 or math.isinf(lf) or math.isnan(lf) or math.isnan(rf):
            # IEEE remainder edge cases (Rust f64: inf % x == NaN, x % 0.0
            # == NaN); math.fmod would raise ValueError on an inf dividend.
            return math.nan
        return math.fmod(lf, rf)
    raise EvalError(f"unknown operator {op}")


def _trunc_div(a: int, b: int) -> int:
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


# -- functions ---------------------------------------------------------------

_METHODS = {"contains", "length", "starts_with", "ends_with", "matches"}
_FREE_FUNCS = {"length"}


def _call(node: ast.Call, ctx: Context) -> object:
    if node.recv is None:
        if node.func not in _FREE_FUNCS:
            raise EvalError(f"unknown function {node.func!r}")
        if len(node.args) != 1:
            raise EvalError(f"{node.func}() takes exactly 1 argument")
        return _length(_eval(node.args[0], ctx))
    if node.func not in _METHODS:
        raise EvalError(f"unknown function {node.func!r}")
    recv = _eval(node.recv, ctx)
    args = [_eval(a, ctx) for a in node.args]
    if node.func == "length":
        if args:
            raise EvalError("length() takes no arguments")
        return _length(recv)
    if len(args) != 1:
        raise EvalError(f"{node.func}() takes exactly 1 argument")
    arg = args[0]
    if node.func == "contains":
        return _contains(recv, arg)
    if node.func == "starts_with":
        _want_strings(node.func, recv, arg)
        return recv.startswith(arg)
    if node.func == "ends_with":
        _want_strings(node.func, recv, arg)
        return recv.endswith(arg)
    if node.func == "matches":
        if not isinstance(recv, str):
            raise EvalError(f"matches() requires String receiver, got {type_name(recv)}")
        if isinstance(arg, Regex):
            return arg.search(recv)
        if isinstance(arg, str):
            return Regex.cached(arg).search(recv)
        raise EvalError(f"matches() requires String or Regex argument, got {type_name(arg)}")
    raise EvalError(f"unknown function {node.func!r}")  # pragma: no cover


def _length(value: object) -> int:
    if isinstance(value, str):
        # Byte length under the framework's canonical string view: host
        # code materializes request strings by latin-1-decoding the raw
        # bytes (bijective), so char count == byte count. This matches
        # the device engine, which only ever sees byte tensors.
        return len(value)
    if isinstance(value, (list, dict)):
        return len(value)
    raise EvalError(f"length() requires String, Array or Map, got {type_name(value)}")


def _want_strings(func: str, recv: object, arg: object) -> None:
    if not isinstance(recv, str) or not isinstance(arg, str):
        raise EvalError(
            f"{func}() requires String receiver and argument, got "
            f"{type_name(recv)} and {type_name(arg)}"
        )


def _contains(recv: object, arg: object) -> bool:
    if isinstance(recv, str):
        if not isinstance(arg, str):
            raise EvalError(f"String.contains() requires String, got {type_name(arg)}")
        return arg in recv
    if isinstance(recv, list):
        if any(isinstance(item, Ip) for item in recv) or isinstance(arg, Ip):
            target = _as_ip(arg)
            return any(_as_ip(item).contains(target) for item in recv)
        for item in recv:
            try:
                if _equals(item, arg):
                    return True
            except EvalError:
                continue
        return False
    raise EvalError(f"contains() requires String or Array receiver, got {type_name(recv)}")
