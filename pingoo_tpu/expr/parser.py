"""Recursive-descent (Pratt) parser for the rule expression language.

Grammar (CEL subset per reference docs/rules.md, `in` excluded per
rules/rules.rs:69-71):

    expr        := or
    or          := and ("||" and)*
    and         := rel ("&&" rel)*
    rel         := add (("=="|"!="|"<"|"<="|">"|">=") add)?   // non-assoc
    add         := mul (("+"|"-") mul)*
    mul         := unary (("*"|"/"|"%") unary)*
    unary       := ("!"|"-")* postfix
    postfix     := primary ("." IDENT ("(" args ")")? | "[" expr "]"
                           | "(" args ")" )*
    primary     := literal | IDENT | "(" expr ")" | array | map
    array       := "[" (expr ("," expr)*)? "]"
    map         := "{" (expr ":" expr ("," expr ":" expr)*)? "}"

Relations are intentionally non-associative (`a < b < c` is a parse
error): that is one of CEL's "surprising things" the reference's language
trims off (docs/rules.md:37).
"""

from __future__ import annotations

from . import ast
from .errors import CompileError
from .lexer import BOOL, EOF, FLOAT, IDENT, INT, OP, STRING, Token, tokenize
from .values import I64_MAX, I64_MIN

_REL_OPS = ("==", "!=", "<", "<=", ">", ">=")


def parse(src: str) -> ast.Node:
    """Parse `src` into an AST. Raises CompileError on invalid input.

    Empty expressions are invalid, matching the reference's
    validate_expression (rules/rules.rs:56-58).
    """
    if not src or not src.strip():
        raise CompileError("expression is empty")
    root = _Parser(tokenize(src)).parse()
    for node in ast.walk(root):
        # Int literals must fit i64 (negative literals were constant-folded
        # in _unary, so I64_MIN is representable).
        if (
            isinstance(node, ast.Literal)
            and isinstance(node.value, int)
            and not isinstance(node.value, bool)
            and not (I64_MIN <= node.value <= I64_MAX)
        ):
            raise CompileError("integer literal out of i64 range", node.pos)
    return root


class _Parser:
    def __init__(self, tokens: list[Token]):
        self._toks = tokens
        self._i = 0

    # -- token helpers -----------------------------------------------------
    @property
    def _cur(self) -> Token:
        return self._toks[self._i]

    def _advance(self) -> Token:
        tok = self._toks[self._i]
        if tok.kind != EOF:
            self._i += 1
        return tok

    def _at_op(self, *ops: str) -> bool:
        return self._cur.kind == OP and self._cur.value in ops

    def _eat_op(self, op: str) -> Token:
        if not self._at_op(op):
            raise CompileError(f"expected {op!r}", self._cur.pos)
        return self._advance()

    # -- grammar -----------------------------------------------------------
    def parse(self) -> ast.Node:
        node = self._or()
        if self._cur.kind != EOF:
            raise CompileError(
                f"unexpected trailing input {self._cur.value!r}", self._cur.pos
            )
        return node

    def _or(self) -> ast.Node:
        node = self._and()
        while self._at_op("||"):
            pos = self._advance().pos
            node = ast.Logical(pos=pos, op="||", left=node, right=self._and())
        return node

    def _and(self) -> ast.Node:
        node = self._rel()
        while self._at_op("&&"):
            pos = self._advance().pos
            node = ast.Logical(pos=pos, op="&&", left=node, right=self._rel())
        return node

    def _rel(self) -> ast.Node:
        node = self._add()
        if self._at_op(*_REL_OPS):
            op_tok = self._advance()
            right = self._add()
            node = ast.Binary(pos=op_tok.pos, op=op_tok.value, left=node, right=right)
            if self._at_op(*_REL_OPS):
                raise CompileError(
                    "comparison operators are non-associative", self._cur.pos
                )
        return node

    def _add(self) -> ast.Node:
        node = self._mul()
        while self._at_op("+", "-"):
            op_tok = self._advance()
            node = ast.Binary(
                pos=op_tok.pos, op=op_tok.value, left=node, right=self._mul()
            )
        return node

    def _mul(self) -> ast.Node:
        node = self._unary()
        while self._at_op("*", "/", "%"):
            op_tok = self._advance()
            node = ast.Binary(
                pos=op_tok.pos, op=op_tok.value, left=node, right=self._unary()
            )
        return node

    def _unary(self) -> ast.Node:
        if self._at_op("!", "-"):
            op_tok = self._advance()
            operand = self._unary()
            if (
                op_tok.value == "-"
                and isinstance(operand, ast.Literal)
                and isinstance(operand.value, (int, float))
                and not isinstance(operand.value, bool)
            ):
                # Constant-fold negative numeric literals so that i64::MIN
                # is writable (checked_i64(-(2**63)) would otherwise be
                # unreachable from the grammar).
                return ast.Literal(pos=op_tok.pos, value=-operand.value)
            return ast.Unary(pos=op_tok.pos, op=op_tok.value, operand=operand)
        return self._postfix()

    def _postfix(self) -> ast.Node:
        node = self._primary()
        while True:
            if self._at_op("."):
                self._advance()
                if self._cur.kind != IDENT:
                    raise CompileError("expected identifier after '.'", self._cur.pos)
                name_tok = self._advance()
                if self._at_op("("):
                    args = self._args()
                    node = ast.Call(
                        pos=name_tok.pos, recv=node, func=name_tok.value, args=args
                    )
                else:
                    node = ast.Member(pos=name_tok.pos, obj=node, attr=name_tok.value)
            elif self._at_op("["):
                pos = self._advance().pos
                key = self._or()
                self._eat_op("]")
                node = ast.Index(pos=pos, obj=node, key=key)
            elif self._at_op("(") and isinstance(node, ast.Ident):
                # Bare function call: length(x). Only identifiers are
                # callable; `(a)(b)` is a parse error.
                args = self._args()
                node = ast.Call(pos=node.pos, recv=None, func=node.name, args=args)
            else:
                return node

    def _args(self) -> tuple[ast.Node, ...]:
        self._eat_op("(")
        args: list[ast.Node] = []
        if not self._at_op(")"):
            args.append(self._or())
            while self._at_op(","):
                self._advance()
                args.append(self._or())
        self._eat_op(")")
        return tuple(args)

    def _primary(self) -> ast.Node:
        tok = self._cur
        if tok.kind in (INT, FLOAT, STRING, BOOL):
            self._advance()
            return ast.Literal(pos=tok.pos, value=tok.value)
        if tok.kind == IDENT:
            self._advance()
            return ast.Ident(pos=tok.pos, name=tok.value)
        if self._at_op("("):
            self._advance()
            node = self._or()
            self._eat_op(")")
            return node
        if self._at_op("["):
            pos = self._advance().pos
            items: list[ast.Node] = []
            if not self._at_op("]"):
                items.append(self._or())
                while self._at_op(","):
                    self._advance()
                    items.append(self._or())
            self._eat_op("]")
            return ast.ArrayLit(pos=pos, items=tuple(items))
        if self._at_op("{"):
            pos = self._advance().pos
            entries: list[tuple[ast.Node, ast.Node]] = []
            if not self._at_op("}"):
                entries.append(self._map_entry())
                while self._at_op(","):
                    self._advance()
                    entries.append(self._map_entry())
            self._eat_op("}")
            return ast.MapLit(pos=pos, entries=tuple(entries))
        if tok.kind == EOF:
            raise CompileError("unexpected end of input", tok.pos)
        raise CompileError(f"unexpected token {tok.value!r}", tok.pos)

    def _map_entry(self) -> tuple[ast.Node, ast.Node]:
        key = self._or()
        self._eat_op(":")
        value = self._or()
        return key, value
