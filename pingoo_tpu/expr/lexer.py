"""Tokenizer for the rule expression language.

The language is the CEL subset documented by the reference at
docs/rules.md ("a subset of the Common Expression Language (CEL) with all
the inconsistencies and 'surprising' things trimmed off"). The reference
consumes it through the external `bel` crate; we implement the language
from the documented surface (docs/rules.md:37-76) rather than from that
crate's internals.

Token set: identifiers, int/float/string literals, `true`/`false`, the
operators `|| && ! == != < <= > >= + - * / %`, and the punctuation
`( ) [ ] { } , . :`. The `in` operator is intentionally NOT a token: the
reference rejects it at validation time (rules/rules.rs:69-71), so we
reject it at lex/parse time with the same user-facing message.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

from .errors import CompileError

# Token kinds
IDENT = "IDENT"
INT = "INT"
FLOAT = "FLOAT"
STRING = "STRING"
BOOL = "BOOL"
OP = "OP"  # operators and punctuation; value holds the exact lexeme
EOF = "EOF"

_PUNCT2 = ("||", "&&", "==", "!=", "<=", ">=")
_PUNCT1 = "!<>+-*/%()[]{},.:"

_KEYWORDS = {"true", "false"}
# Reserved words we refuse outright. `in` mirrors the reference's explicit
# rejection (rules/rules.rs:69-71: "unknown operator: in"); `null` is part
# of full CEL but not of the documented bel type list (docs/rules.md:40-48).
_RESERVED = {"in", "null"}

_ESCAPES = {
    "n": "\n",
    "r": "\r",
    "t": "\t",
    "\\": "\\",
    '"': '"',
    "'": "'",
    "0": "\0",
}


@dataclass(frozen=True)
class Token:
    kind: str
    value: object  # str for IDENT/OP/STRING, int/float for numbers, bool
    pos: int  # byte offset of the first character, for error messages

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Token({self.kind}, {self.value!r}, @{self.pos})"


def tokenize(src: str) -> list[Token]:
    """Tokenize `src`, raising CompileError on any invalid input.

    The reference treats an empty expression as invalid
    (rules/rules.rs:56-58); we defer that check to the parser so that the
    lexer stays a pure function of characters.
    """
    return list(_tokens(src))


def _tokens(src: str) -> Iterator[Token]:
    i = 0
    n = len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "/" and src.startswith("//", i):
            # Line comments, CEL-style.
            j = src.find("\n", i)
            i = n if j == -1 else j + 1
            continue
        start = i
        two = src[i : i + 2]
        if two in _PUNCT2:
            yield Token(OP, two, start)
            i += 2
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            tok, i = _lex_number(src, i)
            yield tok
            continue
        if c in _PUNCT1:
            yield Token(OP, c, start)
            i += 1
            continue
        if c in "\"'":
            tok, i = _lex_string(src, i)
            yield tok
            continue
        if c.isalpha() or c == "_":
            j = i + 1
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            if word in _RESERVED:
                raise CompileError(f"unknown operator: {word}", start)
            if word in _KEYWORDS:
                yield Token(BOOL, word == "true", start)
            else:
                yield Token(IDENT, word, start)
            i = j
            continue
        raise CompileError(f"unexpected character {c!r}", i)
    yield Token(EOF, None, n)


def _lex_number(src: str, i: int) -> tuple[Token, int]:
    start = i
    n = len(src)
    if src.startswith("0x", i) or src.startswith("0X", i):
        j = i + 2
        while j < n and src[j] in "0123456789abcdefABCDEF":
            j += 1
        if j == i + 2:
            raise CompileError("invalid hex literal", start)
        return Token(INT, int(src[start:j], 16), start), j
    j = i
    is_float = False
    while j < n and src[j].isdigit():
        j += 1
    if j < n and src[j] == "." and j + 1 < n and src[j + 1].isdigit():
        is_float = True
        j += 1
        while j < n and src[j].isdigit():
            j += 1
    if j < n and src[j] in "eE":
        k = j + 1
        if k < n and src[k] in "+-":
            k += 1
        if k < n and src[k].isdigit():
            is_float = True
            j = k
            while j < n and src[j].isdigit():
                j += 1
    text = src[start:j]
    if is_float:
        return Token(FLOAT, float(text), start), j
    return Token(INT, int(text), start), j


_HEX = set("0123456789abcdefABCDEF")


def _lex_string(src: str, i: int) -> tuple[Token, int]:
    """Lex a string literal into the framework's canonical byte view.

    String values are sequences of BYTES presented as latin-1 strings
    (one char per byte, bijective — see expr/values.py). Source
    characters encode as their UTF-8 bytes (so a literal "café" compares
    equal to the UTF-8 wire bytes of café, matching the Rust reference's
    &str semantics, and "é".length() == 2 like Rust's str::len);
    `\\xhh` injects the raw byte hh; `\\uXXXX` injects the codepoint's
    UTF-8 bytes.
    """
    quote = src[i]
    start = i
    i += 1
    n = len(src)
    out = bytearray()
    while i < n:
        c = src[i]
        if c == quote:
            return Token(STRING, bytes(out).decode("latin-1"), start), i + 1
        if c == "\\":
            if i + 1 >= n:
                break
            esc = src[i + 1]
            if esc in _ESCAPES:
                out += _ESCAPES[esc].encode("utf-8")
                i += 2
                continue
            if esc == "x" and i + 3 < n:
                hex_digits = src[i + 2 : i + 4]
                if len(hex_digits) != 2 or not set(hex_digits) <= _HEX:
                    raise CompileError("invalid \\x escape", i)
                out.append(int(hex_digits, 16))
                i += 4
                continue
            if esc == "u" and i + 5 < n:
                hex_digits = src[i + 2 : i + 6]
                if len(hex_digits) != 4 or not set(hex_digits) <= _HEX:
                    raise CompileError("invalid \\u escape", i)
                cp = int(hex_digits, 16)
                if 0xD800 <= cp <= 0xDFFF:
                    raise CompileError("invalid \\u escape: surrogate", i)
                out += chr(cp).encode("utf-8")
                i += 6
                continue
            # Unknown escapes are preserved literally (like Python / YAML
            # single-quoted strings): rule expressions embed regexes
            # ("union\s+select"), and forcing double-backslashes there is
            # exactly the kind of surprise this language trims off.
            out += b"\\"
            out += esc.encode("utf-8")
            i += 2
            continue
        out += c.encode("utf-8")
        i += 1
    raise CompileError("unterminated string literal", start)
