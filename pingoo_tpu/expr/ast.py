"""AST node definitions for the rule expression language.

Nodes are small frozen dataclasses. Every node carries its source offset
(`pos`) so both compile-time and runtime diagnostics can point back into
the expression text, and so the TPU compiler can name host-fallback sites
precisely.

The node set covers the documented bel surface (reference docs/rules.md:
types Bool/String/Int/Float/Ip/Regex/Array/Map; functions contains/length/
starts_with/ends_with; operators of the CEL subset) plus `matches` for
regex predicates (the Regex type at docs/rules.md:47 is otherwise
unreachable from the documented grammar).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass(frozen=True)
class Node:
    pos: int = field(default=-1, compare=False)


@dataclass(frozen=True)
class Literal(Node):
    """Int, Float, String, or Bool literal."""

    value: object = None


@dataclass(frozen=True)
class Ident(Node):
    name: str = ""


@dataclass(frozen=True)
class Member(Node):
    """`obj.field` — member access (e.g. http_request.path)."""

    obj: Node = None
    attr: str = ""


@dataclass(frozen=True)
class Index(Node):
    """`obj[key]` — map/array indexing (e.g. lists["blocked_ips"])."""

    obj: Node = None
    key: Node = None


@dataclass(frozen=True)
class Call(Node):
    """`recv.method(args...)` method call, or bare `func(args...)` when
    recv is None (we accept `length(x)` as well as `x.length()`)."""

    recv: Node | None = None
    func: str = ""
    args: Tuple[Node, ...] = ()


@dataclass(frozen=True)
class Unary(Node):
    """`!x` or `-x`."""

    op: str = ""
    operand: Node = None


@dataclass(frozen=True)
class Binary(Node):
    """Arithmetic / comparison: + - * / % == != < <= > >=."""

    op: str = ""
    left: Node = None
    right: Node = None


@dataclass(frozen=True)
class Logical(Node):
    """`&&` / `||` with short-circuit semantics."""

    op: str = ""
    left: Node = None
    right: Node = None


@dataclass(frozen=True)
class ArrayLit(Node):
    items: Tuple[Node, ...] = ()


@dataclass(frozen=True)
class MapLit(Node):
    entries: Tuple[Tuple[Node, Node], ...] = ()


def walk(node: Node):
    """Yield `node` and all descendants, pre-order."""
    yield node
    if isinstance(node, Member):
        yield from walk(node.obj)
    elif isinstance(node, Index):
        yield from walk(node.obj)
        yield from walk(node.key)
    elif isinstance(node, Call):
        if node.recv is not None:
            yield from walk(node.recv)
        for a in node.args:
            yield from walk(a)
    elif isinstance(node, Unary):
        yield from walk(node.operand)
    elif isinstance(node, (Binary, Logical)):
        yield from walk(node.left)
        yield from walk(node.right)
    elif isinstance(node, ArrayLit):
        for it in node.items:
            yield from walk(it)
    elif isinstance(node, MapLit):
        for k, v in node.entries:
            yield from walk(k)
            yield from walk(v)
