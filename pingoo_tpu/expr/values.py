"""Runtime value model for the expression language.

Documented type set (reference docs/rules.md:40-48): Bool, String, Int,
Float, Ip, Regex, Array<T>, Map<K, V>.

Representation: Python natives for Bool/Int/Float/String/Array(list)/
Map(dict), plus two wrapper types:

  - Ip      — wraps either a single address or a CIDR network
              (`ipaddress` stdlib). List entries may be CIDRs (reference
              pingoo/lists.rs parses `IpNetwork`, lists.rs:86-100) and
              `Array<Ip>.contains(client.ip)` is CIDR containment
              (docs/rules.md:110 usage with a blocked_ips list).
  - Regex   — a compiled pattern; created from the string argument of
              `matches(...)`.

Int semantics are checked 64-bit signed (the reference language is Rust
i64; pingoo/rules.rs:30-33 notes "only signed integers are supported").
Arithmetic that leaves the i64 range is an EvalError -> the rule
no-matches (fail-open, pingoo/rules.rs:41-44).
"""

from __future__ import annotations

import functools
import ipaddress
import re
from typing import Union

from .errors import EvalError

I64_MIN = -(2**63)
I64_MAX = 2**63 - 1

_IpAddr = Union[ipaddress.IPv4Address, ipaddress.IPv6Address]
_IpNet = Union[ipaddress.IPv4Network, ipaddress.IPv6Network]


class Ip:
    """An IP address or CIDR network value."""

    __slots__ = ("addr", "net")

    def __init__(self, value: str | _IpAddr | _IpNet):
        self.addr: _IpAddr | None = None
        self.net: _IpNet | None = None
        if isinstance(value, (ipaddress.IPv4Address, ipaddress.IPv6Address)):
            self.addr = value
        elif isinstance(value, (ipaddress.IPv4Network, ipaddress.IPv6Network)):
            self.net = value
        else:
            text = str(value).strip()
            try:
                if "/" in text:
                    self.net = ipaddress.ip_network(text, strict=False)
                else:
                    self.addr = ipaddress.ip_address(text)
            except ValueError as exc:
                raise EvalError(f"invalid ip: {text!r}") from exc

    @property
    def is_network(self) -> bool:
        return self.net is not None

    def contains(self, other: "Ip") -> bool:
        """CIDR/equality containment: network ∋ address, or address == address."""
        if other.addr is None:
            raise EvalError("contains() argument must be a single ip address")
        if self.net is not None:
            if self.net.version != other.addr.version:
                return False
            return other.addr in self.net
        return self.addr == other.addr

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Ip):
            return NotImplemented
        return self.addr == other.addr and self.net == other.net

    def __hash__(self) -> int:
        return hash((self.addr, self.net))

    def __repr__(self) -> str:
        return f"Ip({self.addr or self.net})"

    def __str__(self) -> str:
        return str(self.addr if self.addr is not None else self.net)


class Regex:
    """A compiled regular expression value.

    `matches` is an unanchored search (CEL `matches` semantics). Patterns
    compile in *bytes mode* over UTF-8: the TPU engine scans byte tensors,
    so byte semantics everywhere keeps the CPU oracle and the device
    kernels bit-identical (ASCII-only \\d\\w\\s, `.` = any byte but \\n —
    also what Rust regex's (?-u) mode does). The pattern text is retained
    so the TPU compiler can re-compile it into a bit-parallel NFA
    (compiler/repat.py, compiler/nfa.py).
    """

    __slots__ = ("pattern", "_re")

    def __init__(self, pattern: str):
        self.pattern = pattern
        try:
            self._re = re.compile(pattern.encode("latin-1"))
        except (re.error, UnicodeEncodeError) as exc:
            raise EvalError(f"invalid regex {pattern!r}: {exc}") from exc

    def search(self, text: str) -> bool:
        try:
            data = text.encode("latin-1")
        except UnicodeEncodeError as exc:
            raise EvalError("non-byte string in matches()") from exc
        return self._re.search(data) is not None

    @staticmethod
    def cached(pattern: str) -> "Regex":
        """Compile-once lookup for the interpreter hot path — host-rule
        fallback evaluates `matches(lit)` per request, and re-compiling
        the pattern each time dominated the whole host batch cost.
        Failures are not cached (identical EvalError every call)."""
        return _regex_cache(pattern)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Regex):
            return NotImplemented
        return self.pattern == other.pattern

    def __hash__(self) -> int:
        return hash(self.pattern)

    def __repr__(self) -> str:
        return f"Regex({self.pattern!r})"


@functools.lru_cache(maxsize=4096)
def _regex_cache(pattern: str) -> Regex:
    return Regex(pattern)


def checked_i64(value: int) -> int:
    if not (I64_MIN <= value <= I64_MAX):
        raise EvalError("integer overflow")
    return value


def type_name(value: object) -> str:
    """Human-readable type name matching docs/rules.md:40-48 vocabulary."""
    if isinstance(value, bool):
        return "Bool"
    if isinstance(value, int):
        return "Int"
    if isinstance(value, float):
        return "Float"
    if isinstance(value, str):
        return "String"
    if isinstance(value, Ip):
        return "Ip"
    if isinstance(value, Regex):
        return "Regex"
    if isinstance(value, list):
        return "Array"
    if isinstance(value, dict):
        return "Map"
    return type(value).__name__
