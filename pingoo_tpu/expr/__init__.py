"""The rule expression language: a bel-compatible CEL subset.

Public surface mirrors the reference's rules-crate boundary
(rules/rules.rs, pingoo/rules.rs): compile_expression / validate_expression
/ Program / Context, plus the value types (Ip, Regex) and error split
(CompileError at config load, EvalError -> no-match at runtime).
"""

from .errors import CompileError, EvalError, ExprError
from .interp import Context, evaluate
from .parser import parse
from .program import (
    Program,
    References,
    compile_expression,
    execute_as_bool,
    validate_expression,
)
from .values import Ip, Regex, type_name

__all__ = [
    "CompileError",
    "Context",
    "EvalError",
    "ExprError",
    "Ip",
    "Program",
    "References",
    "Regex",
    "compile_expression",
    "evaluate",
    "execute_as_bool",
    "parse",
    "type_name",
    "validate_expression",
]
