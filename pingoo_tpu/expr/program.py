"""Compiled-expression API mirroring the reference's rules-crate boundary.

The reference exposes `rules::compile_expression(&str) -> bel::Program`
(rules/rules.rs:45-53), `program.execute(&Context) -> Result<Value>`
(pingoo/rules.rs:39) and `program.references().functions()`
(rules/rules.rs:65-68, used by validate_expression). This module is that
boundary for the TPU framework: everything above it (config loading, rule
matching, the TPU compiler) works with `Program` objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import ast as _ast
from .errors import CompileError, EvalError
from .interp import _FREE_FUNCS, _METHODS, Context, evaluate
from .parser import parse


@dataclass(frozen=True)
class References:
    """Identifiers a program references — compile-time introspection used
    for validation (reference rules/rules.rs:60-71)."""

    functions: frozenset[str] = field(default_factory=frozenset)
    variables: frozenset[str] = field(default_factory=frozenset)


class Program:
    """A compiled expression."""

    __slots__ = ("source", "root", "_refs")

    def __init__(self, source: str, root: _ast.Node):
        self.source = source
        self.root = root
        funcs: set[str] = set()
        vars_: set[str] = set()
        for node in _ast.walk(root):
            if isinstance(node, _ast.Call):
                funcs.add(node.func)
            elif isinstance(node, _ast.Ident):
                vars_.add(node.name)
        self._refs = References(frozenset(funcs), frozenset(vars_))

    @staticmethod
    def compile(source: str) -> "Program":
        return Program(source, parse(source))

    def execute(self, ctx: Context) -> object:
        """Evaluate and return the result value. Raises EvalError."""
        return evaluate(self.root, ctx)

    def references(self) -> References:
        return self._refs

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Program({self.source!r})"


# Single source of truth for the callable surface is the interpreter.
_KNOWN_FUNCTIONS = _METHODS | _FREE_FUNCS
_KNOWN_VARIABLES = {"http_request", "client", "lists"}


def compile_expression(expression: str) -> Program:
    """Compile, raising CompileError on invalid input.

    Reference parity: rules/rules.rs:45-53 (parser panic/-error ->
    ExpressionIsNotValid).
    """
    return Program.compile(expression)


def validate_expression(expression: str) -> None:
    """Validate an expression for use in rules/routes.

    Reference parity: rules/rules.rs:55-77 — empty expressions and the
    `in` operator are rejected (the lexer already refuses `in`); unknown
    functions and variables are additionally rejected here since, unlike
    the reference's TODO (:73), we know the full variable surface.
    """
    program = Program.compile(expression)  # rejects empty input in parse()
    refs = program.references()
    for func in sorted(refs.functions):
        if func not in _KNOWN_FUNCTIONS:
            raise CompileError(f"unknown function: {func}")
    for var in sorted(refs.variables):
        if var not in _KNOWN_VARIABLES:
            raise CompileError(f"unknown variable: {var}")


def execute_as_bool(program: Program, ctx: Context) -> bool:
    """Run a program for rule matching: the result matches only if it is
    exactly `true` (reference pingoo/rules.rs:47 compares against
    `true.into()`); evaluation errors are no-match (pingoo/rules.rs:41-44).
    """
    try:
        result = program.execute(ctx)
    except EvalError:
        return False
    return result is True
