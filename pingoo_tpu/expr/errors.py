"""Errors for the expression language.

Mirrors the reference's error split: compile-time errors surface at config
load (reference: rules/rules.rs:45-53 `compile_expression` returns
`ExpressionIsNotValid`), while runtime evaluation errors make the rule
evaluate to no-match with a warning (reference: pingoo/rules.rs:41-44).
"""


class ExprError(Exception):
    """Base class for all expression-language errors."""


class CompileError(ExprError):
    """Raised while lexing/parsing/type-checking an expression.

    Reference parity: rules/rules.rs:45-53 — any parser failure (including
    panics, which the reference catches with catch_unwind) becomes an
    'Expression is not valid' config error.
    """

    def __init__(self, message: str, pos: int = -1):
        self.pos = pos
        if pos >= 0:
            message = f"{message} (at offset {pos})"
        super().__init__(message)


class EvalError(ExprError):
    """Raised while evaluating an expression against a context.

    Callers that implement rule matching must treat this as no-match
    (fail-open), matching pingoo/rules.rs:41-44.
    """
