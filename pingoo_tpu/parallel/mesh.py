"""Device mesh + sharding specs for the verdict engine.

The scaling model (SURVEY.md §2 "Parallelism strategies"): the natural
mapping of the classic axes onto a WAF verdict engine is

  dp — request-batch sharding (the throughput lever; every batch row is
       independent, so dp scales embarrassingly),
  tp — rule/pattern sharding: pattern tables shard on their pattern axis
       and NFA banks on their word axis (most patterns occupy one uint32
       word, so word sharding is mostly rule sharding; a multi-word span
       straddling a shard boundary keeps its cross-word carry via GSPMD
       halo exchange, compiler/nfa.py pack_span),
  sp — sequence (byte-dimension) sharding for long fields via the ring
       scan in parallel/ring.py.

Everything here uses jax.sharding + GSPMD: we annotate in_shardings on
the jitted verdict and let XLA insert the collectives over ICI, rather
than hand-writing them (scaling-book recipe: pick a mesh, annotate,
let XLA do the rest).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.match_ops import PatternTable
from ..ops.nfa_scan import NfaTables
from ..ops.window_match import WindowTable


def parse_mesh_spec(spec: str) -> tuple[int, int, int]:
    """`"dpxtpxsp"` -> (dp, tp, sp), e.g. "2x2x2" -> (2, 2, 2).

    The serving-path mesh knob (PINGOO_MESH, sched/mesh_exec.py) is
    parsed here next to `make_mesh` so the spec grammar and the mesh
    axis order live in one place. Raises ValueError with the offending
    spec on anything malformed — boot fails fast instead of silently
    serving unsharded."""
    parts = str(spec).strip().lower().split("x")
    if len(parts) != 3:
        raise ValueError(
            f"bad mesh spec {spec!r}: want dpxtpxsp, e.g. 2x2x2")
    try:
        dp, tp, sp = (int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"bad mesh spec {spec!r}: non-integer axis") from None
    if dp < 1 or tp < 1 or sp < 1:
        raise ValueError(f"bad mesh spec {spec!r}: axes must be >= 1")
    return dp, tp, sp


def make_mesh(dp: int = 1, tp: int = 1, sp: int = 1,
              devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = dp * tp * sp
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(dp, tp, sp)
    return Mesh(arr, axis_names=("dp", "tp", "sp"))


def batch_shardings(mesh: Mesh, arrays: Mapping[str, Any]) -> dict:
    """Batch pytree: every array shards its leading (request) axis on dp."""
    out = {}
    for key, arr in arrays.items():
        spec = [None] * np.ndim(arr)
        if np.ndim(arr) >= 1:
            spec[0] = "dp"
        out[key] = NamedSharding(mesh, P(*spec))
    return out


def table_shardings(mesh: Mesh, tables: Mapping[str, Any]) -> dict:
    """Device-table pytree: shard rule-parallel axes on tp, replicate the
    rest. PatternTable shards its pattern axis; NfaTables shards the NFA
    word axis (and the per-pattern slot arrays)."""
    repl = NamedSharding(mesh, P())

    def shard_pattern_table(t: PatternTable) -> PatternTable:
        return PatternTable(
            bytes=NamedSharding(mesh, P("tp", None)),
            lengths=NamedSharding(mesh, P("tp")),
            ci=NamedSharding(mesh, P("tp")),
        )

    def shard_nfa(t: NfaTables) -> NfaTables:
        from dataclasses import replace

        w = NamedSharding(mesh, P("tp"))
        # Word-axis arrays shard on tp (word sharding IS rule sharding);
        # the per-pattern accept/slot arrays are tiny and replicate —
        # extraction is one gather + matmul, not worth a halo. Cross-word
        # carries of multi-word spans that straddle a tp shard boundary
        # become GSPMD halo exchanges (correct, slightly slower).
        return replace(
            t,
            byte_table=NamedSharding(mesh, P(None, "tp")),
            # Class-compression tables: cls_table shares the word axis;
            # cls_map is [256] and cls_u16 interleaves u16 halves along
            # its second axis (lo block then hi block), so a tp split
            # would not align halves to words — replicate it (it is
            # C x 2W u32-equivalent, tiny next to the batch tensors).
            cls_map=repl,
            cls_table=NamedSharding(mesh, P(None, "tp")),
            cls_u16=repl,
            init_anchored=w,
            init_unanchored=w,
            opt=w,
            rep=w,
            carry_mask=w,
            sticky=w,
            accept_word=repl,
            accept_mask=repl,
            accept_member=repl,
            slot_always=repl,
            slot_empty_ok=repl,
        )

    def shard_window_table(t: WindowTable) -> WindowTable:
        # Pattern axis is rule-parallel, like PatternTable; the conv and
        # the per-pattern fit mask are elementwise in P, and the leaf
        # span matmul contracts P (GSPMD inserts the psum).
        return WindowTable(
            kernel=NamedSharding(mesh, P("tp", None, None)),
            const=NamedSharding(mesh, P("tp")),
            min_len=NamedSharding(mesh, P("tp")),
        )

    out: dict = {}
    for key, val in tables.items():
        if isinstance(val, PatternTable) and _divisible(val.bytes.shape[0], mesh, "tp"):
            out[key] = shard_pattern_table(val)
        elif isinstance(val, WindowTable) and _divisible(
                val.kernel.shape[0], mesh, "tp"):
            out[key] = shard_window_table(val)
        elif isinstance(val, NfaTables) and _divisible(
                val.opt.shape[0], mesh, "tp"):
            out[key] = shard_nfa(val)
        else:
            out[key] = jax.tree_util.tree_map(lambda _: repl, val)
    return out


def _divisible(dim: int, mesh: Mesh, axis: str) -> bool:
    size = mesh.shape[axis]
    return size > 1 and dim % size == 0 or size == 1


def pad_tables_for_tp(np_tables: dict, tp: int) -> dict:
    """Pad pattern/word axes to multiples of tp so they shard evenly.

    Padding rows are inert: zero-length patterns in a PatternTable can
    only produce spurious columns that no leaf binding reads; NFA padding
    words carry no init bits and no carry flag so their lanes stay dead
    (accept/slot arrays index words by value and are replicated, so they
    need no padding).
    """
    import numpy as np  # local: keep module import-light

    if tp <= 1:
        return np_tables

    def pad_axis(arr, axis, mult, fill=0):
        size = arr.shape[axis]
        target = -(-size // mult) * mult
        if target == size:
            return arr
        pad = [(0, 0)] * arr.ndim
        pad[axis] = (0, target - size)
        return np.pad(arr, pad, constant_values=fill)

    out = {}
    for key, val in np_tables.items():
        if isinstance(val, PatternTable):
            b = np.asarray(val.bytes)
            out[key] = PatternTable(
                bytes=pad_axis(b, 0, tp),
                # Padded patterns get length > field capacity so
                # prefix/eq on them can never match (lengths check).
                lengths=pad_axis(np.asarray(val.lengths), 0, tp,
                                 fill=np.int32(2**30)),
                ci=pad_axis(np.asarray(val.ci), 0, tp),
            )
        elif isinstance(val, WindowTable):
            # Padded patterns have zero weights (ssd identically 0) but
            # an impossible min_len, so the fit gate kills them.
            out[key] = WindowTable(
                kernel=pad_axis(np.asarray(val.kernel), 0, tp),
                const=pad_axis(np.asarray(val.const), 0, tp),
                min_len=pad_axis(np.asarray(val.min_len), 0, tp,
                                 fill=np.int32(1 << 20)),
            )
        elif isinstance(val, NfaTables):
            from dataclasses import replace

            # Pad only the word axis; padded words carry no init bits and
            # no carry flag, so their lanes stay dead. Accept/slot arrays
            # index words by value and are replicated, so they need no pad.
            # The class-compression tables are rebuilt from the padded
            # byte table (zero padding columns preserve row equality
            # classes, so the class count is unchanged).
            from ..ops.nfa_scan import class_compress

            bt = pad_axis(np.asarray(val.byte_table), 1, tp)
            cls_map, cls_table, cls_u16 = class_compress(bt)
            out[key] = replace(
                val,
                byte_table=bt,
                cls_map=cls_map,
                cls_table=cls_table,
                cls_u16=cls_u16,
                init_anchored=pad_axis(np.asarray(val.init_anchored), 0, tp),
                init_unanchored=pad_axis(np.asarray(val.init_unanchored), 0, tp),
                opt=pad_axis(np.asarray(val.opt), 0, tp),
                rep=pad_axis(np.asarray(val.rep), 0, tp),
                carry_mask=pad_axis(np.asarray(val.carry_mask), 0, tp),
                sticky=pad_axis(np.asarray(val.sticky), 0, tp),
            )
        else:
            out[key] = val
    return out
