"""Sequence-parallel NFA scan: byte-dimension sharding with a state ring.

Long-field handling (SURVEY.md §5 "Long-context / sequence parallelism"):
the byte dimension of a field is split into contiguous chunks across the
`sp` mesh axis; each device scans only its chunk and the carried NFA
state travels around the ring via `ppermute` — the ring-attention-style
accumulation of scan state across chunk boundaries, applied to the
bit-parallel NFA instead of attention blocks.

Stage s: the device holding chunk s advances the state it just received
over its local bytes; every device then rotates its state register one
step around the ring, delivering the true state to the device holding
chunk s+1. Float accepts accumulate on whichever device finds them and
are OR-combined at the end (psum over the one-hot contributions);
$-anchored accepts are evaluated by the device that ran the final stage.

This distributes both the byte tensors and the NFA state over sp, so a
field's device footprint shrinks 1/sp while verdict semantics stay
bit-identical to ops/nfa_scan.nfa_scan (differentially tested on the
8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa_scan import NfaTables, extract_slots, scan_chunk


def ring_nfa_scan(
    mesh: Mesh,
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """nfa_scan with the byte axis sharded over mesh axis 'sp' (and the
    batch axis over 'dp'). data: [B, L] with L % sp == 0."""
    sp = mesh.shape["sp"]
    B, L = data.shape
    assert L % sp == 0, "byte axis must divide evenly over sp"
    Lc = L // sp

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp", "sp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def kernel(tables_local: NfaTables, chunk: jax.Array, lengths_local: jax.Array):
        sp_idx = jax.lax.axis_index("sp")
        Bl = chunk.shape[0]
        W = tables_local.opt.shape[0]
        state = jnp.zeros((Bl, W), dtype=jnp.uint32)
        float_acc = jnp.zeros_like(state)
        end_acc = jnp.zeros_like(state)

        # Trailing-newline flag needs the *global* last byte; each device
        # checks whether it owns position len-1 and the flag is OR-shared.
        lengths_i = lengths_local.astype(jnp.int32)
        local_pos = jnp.clip(lengths_i - 1 - sp_idx * Lc, 0, Lc - 1)
        owns_last = (lengths_i > 0) & (
            (lengths_i - 1) // Lc == sp_idx)
        my_last = chunk[jnp.arange(Bl), local_pos]
        nl_local = owns_last & (my_last == 0x0A)
        ends_nl = jax.lax.psum(nl_local.astype(jnp.int32), "sp") > 0

        perm = [(i, (i + 1) % sp) for i in range(sp)]
        final_end_bits = jnp.zeros_like(state)
        for stage in range(sp):
            my_turn = sp_idx == stage
            s2, f2, e2 = scan_chunk(
                tables_local, chunk, lengths_local, state, float_acc,
                end_acc, ends_nl, stage * Lc)
            # Only the stage owner's results are real this round. Note
            # the owner of stage `stage` is the device whose chunk is at
            # byte offset stage*Lc — device index == stage.
            take = my_turn
            state = jnp.where(take, s2, state)
            float_acc = jnp.where(take, f2, float_acc)
            end_acc = jnp.where(take, e2, end_acc)
            if stage == sp - 1:
                final_end_bits = jnp.where(
                    take, state & tables_local.last_end, final_end_bits)
            # Rotate the state register one step; accs stay local.
            state = jax.lax.ppermute(state, "sp", perm)

        end_acc = end_acc | final_end_bits
        hits = extract_slots(
            tables_local, float_acc, end_acc, lengths_local, ends_nl)
        # OR the per-device partial verdicts (disjoint discovery times,
        # possibly overlapping patterns).
        return jax.lax.psum(hits.astype(jnp.int32), "sp") > 0

    return kernel(tables, data, lengths)


def shard_batch_for_ring(mesh: Mesh, data, lengths):
    """Place [B, L] bytes with B over dp and L over sp; lengths over dp."""
    data_s = jax.device_put(data, NamedSharding(mesh, P("dp", "sp")))
    lens_s = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    return data_s, lens_s
