"""Sequence-parallel NFA scan: byte-dimension sharding over the sp axis.

Long-field handling (SURVEY.md §5 "Long-context / sequence parallelism"):
the byte dimension of a field is split into contiguous chunks across the
`sp` mesh axis. Two strategies:

`halo_nfa_scan` — TRUE sequence parallelism: every device scans its own
chunk CONCURRENTLY, prefixed by a fixed halo of the previous chunk's
trailing bytes (one ppermute before any scanning). Correct whenever the
automaton has bounded memory — every self-loop is a sticky ACCEPT
accumulator (compiler/nfa.py tracks this as `halo_ok`), so the
non-accept state at byte t depends only on the last `max_footprint`
bytes, and a zero-state warm-up over the halo reconstructs it. Sticky
(floating) accepts OR across devices via psum; positional accepts
(`$`-anchored) are taken only from the device whose CHUNK (not halo)
owns each request's final byte, where the warm-up is complete. Wall
clock: L/sp + H per device instead of L.

`ring_nfa_scan` — the sequential-state fallback for banks with real
self-loops (x+ / x*), whose state memory is unbounded: the carried
state travels the ring via ppermute, one stage at a time (distributes
memory 1/sp, but stages serialize).

`sp_nfa_scan` picks per bank. Both are bit-identical to
ops/nfa_scan.nfa_scan (differentially tested on the 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa_scan import NfaTables, extract_slots, init_scan_state, scan_chunk


def _shard_map(f=None, **kwargs):
    """Version-portable shard_map: `jax.shard_map` with `check_vma`
    (new API) when present, else `jax.experimental.shard_map.shard_map`
    with the old `check_rep` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs) if f else jax.shard_map(**kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _sm(f, **kwargs) if f else (lambda fn: _sm(fn, **kwargs))


def ring_nfa_scan(
    mesh: Mesh,
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """nfa_scan with the byte axis sharded over mesh axis 'sp' (and the
    batch axis over 'dp'). data: [B, L] with L % sp == 0."""
    sp = mesh.shape["sp"]
    B, L = data.shape
    assert L % sp == 0, "byte axis must divide evenly over sp"
    Lc = L // sp

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp", "sp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def kernel(tables_local: NfaTables, chunk: jax.Array, lengths_local: jax.Array):
        sp_idx = jax.lax.axis_index("sp")
        Bl = chunk.shape[0]
        W = tables_local.opt.shape[0]
        state = init_scan_state(Bl, W)

        perm = [(i, (i + 1) % sp) for i in range(sp)]
        hits = jnp.zeros(
            (Bl, tables_local.slot_always.shape[0]), dtype=jnp.int32)
        for stage in range(sp):
            my_turn = sp_idx == stage
            s2 = scan_chunk(tables_local, chunk, lengths_local, state,
                            stage * Lc)
            # Only the stage owner's result is real this round (the owner
            # of stage s is the device holding byte offset s*Lc).
            state = jnp.where(my_turn, s2, state)
            if stage == sp - 1:
                final_hits = extract_slots(
                    tables_local, state, lengths_local)
                hits = jnp.where(my_turn, final_hits.astype(jnp.int32), hits)
            else:
                state = jax.lax.ppermute(state, "sp", perm)

        # Broadcast the final-stage device's verdicts to the ring.
        return jax.lax.psum(hits, "sp") > 0

    return kernel(tables, data, lengths)


def halo_nfa_scan(
    mesh: Mesh,
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """Concurrent sequence-parallel scan (see module docstring).

    data: [B, L] with L % sp == 0; requires tables.halo_ok.
    """
    assert tables.halo_ok, "bank has unbounded self-loops; use ring_nfa_scan"
    sp = mesh.shape["sp"]
    B, L = data.shape
    assert L % sp == 0, "byte axis must divide evenly over sp"
    Lc = L // sp
    # Halo = the largest pattern footprint (>= its byte memory). It must
    # fit inside one chunk — the exchange is a single hop from the
    # immediate predecessor. Longer patterns than a chunk need the
    # sequential ring (sp_nfa_scan dispatches accordingly).
    H = int(tables.max_footprint)
    assert H <= Lc, f"halo {H} exceeds chunk {Lc}; use ring_nfa_scan"

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp", "sp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def kernel(tables_local: NfaTables, chunk: jax.Array,
               lengths_local: jax.Array):
        sp_idx = jax.lax.axis_index("sp")
        Bl = chunk.shape[0]
        W = tables_local.opt.shape[0]
        lengths32 = lengths_local.astype(jnp.int32)

        if H > 0:
            # ONE exchange up front: my chunk's trailing H bytes feed my
            # successor's warm-up prefix; then every stage scans
            # concurrently (vs. the ring's serialized stages).
            tail = chunk[:, Lc - H:]
            halo = jax.lax.ppermute(
                tail, "sp", [(i, (i + 1) % sp) for i in range(sp)])
            ext = jnp.concatenate([halo, chunk], axis=1)  # [B, H + Lc]
        else:
            ext = chunk
        # Global position of ext[:, 0]; negative on device 0, where the
        # wrapped-around halo bytes are gated off by the t >= 0 check in
        # scan_chunk (so its warm-up is a no-op and t == 0 injection
        # happens exactly once).
        t0 = sp_idx * Lc - H
        state = scan_chunk(tables_local, ext, lengths32,
                           init_scan_state(Bl, W), t0)

        # Accept split: sticky accumulator bits OR across devices (a
        # floating match is detected by whichever device scanned its
        # final byte with enough context — at least its chunk owner);
        # positional accepts ($-anchored) are valid only on the device
        # whose CHUNK owns the request's last byte, where warm-up is
        # complete by construction. The pair->slot reduction itself is
        # extract_slots', so both paths stay bit-identical.
        lanes = jnp.take(state, tables_local.accept_word, axis=1)  # [B, J]
        masks = tables_local.accept_mask[None, :]
        sticky_j = jnp.take(tables_local.sticky,
                            tables_local.accept_word)[None, :]
        sticky_hit = (lanes & masks & sticky_j) != 0
        owner = jnp.clip((lengths32 - 1) // Lc, 0, sp - 1)  # [B]
        is_owner = (owner == sp_idx)[:, None]
        end_hit = ((lanes & masks & ~sticky_j) != 0) & is_owner
        hits = extract_slots(tables_local, state, lengths32,
                             pair_hit=sticky_hit | end_hit)
        return jax.lax.psum(hits.astype(jnp.int32), "sp") > 0

    return kernel(tables, data, lengths)


def sp_scan_mode(tables: NfaTables, L: int, sp: int) -> str:
    """'halo' when the bank's memory is bounded AND the largest pattern
    fits inside one chunk, else 'ring' — the single source of truth for
    the sp dispatch (also used for diagnostics)."""
    if tables.halo_ok and int(tables.max_footprint) <= L // sp:
        return "halo"
    return "ring"


def sp_nfa_scan(mesh: Mesh, tables: NfaTables, data: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """Sequence-parallel scan: concurrent halo strategy when eligible
    (sp_scan_mode), sequential state ring otherwise."""
    if sp_scan_mode(tables, data.shape[1], mesh.shape["sp"]) == "halo":
        return halo_nfa_scan(mesh, tables, data, lengths)
    return ring_nfa_scan(mesh, tables, data, lengths)


def shard_batch_for_ring(mesh: Mesh, data, lengths):
    """Place [B, L] bytes with B over dp and L over sp; lengths over dp."""
    data_s = jax.device_put(data, NamedSharding(mesh, P("dp", "sp")))
    lens_s = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    return data_s, lens_s
