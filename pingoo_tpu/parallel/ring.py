"""Sequence-parallel NFA scan: byte-dimension sharding with a state ring.

Long-field handling (SURVEY.md §5 "Long-context / sequence parallelism"):
the byte dimension of a field is split into contiguous chunks across the
`sp` mesh axis; each device scans only its chunk and the carried NFA
state travels around the ring via `ppermute` — the ring-attention-style
accumulation of scan state across chunk boundaries, applied to the
bit-parallel NFA instead of attention blocks.

With sticky-accept compilation (compiler/nfa.py) the carried state IS
the accept state, so the ring rotates exactly one [B, W] uint32 tensor;
extraction happens once, on the device that ran the final stage, and the
verdict broadcast rides a psum.

This distributes the byte tensors and NFA state 1/sp per device while
verdict semantics stay bit-identical to ops/nfa_scan.nfa_scan
(differentially tested on the 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.nfa_scan import NfaTables, extract_slots, init_scan_state, scan_chunk


def ring_nfa_scan(
    mesh: Mesh,
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
) -> jax.Array:
    """nfa_scan with the byte axis sharded over mesh axis 'sp' (and the
    batch axis over 'dp'). data: [B, L] with L % sp == 0."""
    sp = mesh.shape["sp"]
    B, L = data.shape
    assert L % sp == 0, "byte axis must divide evenly over sp"
    Lc = L // sp

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("dp", "sp"), P("dp")),
        out_specs=P("dp", None),
        check_vma=False,
    )
    def kernel(tables_local: NfaTables, chunk: jax.Array, lengths_local: jax.Array):
        sp_idx = jax.lax.axis_index("sp")
        Bl = chunk.shape[0]
        W = tables_local.opt.shape[0]
        state = init_scan_state(Bl, W)

        perm = [(i, (i + 1) % sp) for i in range(sp)]
        hits = jnp.zeros(
            (Bl, tables_local.slot_always.shape[0]), dtype=jnp.int32)
        for stage in range(sp):
            my_turn = sp_idx == stage
            s2 = scan_chunk(tables_local, chunk, lengths_local, state,
                            stage * Lc)
            # Only the stage owner's result is real this round (the owner
            # of stage s is the device holding byte offset s*Lc).
            state = jnp.where(my_turn, s2, state)
            if stage == sp - 1:
                final_hits = extract_slots(
                    tables_local, state, lengths_local)
                hits = jnp.where(my_turn, final_hits.astype(jnp.int32), hits)
            else:
                state = jax.lax.ppermute(state, "sp", perm)

        # Broadcast the final-stage device's verdicts to the ring.
        return jax.lax.psum(hits, "sp") > 0

    return kernel(tables, data, lengths)


def shard_batch_for_ring(mesh: Mesh, data, lengths):
    """Place [B, L] bytes with B over dp and L over sp; lengths over dp."""
    data_s = jax.device_put(data, NamedSharding(mesh, P("dp", "sp")))
    lens_s = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
    return data_s, lens_s
