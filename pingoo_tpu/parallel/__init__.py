"""Mesh construction, dp/tp/sp shardings, and the sp NFA scans
(concurrent halo scan + sequential ring fallback)."""

from .. import ops as _ops  # noqa: F401  (x64 before tracing)
from .mesh import (batch_shardings, make_mesh, pad_tables_for_tp,
                   parse_mesh_spec, table_shardings)
from .ring import halo_nfa_scan, ring_nfa_scan, shard_batch_for_ring, sp_nfa_scan

__all__ = [
    "batch_shardings",
    "halo_nfa_scan",
    "make_mesh",
    "pad_tables_for_tp",
    "parse_mesh_spec",
    "ring_nfa_scan",
    "shard_batch_for_ring",
    "sp_nfa_scan",
    "table_shardings",
]
