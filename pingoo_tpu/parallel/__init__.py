"""Mesh construction, dp/tp/sp shardings, and the sp ring NFA scan."""

from .. import ops as _ops  # noqa: F401  (x64 before tracing)
from .mesh import batch_shardings, make_mesh, pad_tables_for_tp, table_shardings
from .ring import ring_nfa_scan, shard_batch_for_ring

__all__ = [
    "batch_shardings",
    "make_mesh",
    "pad_tables_for_tp",
    "ring_nfa_scan",
    "shard_batch_for_ring",
    "table_shardings",
]
