"""List loading: CSV files of String/Int/Ip items for rule expressions.

Reference parity (pingoo/lists.rs:48-125): lists are CSV with 1 value
column and an optional description column; values are trimmed; Int parses
as i64; Ip parses as an address or CIDR network (IpNetwork); all lists are
exposed to expressions as one `lists` map variable whose values are typed
arrays (lists.rs:115-125, used as `lists["blocked_ips"].contains(client.ip)`
per docs/rules.md:110).

The loaded representation is the interpreter's value model; the TPU
compiler separately lowers these into device tables (bitsets / sorted
hash tables) via compiler/lists_lowering.
"""

from __future__ import annotations

import csv
import io
from typing import Iterable

from .config.schema import ConfigError, ListConfig, ListType
from .expr import Ip
from .expr.values import I64_MAX, I64_MIN


def load_lists(lists_config: Iterable[ListConfig]) -> dict[str, list]:
    """Load every configured list into the `lists` expression variable."""
    lists: dict[str, list] = {}
    for cfg in lists_config:
        try:
            with open(cfg.file, "r", encoding="utf-8") as f:
                content = f.read()
        except OSError as exc:
            raise ConfigError(f"error reading list {cfg.file}: {exc}")
        lists[cfg.name] = parse_list(content, cfg.type, path=cfg.file)
    return lists


def parse_list(content: str, list_type: ListType, path: str = "<memory>") -> list:
    """Parse CSV content into a typed item list (reference lists.rs:62-113)."""
    items: list = []
    reader = csv.reader(io.StringIO(content))
    for line_number, record in enumerate(reader, start=1):
        if not record:
            continue
        if len(record) > 2:
            raise ConfigError(
                f"error parsing list {path} at line {line_number}: invalid "
                "number of columns. Min: 1, Max: 2"
            )
        value = record[0].strip()
        if list_type == ListType.STRING:
            items.append(value)
        elif list_type == ListType.INT:
            try:
                parsed = int(value, 10)
            except ValueError:
                raise ConfigError(
                    f"error parsing list {path} at line {line_number}: error parsing int"
                )
            if not (I64_MIN <= parsed <= I64_MAX):
                raise ConfigError(
                    f"error parsing list {path} at line {line_number}: int out of range"
                )
            items.append(parsed)
        else:
            try:
                items.append(Ip(value))
            except Exception:
                raise ConfigError(
                    f"error parsing list {path} at line {line_number}: error "
                    "parsing IP network"
                )
    return items
