"""Bot-score head: vectorized feature extraction + logistic MLP.

The reference's bot protection is a proof-of-work captcha gated per
request by cookie checks (pingoo/captcha.rs; gate wiring at
http_listener.rs:200-236). The TPU-native upgrade from BASELINE.json
config 5: extract cheap request features on device from the already-
encoded verdict batch and score them with a small learned head, so the
captcha gate can be risk-based instead of rule-only. The head's score
rides back with the verdict bitmap; the host decides the gate.

Features (all computed from the RequestBatch tensors, no extra host
work): field lengths, UA byte-class composition (the "UA entropy" proxy),
path shape, method/country/ASN/port hash buckets. The model is a 2-layer
MLP trained with BCE; `train_step` is a pure jittable function suitable
for dp-sharded data-parallel training (GSPMD averages the gradients).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

NUM_FEATURES = 46
HIDDEN = 64


def extract_features(arrays: dict) -> jax.Array:
    """RequestBatch arrays -> [B, NUM_FEATURES] float32 (device-side)."""
    f32 = jnp.float32

    def norm_len(key, cap):
        return (arrays[f"{key}_len"].astype(f32) / cap)[:, None]

    ua = arrays["user_agent_bytes"]
    ua_len = jnp.maximum(arrays["user_agent_len"].astype(f32), 1.0)
    pos_ok = (
        jnp.arange(ua.shape[1], dtype=jnp.int32)[None, :]
        < arrays["user_agent_len"][:, None]
    )

    def frac(lo, hi):
        inside = (ua >= lo) & (ua <= hi) & pos_ok
        return (inside.sum(axis=1).astype(f32) / ua_len)[:, None]

    path = arrays["path_bytes"]
    path_pos = (
        jnp.arange(path.shape[1], dtype=jnp.int32)[None, :]
        < arrays["path_len"][:, None]
    )
    slashes = ((path == 0x2F) & path_pos).sum(axis=1).astype(f32)[:, None]
    dots = ((path == 0x2E) & path_pos).sum(axis=1).astype(f32)[:, None]
    pcts = ((path == 0x25) & path_pos).sum(axis=1).astype(f32)[:, None]

    method = arrays["method_bytes"]
    method_hash = (
        method[:, 0].astype(jnp.int32) * 7 + arrays["method_len"].astype(jnp.int32)
    ) % 8
    country = arrays["country_bytes"]
    country_hash = (
        country[:, 0].astype(jnp.int32) * 31 + country[:, 1].astype(jnp.int32)
    ) % 16
    asn_hash = (
        (arrays["asn"].astype(jnp.uint32) * jnp.uint32(2654435761)) >> 24
    ).astype(jnp.int32) % 8
    port = arrays["remote_port"].astype(f32) / 65535.0

    feats = jnp.concatenate(
        [
            norm_len("user_agent", 256.0),
            norm_len("path", 256.0),
            norm_len("url", 512.0),
            norm_len("host", 128.0),
            (arrays["user_agent_len"] == 0).astype(f32)[:, None],
            frac(0x30, 0x39),  # digits
            frac(0x41, 0x5A),  # uppercase
            frac(0x61, 0x7A),  # lowercase
            frac(0x20, 0x2F),  # punctuation-ish
            slashes / 32.0,
            dots / 16.0,
            pcts / 16.0,
            port[:, None],
            jax.nn.one_hot(method_hash, 8, dtype=f32),
            jax.nn.one_hot(country_hash, 16, dtype=f32),
            jax.nn.one_hot(asn_hash, 8, dtype=f32),
            jnp.ones((ua.shape[0], 1), dtype=f32),  # bias channel
        ],
        axis=1,
    )
    assert feats.shape[1] == NUM_FEATURES, feats.shape
    return feats


class Params(NamedTuple):
    w1: jax.Array  # [F, H]
    b1: jax.Array  # [H]
    w2: jax.Array  # [H, 1]
    b2: jax.Array  # [1]


def init_params(rng: jax.Array, hidden: int = HIDDEN) -> Params:
    k1, k2 = jax.random.split(rng)
    scale1 = 1.0 / np.sqrt(NUM_FEATURES)
    scale2 = 1.0 / np.sqrt(hidden)
    return Params(
        w1=jax.random.normal(k1, (NUM_FEATURES, hidden), jnp.float32) * scale1,
        b1=jnp.zeros((hidden,), jnp.float32),
        w2=jax.random.normal(k2, (hidden, 1), jnp.float32) * scale2,
        b2=jnp.zeros((1,), jnp.float32),
    )


def logits(params: Params, feats: jax.Array) -> jax.Array:
    h = jax.nn.relu(feats @ params.w1 + params.b1)
    return (h @ params.w2 + params.b2)[:, 0]


def score(params: Params, arrays: dict) -> jax.Array:
    """[B] bot probability in [0, 1] — runs inside the verdict step."""
    return jax.nn.sigmoid(logits(params, extract_features(arrays)))


def bce_loss(params: Params, feats: jax.Array, labels: jax.Array) -> jax.Array:
    lg = logits(params, feats)
    return jnp.mean(
        jnp.maximum(lg, 0) - lg * labels + jnp.log1p(jnp.exp(-jnp.abs(lg)))
    )


def save_params(params: Params, path: str) -> None:
    """Persist trained weights (npz) for the server's --bot-score-params."""
    np.savez(path, w1=np.asarray(params.w1), b1=np.asarray(params.b1),
             w2=np.asarray(params.w2), b2=np.asarray(params.b2))


def load_params(path: str) -> Params:
    with np.load(path) as data:
        return Params(w1=jnp.asarray(data["w1"]), b1=jnp.asarray(data["b1"]),
                      w2=jnp.asarray(data["w2"]), b2=jnp.asarray(data["b2"]))


def make_train_step(learning_rate: float = 1e-3):
    """Returns a jittable (params, opt_state, feats, labels) -> updated
    (params, opt_state, loss). dp sharding of feats/labels gives
    data-parallel training; GSPMD inserts the gradient reductions."""
    import optax

    tx = optax.adamw(learning_rate)

    def train_step(params: Params, opt_state, feats, labels):
        loss, grads = jax.value_and_grad(bce_loss)(params, feats, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return tx, train_step
