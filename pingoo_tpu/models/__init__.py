"""Learned components (bot-score head)."""

from . import botscore

__all__ = ["botscore"]
