"""Vectorized byte-tensor string predicates: eq / prefix / suffix.

These lower the bel functions `starts_with` / `ends_with` / `==` over
request string fields (reference docs/rules.md:71-76; hot use:
assets/pingoo.yml `http_request.path.starts_with("/.env")`). `contains`
and `matches` go through the NFA scan instead (ops/nfa_scan.py).

All patterns for one field live in one padded table so a single broadcast
compare scores every (request, pattern) pair: [B, L] x [P, Lp] -> [B, P].
Comparisons are masked past each pattern's length, so the op is exact for
zero-padded fields.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PatternTable(NamedTuple):
    """Padded pattern bytes for one (field, kind) group."""

    bytes: jax.Array  # [P, Lp] uint8
    lengths: jax.Array  # [P] int32
    ci: jax.Array  # [P] bool — case-insensitive compare


def build_pattern_table(patterns: list[tuple[bytes, bool]]) -> PatternTable:
    """patterns: list of (bytes, case_insensitive)."""
    P = len(patterns)
    Lp = max((len(p) for p, _ in patterns), default=1)
    Lp = max(Lp, 1)
    arr = np.zeros((P, Lp), dtype=np.uint8)
    lens = np.zeros(P, dtype=np.int32)
    ci = np.zeros(P, dtype=bool)
    for i, (p, fold) in enumerate(patterns):
        arr[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lens[i] = len(p)
        ci[i] = fold
    return PatternTable(jnp.asarray(arr), jnp.asarray(lens), jnp.asarray(ci))


def _fold_lower(x: jax.Array) -> jax.Array:
    """ASCII-lowercase a uint8 tensor."""
    is_upper = (x >= 0x41) & (x <= 0x5A)
    return jnp.where(is_upper, x + 0x20, x)


def _masked_eq(data: jax.Array, table: PatternTable) -> jax.Array:
    """[B, L], table [P, Lp] -> all-positions-equal [B, P] (masked past
    pattern length). Positions beyond L are handled by the caller via
    length checks (a pattern longer than L can never match)."""
    B, L = data.shape
    P, Lp = table.bytes.shape
    take = min(L, Lp)
    d = data[:, None, :take]  # [B, 1, take]
    p = table.bytes[None, :, :take]  # [1, P, take]
    folded = _fold_lower(d) == _fold_lower(p)
    exact = d == p
    cmp = jnp.where(table.ci[None, :, None], folded, exact)
    pos_ok = jnp.arange(take, dtype=jnp.int32)[None, None, :] >= (
        table.lengths[None, :, None]
    )
    return jnp.all(cmp | pos_ok, axis=2)  # [B, P]


def prefix_match(
    data: jax.Array, lengths: jax.Array, table: PatternTable
) -> jax.Array:
    """starts_with: [B, P] bool."""
    ok = _masked_eq(data, table)
    fits = lengths[:, None] >= table.lengths[None, :]
    return ok & fits


def eq_match(data: jax.Array, lengths: jax.Array, table: PatternTable) -> jax.Array:
    """string equality: [B, P] bool."""
    ok = _masked_eq(data, table)
    same_len = lengths[:, None] == table.lengths[None, :]
    return ok & same_len


def row_tails(data: jax.Array, lengths: jax.Array, M: int) -> jax.Array:
    """Last M bytes of each row, right-aligned: tail[b, M-1] = the byte at
    lengths[b]-1, zero-filled left of short rows. GATHER-FREE: a per-row
    `take_along_axis` costs ~0.7 ms at [2048, 32] on the v5e (per-row
    dynamic addressing defeats the vector units), while this one-hot
    multiply-reduce over static shifts of the padded row is pure
    broadcast + reduction (~free at these shapes, exact in f32 since
    bytes < 2^8)."""
    B, L = data.shape
    padded = jnp.pad(data, ((0, 0), (M, 0)))  # window o ends at byte o
    O = L + 1
    oh = (jnp.arange(O, dtype=jnp.int32)[None, :]
          == lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    cols = []
    for j in range(M):
        # tail[:, j] = padded[b, lengths[b] + j] (window-relative byte j)
        sl = jax.lax.slice_in_dim(padded, j, j + O, axis=1).astype(jnp.float32)
        cols.append((oh * sl).sum(axis=1))
    return jnp.stack(cols, axis=1).astype(jnp.uint8)  # [B, M]


def suffix_match(
    data: jax.Array, lengths: jax.Array, table: PatternTable
) -> jax.Array:
    """ends_with: [B, P] bool. `table` holds RIGHT-aligned patterns
    (build_suffix_table); compare the right-aligned row tail against
    them, masking positions left of each pattern."""
    P, M = table.bytes.shape
    tail = row_tails(data, lengths, M)  # [B, M]
    d = tail[:, None, :]
    p = table.bytes[None, :, :]
    folded = _fold_lower(d) == _fold_lower(p)
    exact = d == p
    cmp = jnp.where(table.ci[None, :, None], folded, exact)
    # Position j belongs to pattern p iff j >= M - len(p); shorter rows
    # zero-fill from the left, so a row shorter than the pattern is
    # rejected by the explicit fits check, not the compare.
    pos_pad = jnp.arange(M, dtype=jnp.int32)[None, None, :] < (
        M - table.lengths[None, :, None]
    )
    ok = jnp.all(cmp | pos_pad, axis=2)  # [B, P]
    fits = lengths[:, None] >= table.lengths[None, :]
    return ok & fits


def build_suffix_table(patterns: list[tuple[bytes, bool]]) -> PatternTable:
    """Right-aligned pattern table for suffix_match."""
    P = len(patterns)
    M = max((len(p) for p, _ in patterns), default=1)
    M = max(M, 1)
    arr = np.zeros((P, M), dtype=np.uint8)
    lens = np.zeros(P, dtype=np.int32)
    ci = np.zeros(P, dtype=bool)
    for i, (p, fold) in enumerate(patterns):
        if p:
            arr[i, M - len(p):] = np.frombuffer(p, dtype=np.uint8)
        lens[i] = len(p)
        ci[i] = fold
    return PatternTable(jnp.asarray(arr), jnp.asarray(lens), jnp.asarray(ci))
