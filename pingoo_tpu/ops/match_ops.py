"""Vectorized byte-tensor string predicates: eq / prefix / suffix.

These lower the bel functions `starts_with` / `ends_with` / `==` over
request string fields (reference docs/rules.md:71-76; hot use:
assets/pingoo.yml `http_request.path.starts_with("/.env")`). `contains`
and `matches` go through the NFA scan instead (ops/nfa_scan.py).

All patterns for one field live in one padded table so a single broadcast
compare scores every (request, pattern) pair: [B, L] x [P, Lp] -> [B, P].
Comparisons are masked past each pattern's length, so the op is exact for
zero-padded fields.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PatternTable(NamedTuple):
    """Padded pattern bytes for one (field, kind) group."""

    bytes: jax.Array  # [P, Lp] uint8
    lengths: jax.Array  # [P] int32
    ci: jax.Array  # [P] bool — case-insensitive compare


def build_pattern_table(patterns: list[tuple[bytes, bool]]) -> PatternTable:
    """patterns: list of (bytes, case_insensitive)."""
    P = len(patterns)
    Lp = max((len(p) for p, _ in patterns), default=1)
    Lp = max(Lp, 1)
    arr = np.zeros((P, Lp), dtype=np.uint8)
    lens = np.zeros(P, dtype=np.int32)
    ci = np.zeros(P, dtype=bool)
    for i, (p, fold) in enumerate(patterns):
        arr[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lens[i] = len(p)
        ci[i] = fold
    return PatternTable(jnp.asarray(arr), jnp.asarray(lens), jnp.asarray(ci))


def _fold_lower(x: jax.Array) -> jax.Array:
    """ASCII-lowercase a uint8 tensor."""
    is_upper = (x >= 0x41) & (x <= 0x5A)
    return jnp.where(is_upper, x + 0x20, x)


def _masked_eq(data: jax.Array, table: PatternTable) -> jax.Array:
    """[B, L], table [P, Lp] -> all-positions-equal [B, P] (masked past
    pattern length). Positions beyond L are handled by the caller via
    length checks (a pattern longer than L can never match)."""
    B, L = data.shape
    P, Lp = table.bytes.shape
    take = min(L, Lp)
    d = data[:, None, :take]  # [B, 1, take]
    p = table.bytes[None, :, :take]  # [1, P, take]
    folded = _fold_lower(d) == _fold_lower(p)
    exact = d == p
    cmp = jnp.where(table.ci[None, :, None], folded, exact)
    pos_ok = jnp.arange(take, dtype=jnp.int32)[None, None, :] >= (
        table.lengths[None, :, None]
    )
    return jnp.all(cmp | pos_ok, axis=2)  # [B, P]


def prefix_match(
    data: jax.Array, lengths: jax.Array, table: PatternTable
) -> jax.Array:
    """starts_with: [B, P] bool."""
    ok = _masked_eq(data, table)
    fits = lengths[:, None] >= table.lengths[None, :]
    return ok & fits


def eq_match(data: jax.Array, lengths: jax.Array, table: PatternTable) -> jax.Array:
    """string equality: [B, P] bool."""
    ok = _masked_eq(data, table)
    same_len = lengths[:, None] == table.lengths[None, :]
    return ok & same_len


def reverse_bytes(data: jax.Array, lengths: jax.Array) -> jax.Array:
    """Reverse each row's first `length` bytes: rev[b, j] = data[b, len-1-j].

    Computed once per field so every suffix predicate becomes a prefix
    predicate on the reversed view.
    """
    B, L = data.shape
    idx = lengths[:, None] - 1 - jnp.arange(L, dtype=jnp.int32)[None, :]
    idx_clipped = jnp.clip(idx, 0, L - 1)
    rev = jnp.take_along_axis(data, idx_clipped, axis=1)
    return jnp.where(idx >= 0, rev, 0)


def suffix_match(
    rev_data: jax.Array, lengths: jax.Array, rev_table: PatternTable
) -> jax.Array:
    """ends_with: prefix match of reversed pattern on reversed data."""
    return prefix_match(rev_data, lengths, rev_table)


def build_suffix_table(patterns: list[tuple[bytes, bool]]) -> PatternTable:
    return build_pattern_table([(p[::-1], ci) for p, ci in patterns])
