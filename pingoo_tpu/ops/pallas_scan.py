"""Fused Pallas NFA scan kernel — the whole byte loop in ONE kernel.

The roofline (docs/ROOFLINE.md) shows the lax.scan verdict kernel
serial-latency-bound: ~7.3 us per dependent scan step against a ~0.5 us
execution floor, because each loop iteration's gather/advance round-trips
through XLA's while-loop machinery (and, on a tunnel-attached chip,
cannot be dispatch-pipelined). This kernel executes an entire field's
byte loop inside one `pl.pallas_call`:

  * the [B_tile, W] state vector stays in VMEM (a fori_loop carry) for
    the whole chunk — nothing round-trips HBM between bytes;
  * the byte-class lookup is fused with the ~7-op advance per byte: a
    one-hot [B_tile, C] x [C, 2W] f32 matmul against the u16-halved
    class table (exact — every value < 2^16 is f32-representable and a
    one-hot row selects exactly one table row, the same trick as the
    `oh_f32` strategy in nfa_scan.py), recombined into uint32 lanes;
  * the grid tiles the batch dimension only; each grid step owns its
    rows end to end, so there is no cross-tile communication.

Semantics are bit-identical to `nfa_scan.scan_chunk` (differentially
enforced by tests/test_pallas_scan.py and the corpus parity suite):
per-row global offsets `t_offset` (the halo split's stacked chunks),
negative-t warm-up gating, cross-word carry, multi-pass opt
propagation, and per-row length gating all behave identically.

`pair=True` advances TWO bytes per loop iteration (two fused
lookup+advance half-steps), halving the loop-iteration count the same
way the `pair` lookup strategy does for lax.scan — inside a fused
kernel the win is loop bookkeeping rather than gather dispatch, but it
keeps the dependent-step accounting of the two strategies aligned.

On hosts without a TPU the kernel runs under `interpret=True` (pallas'
jax-level interpreter), so the CPU differential-parity suite covers the
exact kernel the chip would run. Override with PINGOO_PALLAS_INTERPRET.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from .nfa_scan import NfaTables

try:  # pallas ships with jax; guard anyway so import never kills the engine
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    PALLAS_AVAILABLE = False

# Batch tile: grid steps own [B_TILE, W] state slabs. 128 matches the
# VPU lane width; small test batches pad up to one tile.
B_TILE = 128


def pallas_available() -> bool:
    return PALLAS_AVAILABLE


def _use_interpret() -> bool:
    env = os.environ.get("PINGOO_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "tpu"


def _kernel(cls_ref, len_ref, toff_ref, state_ref, tab_ref, vec_ref,
            out_ref, *, W, C, Lc, passes, has_carry, pair, odd, gate_neg):
    """One batch tile: scan Lc byte columns with the state in VMEM."""
    cls_all = cls_ref[...]  # [Lc(+pad), B_tile] int32 class ids
    lens = len_ref[...][:, 0]  # [B_tile]
    toff = toff_ref[...][:, 0]  # [B_tile] global offset of column 0
    tab = tab_ref[...]  # [C, 2W] f32 u16 halves
    vecs = vec_ref[...]  # [5, W] uint32
    init_a, init_u = vecs[0], vecs[1]
    opt, rep, carry = vecs[2], vecs[3], vecs[4]
    one = jnp.uint32(1)

    def shift_words(x):
        return jnp.pad(x[:, :-1], ((0, 0), (1, 0)))

    def lookup(c):
        """Class ids [B_tile] -> byte-class masks [B_tile, W] uint32."""
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)
        oh = (c[:, None] == iota).astype(jnp.float32)
        halves = jnp.dot(oh, tab, preferred_element_type=jnp.float32)
        return (halves[:, :W].astype(jnp.uint32)
                | (halves[:, W:].astype(jnp.uint32) << jnp.uint32(16)))

    def advance(S, bc, t):
        """One byte of the sticky-accept algebra at global positions t."""
        inj = init_u[None, :] | jnp.where(
            (t == 0)[:, None], init_a[None, :], jnp.uint32(0))
        adv = (S << one) | inj
        if has_carry:
            adv = adv | (shift_words((S >> jnp.uint32(31)) & one)
                         & carry[None, :])
        for p in range(passes):
            x = (adv & opt[None, :]) + opt[None, :]
            adv = adv | (x ^ opt[None, :])
            if has_carry and p + 1 < passes:
                esc = (x < opt[None, :]).astype(jnp.uint32)
                adv = adv | (shift_words(esc) & carry[None, :])
        S_new = (adv | (S & rep[None, :])) & bc
        live = t < lens
        if gate_neg:
            live = (t >= 0) & live
        return jnp.where(live[:, None], S_new, S)

    def column(i):
        return jax.lax.dynamic_index_in_dim(cls_all, i, 0, keepdims=False)

    if pair:
        Lp = (Lc + 1) // 2

        def body(i, S):
            t0 = toff + 2 * i
            S1 = advance(S, lookup(column(2 * i)), t0)
            S2 = advance(S1, lookup(column(2 * i + 1)), t0 + 1)
            if odd:
                # The pad column is SYNTHETIC (see scan_chunk's pair
                # path): in chunked callers its global position can lie
                # inside the request, so it is skipped structurally, not
                # by the live gate.
                S2 = jnp.where(i == Lp - 1, S1, S2)
            return S2

        S = jax.lax.fori_loop(0, Lp, body, state_ref[...])
    else:
        def body(i, S):
            return advance(S, lookup(column(i)), toff + i)

        S = jax.lax.fori_loop(0, Lc, body, state_ref[...])
    out_ref[...] = S


def fused_scan_chunk(
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
    state: jax.Array,
    t_offset,
    pair: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """Drop-in replacement for `nfa_scan.scan_chunk` (same contract):
    advance the NFA over one [B, Lc] byte chunk whose first column sits
    at global position `t_offset` (int, traced scalar, or per-row [B]),
    returning the new [B, W] state."""
    if not PALLAS_AVAILABLE:  # pragma: no cover - environment guard
        from .nfa_scan import scan_chunk

        return scan_chunk(tables, data, lengths, state, t_offset)
    B, Lc = data.shape
    W = tables.opt.shape[0]
    if Lc == 0:
        return state
    if interpret is None:
        interpret = _use_interpret()

    # Byte -> class ids ONCE, outside the loop (cls_map is [256]).
    cls = jnp.take(tables.cls_map, data.astype(jnp.int32))  # [B, Lc]
    odd = bool(Lc % 2) if pair else False
    if odd:
        cls = jnp.pad(cls, ((0, 0), (0, 1)))

    if isinstance(t_offset, int):
        toff = jnp.full((B,), t_offset, dtype=jnp.int32)
        gate_neg = t_offset < 0
    else:
        toff = jnp.broadcast_to(
            jnp.asarray(t_offset, dtype=jnp.int32), (B,))
        gate_neg = True  # traced offsets (halo) may be negative

    lens = lengths.astype(jnp.int32)
    Bp = -(-B // B_TILE) * B_TILE
    if Bp != B:
        padb = Bp - B
        cls = jnp.pad(cls, ((0, padb), (0, 0)))
        lens = jnp.pad(lens, (0, padb))  # length 0: rows never advance
        toff = jnp.pad(toff, (0, padb))
        state = jnp.pad(state, ((0, padb), (0, 0)))

    C = tables.cls_table.shape[0]
    vecs = jnp.stack([tables.init_anchored, tables.init_unanchored,
                      tables.opt, tables.rep, tables.carry_mask])  # [5, W]
    Lcp = cls.shape[1]
    kernel = functools.partial(
        _kernel, W=W, C=C, Lc=Lc, passes=1 + tables.extra_passes,
        has_carry=tables.has_carry, pair=pair, odd=odd, gate_neg=gate_neg)
    out = pl.pallas_call(
        kernel,
        grid=(Bp // B_TILE,),
        in_specs=[
            # data transposed to [Lc, B]: the per-iteration column read
            # indexes the SUBLANE axis, which Mosaic slices cheaply.
            pl.BlockSpec((Lcp, B_TILE), lambda i: (0, i)),
            pl.BlockSpec((B_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((B_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((B_TILE, W), lambda i: (i, 0)),
            pl.BlockSpec((C, 2 * W), lambda i: (0, 0)),
            pl.BlockSpec((5, W), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B_TILE, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Bp, W), jnp.uint32),
        interpret=interpret,
    )(cls.T, lens[:, None], toff[:, None], state, tables.cls_u16, vecs)
    return out[:B]
