"""Bitsplit-DFA scan kernel (ISSUE 8): one gather per byte, no matmul.

compiler/nfa.py lowers small/hot NFA banks to byte-indexed DFA tables
(`lower_bank_to_dfa`). This module executes them three ways, mirroring
ops/prefilter.py's structure:

  * `scan_numpy`      — pure-numpy oracle for differential tests;
  * `dfa_scan`        — `lax.scan` ladder: per byte, ONE flat-table
                        gather `trans[state * C + cls]` plus two accept
                        gathers into the sticky accumulator `H`. The
                        dependent chain is L scalar-gather steps at ~4
                        lane-ops/byte — the dependent one-hot matmul
                        chain of the NFA path is gone;
  * `_fused_dfa`      — Pallas kernel keeping state + H in VMEM for the
                        whole byte loop (one-hot f32 matmul lookups,
                        exact for values < 2^16; same trick as
                        ops/pallas_scan.py), `interpret=True` off-TPU.

Accept semantics (see DfaBank's docstring): sticky accepts fire per
consumed byte through `step_accept[state]` OR-ed into H; absolute-end
accepts read `end_accept` at the final state; the always/empty_ok slot
lanes are applied at extraction, identical to nfa_scan.extract_slots.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.nfa import DfaBank

try:  # pallas ships with jax; guard anyway so import never kills the engine
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    PALLAS_AVAILABLE = False

# Batch tile for the fused kernel (matches the VPU lane width).
B_TILE = 128


def _use_interpret() -> bool:
    env = os.environ.get("PINGOO_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "tpu"


@dataclass(frozen=True)
class DfaTables:
    """Device-resident DFA tables (registered pytree; rides np_tables
    through RulesetPlan.device_tables() and the artifact cache)."""

    trans_flat: jax.Array    # [S * C] int32, row-major (state, class)
    byte_cls: jax.Array      # [256] int32
    step_accept: jax.Array   # [S, Wh] uint32
    end_accept: jax.Array    # [S, Wh] uint32
    trans_f32: jax.Array     # [S, C] f32 (fused one-hot path; ids < 2^16)
    step_u16: jax.Array      # [S, 2*Wh] f32 u16 halves of step_accept
    end_u16: jax.Array       # [S, 2*Wh] f32 u16 halves of end_accept
    slot_word: jax.Array     # [P] int32 H word per pattern slot
    slot_mask: jax.Array     # [P] uint32 bit per pattern slot
    slot_always: jax.Array   # [P] bool
    slot_empty_ok: jax.Array  # [P] bool
    num_states: int
    num_classes: int
    num_words: int
    num_slots: int
    exact: bool


jax.tree_util.register_dataclass(
    DfaTables,
    data_fields=["trans_flat", "byte_cls", "step_accept", "end_accept",
                 "trans_f32", "step_u16", "end_u16", "slot_word",
                 "slot_mask", "slot_always", "slot_empty_ok"],
    meta_fields=["num_states", "num_classes", "num_words", "num_slots",
                 "exact"],
)


def _u16_halves(words: np.ndarray) -> np.ndarray:
    """[S, W] uint32 -> [S, 2W] f32 (lo halves then hi halves)."""
    lo = (words & np.uint32(0xFFFF)).astype(np.float32)
    hi = (words >> np.uint32(16)).astype(np.float32)
    return np.concatenate([lo, hi], axis=1)


def dfa_to_tables(bank: DfaBank) -> DfaTables:
    S, C = bank.trans.shape
    P = bank.num_slots
    slot_word = np.arange(P, dtype=np.int32) // 32
    slot_mask = (np.uint32(1) << (np.arange(P, dtype=np.uint32) % 32))
    return DfaTables(
        trans_flat=jnp.asarray(bank.trans.astype(np.int32).reshape(-1)),
        byte_cls=jnp.asarray(bank.byte_cls.astype(np.int32)),
        step_accept=jnp.asarray(bank.step_accept.astype(np.uint32)),
        end_accept=jnp.asarray(bank.end_accept.astype(np.uint32)),
        trans_f32=jnp.asarray(bank.trans.astype(np.float32)),
        step_u16=jnp.asarray(_u16_halves(bank.step_accept.astype(np.uint32))),
        end_u16=jnp.asarray(_u16_halves(bank.end_accept.astype(np.uint32))),
        slot_word=jnp.asarray(slot_word),
        slot_mask=jnp.asarray(slot_mask),
        slot_always=jnp.asarray(bank.slot_always.astype(bool)),
        slot_empty_ok=jnp.asarray(bank.slot_empty_ok.astype(bool)),
        num_states=S, num_classes=C, num_words=bank.num_words,
        num_slots=P, exact=bool(bank.exact),
    )


# -- numpy oracle ------------------------------------------------------------


def scan_numpy(bank: DfaBank, data: np.ndarray,
               lengths: np.ndarray) -> np.ndarray:
    """Reference DFA scan. data: [B, L] uint8 -> matched [B, P] bool."""
    B, L = data.shape
    state = np.zeros(B, dtype=np.int64)
    H = np.zeros((B, bank.num_words), dtype=np.uint32)
    for t in range(L):
        live = t < lengths
        H[live] |= bank.step_accept[state[live]]
        c = bank.byte_cls[data[:, t].astype(np.int64)]
        state[live] = bank.trans[state[live], c[live]]
    H |= bank.end_accept[state]
    return _extract_np(bank, H, lengths)


def _extract_np(bank: DfaBank, H: np.ndarray,
                lengths: np.ndarray) -> np.ndarray:
    P = bank.num_slots
    idx = np.arange(P)
    lanes = H[:, idx // 32]
    hit = (lanes & (np.uint32(1) << (idx % 32).astype(np.uint32))) != 0
    hit |= bank.slot_always[None, :]
    hit |= bank.slot_empty_ok[None, :] & (lengths == 0)[:, None]
    return hit


# -- lax.scan ladder ---------------------------------------------------------


def dfa_init_state(B: int,
                   num_words: int) -> tuple[jax.Array, jax.Array]:
    """Fresh per-row carry for a chunked scan: (state [B] int32,
    H [B, Wh] uint32)."""
    return (jnp.zeros((B,), dtype=jnp.int32),
            jnp.zeros((B, num_words), dtype=jnp.uint32))


def dfa_scan_chunk(tables: DfaTables, data: jax.Array, lengths: jax.Array,
                   state: jax.Array, H: jax.Array,
                   t_offset) -> tuple[jax.Array, jax.Array]:
    """Advance the (state, H) carry over one [B, Lc] byte chunk whose
    first column sits at global position `t_offset` (scalar or per-row
    [B] int32). Chunks compose: the streaming body scanner
    (engine/bodyscan.py) threads the carry across ring windows, and
    `dfa_scan` below is literally one chunk plus `dfa_finalize` — so a
    payload split at any byte boundary walks the identical state
    sequence as the contiguous scan. `lengths` is each row's TOTAL live
    byte count at global positions (columns with t_offset + i >=
    lengths are padding and leave the carry untouched); `end_accept` is
    deliberately NOT applied here — it reads the final state, which
    only `dfa_finalize` knows."""
    B, Lc = data.shape
    if Lc == 0:
        return state, H
    C = tables.num_classes
    lens = lengths.astype(jnp.int32)
    t_off = jnp.asarray(t_offset, dtype=jnp.int32)
    # Byte -> class ids ONCE, outside the loop (byte_cls is [256]).
    cls = jnp.take(tables.byte_cls, data.astype(jnp.int32))  # [B, Lc]

    def step(carry, xs):
        state, H = carry
        c, i = xs
        live = (t_off + i) < lens  # t_off broadcasts: scalar or [B]
        fire = jnp.take(tables.step_accept, state, axis=0)  # [B, Wh]
        H = jnp.where(live[:, None], H | fire, H)
        nxt = jnp.take(tables.trans_flat, state * C + c)
        state = jnp.where(live, nxt, state)
        return (state, H), None

    xs = (cls.T, jnp.arange(Lc, dtype=jnp.int32))
    (state, H), _ = jax.lax.scan(step, (state, H), xs,
                                 unroll=8 if Lc >= 8 else 1)
    return state, H


def dfa_finalize(tables: DfaTables, state: jax.Array, H: jax.Array,
                 lengths: jax.Array) -> jax.Array:
    """Apply absolute-end accepts at the final carried state and extract
    per-slot hits — the closing half of a chunked scan."""
    H = H | jnp.take(tables.end_accept, state, axis=0)
    return dfa_extract(tables, H, lengths.astype(jnp.int32))


def dfa_scan(tables: DfaTables, data: jax.Array, lengths: jax.Array,
             backend: str | None = None) -> jax.Array:
    """Scan one field's [B, L] bytes -> per-slot hits [B, P] bool."""
    if backend == "pallas" and PALLAS_AVAILABLE:
        return _fused_dfa(tables, data, lengths)
    B, L = data.shape
    lens = lengths.astype(jnp.int32)
    state, H = dfa_init_state(B, tables.num_words)
    state, H = dfa_scan_chunk(tables, data, lens, state, H, 0)
    return dfa_finalize(tables, state, H, lens)


def dfa_extract(tables: DfaTables, H: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """[B, Wh] accumulator -> [B, P] slot hits (always/empty lanes in)."""
    lanes = jnp.take(H, tables.slot_word, axis=1)  # [B, P]
    hit = (lanes & tables.slot_mask[None, :]) != 0
    hit = hit | tables.slot_always[None, :]
    hit = hit | (tables.slot_empty_ok[None, :] & (lengths == 0)[:, None])
    return hit


def dfa_skip_hits(tables: DfaTables, lengths: jax.Array) -> jax.Array:
    """Hits for rows that never scan: the always/empty_ok base only
    (the DFA analogue of verdict's bank_skip_result)."""
    B = lengths.shape[0]
    H = jnp.zeros((B, tables.num_words), dtype=jnp.uint32)
    return dfa_extract(tables, H, lengths.astype(jnp.int32))


def dfa_row_candidates(tables: DfaTables, hits: jax.Array,
                       lengths: jax.Array) -> jax.Array:
    """[B] bool: rows whose DFA hits exceed the skip base — the rows an
    approximate (over-approximating) DFA must hand to the exact-NFA
    recheck. Rows below the base are PROVABLY clean (candidates ⊇
    matches), so pruning them is sound."""
    base = dfa_skip_hits(tables, lengths)
    return jnp.any(hits & ~base, axis=1)


# -- fused Pallas kernel -----------------------------------------------------


def _dfa_kernel(cls_ref, len_ref, trans_ref, step_ref, end_ref, out_ref,
                *, S, C, Wh, Lc):
    """One batch tile: walk Lc byte columns with state + H in VMEM.

    The state id is carried as a one-hot [B_tile, S] f32 row (ids stay
    < 2^16, so every table value is f32-exact); per byte: the one-hot
    row gathers the state's transition row and its step-accept halves
    in two matmuls, the class one-hot selects the next state, and H
    accumulates in uint32 lanes.
    """
    cls_all = cls_ref[...]       # [Lc, B_tile] int32
    lens = len_ref[...][:, 0]    # [B_tile]
    trans = trans_ref[...]       # [S, C] f32
    step_tab = step_ref[...]     # [S, 2Wh] f32
    end_tab = end_ref[...]       # [S, 2Wh] f32
    B = lens.shape[0]
    s_iota = jax.lax.broadcasted_iota(jnp.float32, (1, S), 1)
    c_iota = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)

    def halves_to_u32(halves):
        return (halves[:, :Wh].astype(jnp.uint32)
                | (halves[:, Wh:].astype(jnp.uint32) << jnp.uint32(16)))

    def body(i, carry):
        state, H = carry  # state: [B] f32 ids, H: [B, Wh] uint32
        oh = (state[:, None] == s_iota).astype(jnp.float32)  # [B, S]
        live = i < lens
        fire = halves_to_u32(jnp.dot(
            oh, step_tab, preferred_element_type=jnp.float32))
        H = jnp.where(live[:, None], H | fire, H)
        rows = jnp.dot(oh, trans, preferred_element_type=jnp.float32)
        c = jax.lax.dynamic_index_in_dim(cls_all, i, 0, keepdims=False)
        oh_c = (c[:, None] == c_iota).astype(jnp.float32)  # [B, C]
        nxt = jnp.sum(rows * oh_c, axis=1)
        state = jnp.where(live, nxt, state)
        return state, H

    state0 = jnp.zeros((B,), dtype=jnp.float32)
    H0 = jnp.zeros((B, Wh), dtype=jnp.uint32)
    state, H = jax.lax.fori_loop(0, Lc, body, (state0, H0))
    oh = (state[:, None] == s_iota).astype(jnp.float32)
    H = H | halves_to_u32(jnp.dot(
        oh, end_tab, preferred_element_type=jnp.float32))
    out_ref[...] = H


def _fused_dfa(tables: DfaTables, data: jax.Array, lengths: jax.Array,
               interpret: bool | None = None) -> jax.Array:
    """Fused-kernel variant of dfa_scan (same contract + extraction)."""
    B, L = data.shape
    lens = lengths.astype(jnp.int32)
    if not PALLAS_AVAILABLE or L == 0:  # pragma: no cover - env guard
        return dfa_scan(tables, data, lengths, backend=None)
    if interpret is None:
        interpret = _use_interpret()
    cls = jnp.take(tables.byte_cls, data.astype(jnp.int32))  # [B, L]
    Bp = -(-B // B_TILE) * B_TILE
    lens_p = lens
    if Bp != B:
        padb = Bp - B
        cls = jnp.pad(cls, ((0, padb), (0, 0)))
        lens_p = jnp.pad(lens_p, (0, padb))  # len-0 rows never advance
    S, C, Wh = tables.num_states, tables.num_classes, tables.num_words
    kernel = functools.partial(_dfa_kernel, S=S, C=C, Wh=Wh, Lc=L)
    H = pl.pallas_call(
        kernel,
        grid=(Bp // B_TILE,),
        in_specs=[
            pl.BlockSpec((L, B_TILE), lambda i: (0, i)),
            pl.BlockSpec((B_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((S, C), lambda i: (0, 0)),
            pl.BlockSpec((S, 2 * Wh), lambda i: (0, 0)),
            pl.BlockSpec((S, 2 * Wh), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B_TILE, Wh), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Bp, Wh), jnp.uint32),
        interpret=interpret,
    )(cls.T, lens_p[:, None], tables.trans_f32, tables.step_u16,
      tables.end_u16)
    return dfa_extract(tables, H[:B], lens)
