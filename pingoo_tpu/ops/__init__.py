"""Device ops (JAX/XLA; Pallas variants where profitable).

Enables x64 so numeric predicate lanes run in true int64 — required for
Rust-i64 parity with the interpreter (expr/values.py checked_i64). All
ops pin their dtypes explicitly, so the global flag only affects the
intended lanes.
"""

import jax

jax.config.update("jax_enable_x64", True)

from . import cidr, match_ops, nfa_scan, pallas_scan, prefilter  # noqa: E402,F401
