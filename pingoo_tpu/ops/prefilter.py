"""Packed multi-literal shift-AND prefilter — Stage A of the verdict
cascade (docs/PREFILTER.md, ISSUE 4).

Hyperscan and the FPGA DPI line (arXiv:1904.10786) get their
order-of-magnitude from a cheap approximate pass that over-approximates
the match set before exact automata run; arXiv:1312.4188 shows the same
cascade vectorizes on SIMD hardware. This module is that pass for the
TPU verdict engine: each byte field is scanned ONCE per batch against
every *necessary literal factor* the compiler extracted
(compiler/repat.necessary_factor), and the resulting [B, F] hit bitmap
gates the serial NFA banks in engine/verdict.py — skipping or
compacting them when no candidate survives.

The kernel is deliberately much cheaper than the NFA scan it gates:

  * plain shift-AND over byte CLASSES (case folds ride the class table
    for free) — no optional-skip closure, no rep self-loops, no
    cross-word carry, no multi-pass propagation;
  * factors never span words (FACTOR_MAX_LEN = 12 << 31 bits), so
    packing is dense first-fit and the step is 4 uint32 vector ops plus
    one [256, Wp] row gather;
  * NO guard bits: bit0 of every factor is re-armed by `init` each
    step, so a neighboring factor's top bit shifting in is absorbed by
    the OR — factors pack at exactly their own width.

Per step, with S = in-progress positions and H = sticky hit
accumulator (both [B, Wp] uint32 carries):

    S' = ((S << 1) | init) & B[c]
    H' = H | S'

A factor hit is its LAST position's bit in H. Inputs beyond each
request's length are gated exactly like the NFA scan (padding can never
arm a factor).

`scan_numpy` is the pure-numpy oracle used by the differential property
tests (tests/test_prefilter.py); `prefilter_scan` is the lax.scan
device op; `backend="pallas"` routes through a fused kernel keeping
both carries in VMEM for the whole field (interpret=True off-TPU, the
same program a chip would compile — mirroring ops/pallas_scan.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

WORD_BITS = 32


@dataclass
class PrefilterBank:
    """Host/numpy build product — pickles with the RulesetPlan artifact.

    factors are packed first-fit into uint32 words; factor f occupies
    `width(f)` consecutive bits of one word, accepts at its top bit."""

    num_words: int
    num_factors: int
    byte_table: np.ndarray  # [256, Wp] uint32 class masks
    init: np.ndarray  # [Wp] uint32: bit0 of every factor
    accept_word: np.ndarray  # [F] int32
    accept_mask: np.ndarray  # [F] uint32


@dataclass(frozen=True)
class PrefilterTables:
    """Device-resident tables (registered pytree; static meta fields
    steer trace-time control flow only)."""

    byte_table: jax.Array  # [256, Wp] uint32
    tab_u16: jax.Array  # [256, 2*Wp] f32 u16 halves (pallas lookup)
    init: jax.Array  # [Wp] uint32
    accept_word: jax.Array  # [F] int32
    accept_mask: jax.Array  # [F] uint32
    num_words: int = 1
    num_factors: int = 0


jax.tree_util.register_dataclass(
    PrefilterTables,
    data_fields=["byte_table", "tab_u16", "init", "accept_word",
                 "accept_mask"],
    meta_fields=["num_words", "num_factors"],
)


def build_prefilter_bank(
        factors: list[tuple[frozenset[int], ...]]) -> PrefilterBank:
    """First-fit pack factor byte-class runs into uint32 words."""
    assert factors, "prefilter bank needs at least one factor"
    used: list[int] = []
    rows: list[dict[int, int]] = []
    init: list[int] = []
    acc_word: list[int] = []
    acc_mask: list[int] = []
    for fac in factors:
        m = len(fac)
        assert 0 < m <= WORD_BITS
        w = -1
        for idx, u in enumerate(used):
            if u + m <= WORD_BITS:
                w = idx
                break
        if w == -1:
            used.append(0)
            rows.append({})
            init.append(0)
            w = len(used) - 1
        base = used[w]
        for i, cls in enumerate(fac):
            bit = 1 << (base + i)
            for b in cls:
                rows[w][b] = rows[w].get(b, 0) | bit
        init[w] |= 1 << base
        acc_word.append(w)
        acc_mask.append(1 << (base + m - 1))
        used[w] += m
    W = len(used)
    table = np.zeros((256, W), dtype=np.uint32)
    for w in range(W):
        for b, mask in rows[w].items():
            table[b, w] = mask
    return PrefilterBank(
        num_words=W,
        num_factors=len(factors),
        byte_table=table,
        init=np.array(init, dtype=np.uint32),
        accept_word=np.array(acc_word, dtype=np.int32),
        accept_mask=np.array(acc_mask, dtype=np.uint32),
    )


def bank_to_prefilter_tables(bank: PrefilterBank) -> PrefilterTables:
    tab_u16 = np.concatenate(
        [(bank.byte_table & 0xFFFF).astype(np.float32),
         (bank.byte_table >> 16).astype(np.float32)], axis=1)
    return PrefilterTables(
        byte_table=jnp.asarray(bank.byte_table),
        tab_u16=jnp.asarray(tab_u16),
        init=jnp.asarray(bank.init),
        accept_word=jnp.asarray(bank.accept_word),
        accept_mask=jnp.asarray(bank.accept_mask),
        num_words=bank.num_words,
        num_factors=bank.num_factors,
    )


def scan_numpy(bank: PrefilterBank, data: np.ndarray,
               lengths: np.ndarray) -> np.ndarray:
    """Reference shift-AND scan (oracle). data [B, L] uint8 -> [B, F]."""
    B, L = data.shape
    S = np.zeros((B, bank.num_words), dtype=np.uint32)
    H = np.zeros_like(S)
    for t in range(L):
        bc = bank.byte_table[data[:, t].astype(np.int64)]
        S_new = (((S << np.uint32(1)) | bank.init[None, :]) & bc).astype(
            np.uint32)
        S = np.where((t < lengths)[:, None], S_new, S)
        H |= S
    lanes = H[:, bank.accept_word]
    return (lanes & bank.accept_mask[None, :]) != 0


def prefilter_init_state(
        B: int, num_words: int) -> tuple[jax.Array, jax.Array]:
    """Fresh (S, H) carry pair for a chunked scan, both [B, Wp]."""
    zero = jnp.zeros((B, num_words), dtype=jnp.uint32)
    return zero, zero


def prefilter_scan_chunk(tables: PrefilterTables, data: jax.Array,
                         lengths: jax.Array, S: jax.Array, H: jax.Array,
                         t_offset) -> tuple[jax.Array, jax.Array]:
    """Advance the (S, H) shift-AND carry over one [B, Lc] chunk whose
    first column sits at global position `t_offset` (scalar or per-row
    [B] int32). S holds every factor's in-progress positions, so a
    literal straddling the chunk boundary completes exactly on the
    carry-in — no overlap-tail re-scan needed for the prefilter itself
    (engine/bodyscan.py relies on this to decide lazy NFA starts).
    `lengths` is each row's TOTAL live byte count in global positions;
    `prefilter_scan` below is one chunk at offset 0."""
    B, Lc = data.shape
    if Lc == 0:
        return S, H
    lens = lengths.astype(jnp.int32)
    t_off = jnp.asarray(t_offset, dtype=jnp.int32)
    init = tables.init
    one = jnp.uint32(1)

    def step(carry, xs):
        S, H = carry
        c, i = xs
        bc = jnp.take(tables.byte_table, c.astype(jnp.int32), axis=0)
        S_new = ((S << one) | init[None, :]) & bc
        # Rows past their length keep S unchanged, so H | S adds
        # nothing for them — no second gate needed.
        S = jnp.where((t_off + i < lens)[:, None], S_new, S)
        return (S, H | S), None

    (S, H), _ = jax.lax.scan(
        step, (S, H), (data.T, jnp.arange(Lc, dtype=jnp.int32)),
        unroll=8 if Lc >= 8 else 1)
    return S, H


def prefilter_extract(tables: PrefilterTables, H: jax.Array) -> jax.Array:
    """[B, Wp] sticky accumulator -> [B, F] factor hits."""
    lanes = jnp.take(H, tables.accept_word, axis=1)
    return (lanes & tables.accept_mask[None, :]) != 0


def prefilter_scan(tables: PrefilterTables, data: jax.Array,
                   lengths: jax.Array,
                   backend: str | None = None) -> jax.Array:
    """Scan one byte field against every packed factor.

    data: [B, L] uint8 (zero-padded), lengths: [B] int32
    returns: hits [B, F] bool — factor f appears in request b's field.
    """
    if backend == "pallas":
        H = _fused_prefilter(tables, data, lengths)
        return prefilter_extract(tables, H)
    B, L = data.shape
    S, H = prefilter_init_state(B, tables.init.shape[0])
    S, H = prefilter_scan_chunk(tables, data, lengths, S, H, 0)
    return prefilter_extract(tables, H)


# -- fused Pallas variant -----------------------------------------------------

try:  # pallas ships with jax; guard so import never kills the engine
    from jax.experimental import pallas as pl

    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    PALLAS_AVAILABLE = False

B_TILE = 128  # VPU lane width, same tiling as ops/pallas_scan.py


def _pf_kernel(byte_ref, len_ref, init_ref, tab_ref, out_ref, *, W, Lc):
    """One batch tile: both carries live in VMEM for the whole field.
    The byte lookup is the exact one-hot u16-halves matmul from
    ops/pallas_scan.py (one-hot x u16-valued f32 is exact)."""
    bytes_all = byte_ref[...]  # [Lc, B_tile] int32
    lens = len_ref[...][:, 0]  # [B_tile]
    init = init_ref[...][0]  # [W] uint32
    tab = tab_ref[...]  # [256, 2W] f32
    one = jnp.uint32(1)

    def lookup(c):
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, 256), 1)
        oh = (c[:, None] == iota).astype(jnp.float32)
        halves = jnp.dot(oh, tab, preferred_element_type=jnp.float32)
        return (halves[:, :W].astype(jnp.uint32)
                | (halves[:, W:].astype(jnp.uint32) << jnp.uint32(16)))

    def body(t, carry):
        S, H = carry
        c = jax.lax.dynamic_index_in_dim(bytes_all, t, 0, keepdims=False)
        S_new = ((S << one) | init[None, :]) & lookup(c)
        S = jnp.where((t < lens)[:, None], S_new, S)
        return S, H | S

    zero = jnp.zeros((lens.shape[0], W), dtype=jnp.uint32)
    _, H = jax.lax.fori_loop(0, Lc, body, (zero, zero))
    out_ref[...] = H


def _use_interpret() -> bool:
    import os

    env = os.environ.get("PINGOO_PALLAS_INTERPRET")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "tpu"


def _fused_prefilter(tables: PrefilterTables, data: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """Fused shift-AND over one field -> hit-accumulator H [B, Wp]."""
    import functools

    if not PALLAS_AVAILABLE:  # pragma: no cover - environment guard
        raise RuntimeError("pallas unavailable")
    B, Lc = data.shape
    W = tables.init.shape[0]
    lens = lengths.astype(jnp.int32)
    ints = data.astype(jnp.int32)
    Bp = -(-B // B_TILE) * B_TILE
    if Bp != B:
        padb = Bp - B
        ints = jnp.pad(ints, ((0, padb), (0, 0)))
        lens = jnp.pad(lens, (0, padb))  # length 0: rows never arm
    kernel = functools.partial(_pf_kernel, W=W, Lc=Lc)
    out = pl.pallas_call(
        kernel,
        grid=(Bp // B_TILE,),
        in_specs=[
            pl.BlockSpec((Lc, B_TILE), lambda i: (0, i)),
            pl.BlockSpec((B_TILE, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, W), lambda i: (0, 0)),
            pl.BlockSpec((256, 2 * W), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B_TILE, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Bp, W), jnp.uint32),
        interpret=_use_interpret(),
    )(ints.T, lens[:, None], tables.init[None, :], tables.tab_u16)
    return out[:B]
