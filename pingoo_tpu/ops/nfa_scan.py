"""JAX bit-parallel NFA scan — the device hot op of the verdict engine.

Executes the sticky-accept algebra built by compiler/nfa.py (build_bank)
over a byte tensor [B, L]: a `lax.scan` over the length dimension
carrying a SINGLE [B, W] uint32 state vector. Everything — floating
matches (sticky bits), `$` (expanded to an extra accept position with an
optional-\\n alternative), and \\b (expanded to word-class positions /
anchored alternatives) — lives inside the state word, so per step the
loop does one embedding-style row gather of the [256, W] byte-class
table plus ~7 elementwise uint32 ops, and only S round-trips HBM
between scan iterations (four carried accumulator lanes in an earlier
design tripled the scan's HBM traffic).

The reference behavior this replaces: per-request sequential regex
execution inside the rules loop (reference pingoo/listeners/
http_listener.rs:251-264 -> bel tree-walk with Rust regex).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.nfa import NfaBank


class NfaTables(NamedTuple):
    """Device-resident tables for one field's NFA bank (a pytree)."""

    byte_table: jax.Array  # [256, W] uint32
    init_anchored: jax.Array  # [W] injected at t == 0 only
    init_unanchored: jax.Array  # [W] injected every step
    opt: jax.Array  # [W]
    rep: jax.Array  # [W]
    # Per-pattern slot extraction data:
    slot_word: jax.Array  # [P] int32
    slot_mask: jax.Array  # [P] uint32
    slot_always: jax.Array  # [P] bool
    slot_empty_ok: jax.Array  # [P] bool


def bank_to_tables(bank: NfaBank) -> NfaTables:
    slots = bank.slots
    W = max(bank.num_words, 1)  # keep shapes non-empty for jit

    def pad(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == W:
            return a
        out = np.zeros(W, dtype=np.uint32)
        out[: a.shape[0]] = a
        return out

    byte_table = bank.byte_table
    if byte_table.shape[1] != W:
        bt = np.zeros((256, W), dtype=np.uint32)
        bt[:, : byte_table.shape[1]] = byte_table
        byte_table = bt
    return NfaTables(
        byte_table=jnp.asarray(byte_table),
        init_anchored=jnp.asarray(pad(bank.init_anchored)),
        init_unanchored=jnp.asarray(pad(bank.init_unanchored)),
        opt=jnp.asarray(pad(bank.opt)),
        rep=jnp.asarray(pad(bank.rep)),
        slot_word=jnp.asarray(np.array([s.word for s in slots], dtype=np.int32)),
        slot_mask=jnp.asarray(
            np.array([s.accept_mask for s in slots], dtype=np.uint32)),
        slot_always=jnp.asarray(
            np.array([s.always_match for s in slots], dtype=bool)),
        slot_empty_ok=jnp.asarray(
            np.array([s.empty_ok for s in slots], dtype=bool)),
    )


def scan_chunk(
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
    state: jax.Array,
    t_offset,
) -> jax.Array:
    """Advance the NFA over one [B, Lc] byte chunk whose first column sits
    at global position `t_offset`; returns the new [B, W] state. Chunks
    compose — the sp ring (parallel/ring.py) passes the state between
    devices via ppermute.
    """
    Lc = data.shape[1]
    one = jnp.uint32(1)
    opt = tables.opt
    rep = tables.rep
    lengths = lengths.astype(jnp.int32)

    def step(S, xs):
        c, t_local = xs  # c: [B] uint8
        t = t_local + t_offset  # global byte position
        bc = jnp.take(tables.byte_table, c.astype(jnp.int32), axis=0)  # [B, W]
        inj = jnp.where(t == 0, tables.init_unanchored | tables.init_anchored,
                        tables.init_unanchored)
        adv = (S << one) | inj[None, :]
        adv = adv | (((adv & opt) + opt) ^ opt)
        S_new = (adv | (S & rep)) & bc
        S = jnp.where((t < lengths)[:, None], S_new, S)
        return S, None

    # unroll amortizes loop bookkeeping and lets XLA fuse across steps
    # while the single carry stays register/VMEM-resident (~20% on the
    # dominant bank; measured in-process with floor subtraction).
    state, _ = jax.lax.scan(
        step, state, (data.T, jnp.arange(Lc, dtype=jnp.int32)),
        unroll=8 if Lc >= 8 else 1)
    return state


def init_scan_state(B: int, W: int) -> jax.Array:
    return jnp.zeros((B, W), dtype=jnp.uint32)


def extract_slots(tables: NfaTables, state: jax.Array,
                  lengths: jax.Array) -> jax.Array:
    """Per-pattern verdicts [B, P] from the final state."""
    lengths = lengths.astype(jnp.int32)
    lanes = jnp.take(state, tables.slot_word, axis=1)  # [B, P]
    hit = (lanes & tables.slot_mask[None, :]) != 0
    hit = hit | (tables.slot_empty_ok[None, :] & (lengths == 0)[:, None])
    return hit | tables.slot_always[None, :]


def nfa_scan(tables: NfaTables, data: jax.Array, lengths: jax.Array) -> jax.Array:
    """Run the bank over a byte batch.

    data: [B, L] uint8 (zero-padded), lengths: [B] int32
    returns: matched [B, P] bool  (P = number of packed patterns)
    """
    B, L = data.shape
    state = scan_chunk(
        tables, data, lengths, init_scan_state(B, tables.opt.shape[0]), 0)
    return extract_slots(tables, state, lengths)
