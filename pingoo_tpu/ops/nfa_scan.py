"""JAX bit-parallel NFA scan — the device hot op of the verdict engine.

Executes the extended Shift-And algebra built by compiler/nfa.py
(build_bank) over a byte tensor [B, L]: a `lax.scan` over the length
dimension carrying [B, W] uint32 state lanes. All ops are elementwise
uint32 (VPU-friendly); the only memory op per step is an embedding-style
row gather of the [256, W] byte-class table. See compiler/nfa.py for the
algebra derivation and the numpy reference implementation this op is
differentially tested against.

The reference behavior this replaces: per-request sequential regex
execution inside the rules loop (reference pingoo/listeners/
http_listener.rs:251-264 -> bel tree-walk with Rust regex). Here a whole
batch advances through all patterns simultaneously, one byte per step.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.nfa import NfaBank


class NfaTables(NamedTuple):
    """Device-resident tables for one field's NFA bank (a pytree)."""

    byte_table: jax.Array  # [256, W] uint32
    init_anchored: jax.Array  # [W]
    init_unanchored: jax.Array  # [W]
    opt: jax.Array  # [W]
    rep: jax.Array  # [W]
    last_float: jax.Array  # [W]
    last_end: jax.Array  # [W]
    # Per-pattern slot extraction data:
    slot_word: jax.Array  # [P] int32
    slot_mask: jax.Array  # [P] uint32
    slot_end: jax.Array  # [P] bool ($-anchored)
    slot_always: jax.Array  # [P] bool
    slot_empty_ok: jax.Array  # [P] bool


def bank_to_tables(bank: NfaBank) -> NfaTables:
    slots = bank.slots
    W = max(bank.num_words, 1)  # keep shapes non-empty for jit

    def pad(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == W:
            return a
        out = np.zeros(W, dtype=np.uint32)
        out[: a.shape[0]] = a
        return out

    byte_table = bank.byte_table
    if byte_table.shape[1] != W:
        bt = np.zeros((256, W), dtype=np.uint32)
        bt[:, : byte_table.shape[1]] = byte_table
        byte_table = bt
    return NfaTables(
        byte_table=jnp.asarray(byte_table),
        init_anchored=jnp.asarray(pad(bank.init_anchored)),
        init_unanchored=jnp.asarray(pad(bank.init_unanchored)),
        opt=jnp.asarray(pad(bank.opt)),
        rep=jnp.asarray(pad(bank.rep)),
        last_float=jnp.asarray(pad(bank.last_float)),
        last_end=jnp.asarray(pad(bank.last_end)),
        slot_word=jnp.asarray(
            np.array([s.word for s in slots], dtype=np.int32)
        ),
        slot_mask=jnp.asarray(
            np.array([s.accept_mask for s in slots], dtype=np.uint32)
        ),
        slot_end=jnp.asarray(np.array([s.end_anchored for s in slots], dtype=bool)),
        slot_always=jnp.asarray(
            np.array([s.always_match for s in slots], dtype=bool)
        ),
        slot_empty_ok=jnp.asarray(
            np.array([s.empty_ok for s in slots], dtype=bool)
        ),
    )


def scan_chunk(
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
    state: jax.Array,
    float_acc: jax.Array,
    end_acc: jax.Array,
    ends_nl: jax.Array,
    t_offset,
):
    """Advance the NFA over one [B, Lc] byte chunk whose first column sits
    at global position `t_offset`. Carries (state, float_acc, end_acc) so
    chunks compose — used by the plain scan and by the sp ring scan
    (parallel/ring.py), which passes state between devices via ppermute.
    """
    Lc = data.shape[1]
    one = jnp.uint32(1)
    opt = tables.opt
    rep = tables.rep
    lengths = lengths.astype(jnp.int32)

    def step(carry, xs):
        S, fa, ea = carry
        c, t_local = xs  # c: [B] uint8
        t = t_local + t_offset  # global byte position
        bc = jnp.take(tables.byte_table, c.astype(jnp.int32), axis=0)  # [B, W]
        inj = jnp.where(t == 0, tables.init_unanchored | tables.init_anchored,
                        tables.init_unanchored)
        adv = (S << one) | inj[None, :]
        adv = adv | (((adv & opt) + opt) ^ opt)
        pre = adv | (S & rep)
        S_new = pre & bc
        active = (t < lengths)[:, None]
        S = jnp.where(active, S_new, S)
        fa = fa | jnp.where(active, S_new & tables.last_float, 0)
        before_nl = (ends_nl & (t == lengths - 2))[:, None]
        ea = ea | jnp.where(before_nl, S_new & tables.last_end, 0)
        return (S, fa, ea), None

    (state, float_acc, end_acc), _ = jax.lax.scan(
        step,
        (state, float_acc, end_acc),
        (data.T, jnp.arange(Lc, dtype=jnp.int32)),
    )
    return state, float_acc, end_acc


def trailing_newline_mask(data: jax.Array, lengths: jax.Array) -> jax.Array:
    B = data.shape[0]
    lengths = lengths.astype(jnp.int32)
    last_byte = data[jnp.arange(B), jnp.maximum(lengths - 1, 0)]
    return (lengths > 0) & (last_byte == 0x0A)


def extract_slots(
    tables: NfaTables,
    float_acc: jax.Array,
    end_acc: jax.Array,
    lengths: jax.Array,
    ends_nl: jax.Array,
) -> jax.Array:
    """Per-pattern verdict columns [B, P] from accumulated word lanes."""
    lengths = lengths.astype(jnp.int32)
    fa = jnp.take(float_acc, tables.slot_word, axis=1)  # [B, P]
    ea = jnp.take(end_acc, tables.slot_word, axis=1)
    lanes = jnp.where(tables.slot_end[None, :], ea, fa)
    hit = (lanes & tables.slot_mask[None, :]) != 0
    empty_like = ((lengths == 0) | (ends_nl & (lengths == 1)))[:, None]
    hit = hit | (tables.slot_end & tables.slot_empty_ok)[None, :] & empty_like
    hit = hit | tables.slot_always[None, :]
    return hit


def nfa_scan(tables: NfaTables, data: jax.Array, lengths: jax.Array) -> jax.Array:
    """Run the bank over a byte batch.

    data: [B, L] uint8 (zero-padded), lengths: [B] int32
    returns: matched [B, P] bool  (P = number of packed patterns)
    """
    B, L = data.shape
    state0 = jnp.zeros((B, tables.opt.shape[0]), dtype=jnp.uint32)
    acc0 = jnp.zeros_like(state0)
    endacc0 = jnp.zeros_like(state0)
    ends_nl = trailing_newline_mask(data, lengths)
    state, float_acc, end_acc = scan_chunk(
        tables, data, lengths, state0, acc0, endacc0, ends_nl, 0)
    end_acc = end_acc | (state & tables.last_end)
    return extract_slots(tables, float_acc, end_acc, lengths, ends_nl)
