"""JAX bit-parallel NFA scan — the device hot op of the verdict engine.

Executes the sticky-accept algebra built by compiler/nfa.py (build_bank)
over a byte tensor [B, L]: a `lax.scan` over the length dimension
carrying a SINGLE [B, W] uint32 state vector. Everything — floating
matches (sticky bits), `$` (expanded to an extra accept position with an
optional-\\n alternative), and \\b (expanded to word-class positions /
anchored alternatives) — lives inside the state word, so per step the
loop does one embedding-style row gather of the [256, W] byte-class
table plus ~7 elementwise uint32 ops, and only S round-trips HBM
between scan iterations (four carried accumulator lanes in an earlier
design tripled the scan's HBM traffic).

Multi-word patterns (compiler/nfa.py pack_span) add a cross-word carry:
bit31 of a span word advances into bit0 of the next (`carry_mask`), and
optional-run closures that overflow a word re-inject there before an
extra propagation pass. Both the carry and the pass count are STATIC
bank properties (`has_carry`, `extra_passes`), so single-word banks —
the common case — trace to exactly the old 7-op step.

The reference behavior this replaces: per-request sequential regex
execution inside the rules loop (reference pingoo/listeners/
http_listener.rs:251-264 -> bel tree-walk with Rust regex).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.nfa import NfaBank


@dataclass(frozen=True)
class NfaTables:
    """Device-resident tables for one field's NFA bank.

    Registered as a pytree whose array fields are leaves and whose
    `has_carry` / `extra_passes` / `identity_accept` fields are STATIC
    metadata — they steer trace-time control flow (python ifs/loops in
    scan_chunk / extract_slots), never device data.
    """

    byte_table: jax.Array  # [256, W] uint32
    init_anchored: jax.Array  # [W] injected at t == 0 only
    init_unanchored: jax.Array  # [W] injected every step
    opt: jax.Array  # [W]
    rep: jax.Array  # [W]
    carry_mask: jax.Array  # [W] uint32: 1 where word w continues word w-1
    sticky: jax.Array  # [W] uint32: sticky-accept accumulator bits
    # Accept extraction: J (word, mask) pairs; pattern p owns the pairs
    # member[:, p] selects (pairs are contiguous per pattern).
    accept_word: jax.Array  # [J] int32
    accept_mask: jax.Array  # [J] uint32
    accept_member: jax.Array  # [J, P] float32 OR-membership matrix
    slot_always: jax.Array  # [P] bool
    slot_empty_ok: jax.Array  # [P] bool
    # -- static metadata (not pytree leaves) --
    has_carry: bool = False
    extra_passes: int = 0  # opt-propagation passes beyond the first
    identity_accept: bool = True  # J == P with pair j belonging to slot j
    # Bounded-memory property: every self-loop is a sticky accept
    # accumulator, so the non-accept state at position t depends only on
    # the last `max_footprint` bytes — the precondition for the
    # halo-parallel sequence scan (parallel/ring.py halo_nfa_scan).
    halo_ok: bool = False
    max_footprint: int = 0


jax.tree_util.register_dataclass(
    NfaTables,
    data_fields=["byte_table", "init_anchored", "init_unanchored", "opt",
                 "rep", "carry_mask", "sticky", "accept_word", "accept_mask",
                 "accept_member", "slot_always", "slot_empty_ok"],
    meta_fields=["has_carry", "extra_passes", "identity_accept", "halo_ok",
                 "max_footprint"],
)


def bank_to_tables(bank: NfaBank) -> NfaTables:
    slots = bank.slots
    W = max(bank.num_words, 1)  # keep shapes non-empty for jit

    def pad(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == W:
            return a
        out = np.zeros(W, dtype=np.uint32)
        out[: a.shape[0]] = a
        return out

    byte_table = bank.byte_table
    if byte_table.shape[1] != W:
        bt = np.zeros((256, W), dtype=np.uint32)
        bt[:, : byte_table.shape[1]] = byte_table
        byte_table = bt

    # Flatten accept pairs in slot order; never-match slots contribute a
    # dead pair (word 0, mask 0) so the identity fast path (J == P, pair
    # j <-> slot j) survives banks that mix in always/never patterns.
    acc_word: list[int] = []
    acc_mask: list[int] = []
    pair_slot: list[int] = []
    for p, slot in enumerate(slots):
        pairs = slot.accepts or ((0, 0),)
        for w, mask in pairs:
            acc_word.append(w)
            acc_mask.append(mask)
            pair_slot.append(p)
    J, P = len(acc_word), len(slots)
    identity = J == P and all(pair_slot[j] == j for j in range(J))
    # Rows follow the (possibly padded-to-1) accept arrays; columns are
    # exactly P so the matmul output shape is [B, P] even when P == 0.
    member = np.zeros((max(J, 1), P), dtype=np.float32)
    for j, p in enumerate(pair_slot):
        member[j, p] = 1.0

    halo_ok = bool(np.all((bank.rep & ~bank.sticky_mask) == 0)) \
        if bank.num_words else True
    return NfaTables(
        byte_table=jnp.asarray(byte_table),
        init_anchored=jnp.asarray(pad(bank.init_anchored)),
        init_unanchored=jnp.asarray(pad(bank.init_unanchored)),
        opt=jnp.asarray(pad(bank.opt)),
        rep=jnp.asarray(pad(bank.rep)),
        carry_mask=jnp.asarray(pad(bank.carry_mask)),
        sticky=jnp.asarray(pad(bank.sticky_mask)),
        accept_word=jnp.asarray(np.array(acc_word or [0], dtype=np.int32)),
        accept_mask=jnp.asarray(np.array(acc_mask or [0], dtype=np.uint32)),
        accept_member=jnp.asarray(member),
        slot_always=jnp.asarray(
            np.array([s.always_match for s in slots], dtype=bool)),
        slot_empty_ok=jnp.asarray(
            np.array([s.empty_ok for s in slots], dtype=bool)),
        has_carry=bank.has_carry,
        extra_passes=max(bank.prop_passes - 1, 0),
        identity_accept=identity,
        halo_ok=halo_ok,
        max_footprint=int(bank.max_footprint),
    )


def scan_chunk(
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
    state: jax.Array,
    t_offset,
) -> jax.Array:
    """Advance the NFA over one [B, Lc] byte chunk whose first column sits
    at global position `t_offset`; returns the new [B, W] state. Chunks
    compose — the sp ring (parallel/ring.py) passes the state between
    devices via ppermute.
    """
    Lc = data.shape[1]
    one = jnp.uint32(1)
    opt = tables.opt
    rep = tables.rep
    carry_mask = tables.carry_mask
    lengths = lengths.astype(jnp.int32)
    has_carry = tables.has_carry
    passes = 1 + tables.extra_passes
    # Only the halo scan passes a (traced, possibly negative) t_offset;
    # the plain/ring paths pass a non-negative Python int, so the t >= 0
    # warm-up gate stays OUT of their traced hot step.
    t_can_be_negative = not (isinstance(t_offset, int) and t_offset >= 0)

    def shift_words(x):
        """[B, W] -> value of word w-1 moved into word w (word 0 gets 0)."""
        return jnp.pad(x[:, :-1], ((0, 0), (1, 0)))

    def step(S, xs):
        c, t_local = xs  # c: [B] uint8
        t = t_local + t_offset  # global byte position
        bc = jnp.take(tables.byte_table, c.astype(jnp.int32), axis=0)  # [B, W]
        inj = jnp.where(t == 0, tables.init_unanchored | tables.init_anchored,
                        tables.init_unanchored)
        adv = (S << one) | inj[None, :]
        if has_carry:
            # bit31 of span word w-1 advances into bit0 of word w.
            adv = adv | (shift_words((S >> jnp.uint32(31)) & one) & carry_mask)
        for p in range(passes):
            x = (adv & opt) + opt  # wraps (mod 2^32) when closure escapes
            adv = adv | (x ^ opt)
            if has_carry and p + 1 < passes:
                esc = (x < opt).astype(jnp.uint32)
                adv = adv | (shift_words(esc) & carry_mask)
        S_new = (adv | (S & rep)) & bc
        live = t < lengths
        if t_can_be_negative:  # halo warm-up prefix on device 0
            live = (t >= 0) & live
        S = jnp.where(live[:, None], S_new, S)
        return S, None

    # unroll amortizes loop bookkeeping and lets XLA fuse across steps
    # while the single carry stays register/VMEM-resident (~20% on the
    # dominant bank; measured in-process with floor subtraction).
    state, _ = jax.lax.scan(
        step, state, (data.T, jnp.arange(Lc, dtype=jnp.int32)),
        unroll=8 if Lc >= 8 else 1)
    return state


def init_scan_state(B: int, W: int) -> jax.Array:
    return jnp.zeros((B, W), dtype=jnp.uint32)


def extract_slots(tables: NfaTables, state: jax.Array, lengths: jax.Array,
                  pair_hit: jax.Array | None = None) -> jax.Array:
    """Per-pattern verdicts [B, P] from the final state.

    `pair_hit` overrides the default per-accept-pair hit matrix — the
    halo scan passes its sticky/owner-gated variant and reuses the
    identical pair->slot reduction and empty/always lanes here."""
    lengths = lengths.astype(jnp.int32)
    if pair_hit is None:
        lanes = jnp.take(state, tables.accept_word, axis=1)  # [B, J]
        pair_hit = (lanes & tables.accept_mask[None, :]) != 0
    if tables.identity_accept:
        hit = pair_hit  # J == P, pair j IS slot j
    else:
        # OR pairs into slots with one [B, J] x [J, P] matmul (MXU does
        # the reduction; same trick as the leaf-span extraction in
        # engine/verdict.py).
        counts = jnp.dot(pair_hit.astype(jnp.float32), tables.accept_member,
                         preferred_element_type=jnp.float32)
        hit = counts > 0.0
    hit = hit | (tables.slot_empty_ok[None, :] & (lengths == 0)[:, None])
    return hit | tables.slot_always[None, :]


def nfa_scan(tables: NfaTables, data: jax.Array, lengths: jax.Array) -> jax.Array:
    """Run the bank over a byte batch.

    data: [B, L] uint8 (zero-padded), lengths: [B] int32
    returns: matched [B, P] bool  (P = number of packed patterns)
    """
    B, L = data.shape
    state = scan_chunk(
        tables, data, lengths, init_scan_state(B, tables.opt.shape[0]), 0)
    return extract_slots(tables, state, lengths)
