"""JAX bit-parallel NFA scan — the device hot op of the verdict engine.

Executes the sticky-accept algebra built by compiler/nfa.py (build_bank)
over a byte tensor [B, L]: a `lax.scan` over the length dimension
carrying a SINGLE [B, W] uint32 state vector. Everything — floating
matches (sticky bits), `$` (expanded to an extra accept position with an
optional-\\n alternative), and \\b (expanded to word-class positions /
anchored alternatives) — lives inside the state word, so per step the
loop does one embedding-style row gather of the [256, W] byte-class
table plus ~7 elementwise uint32 ops, and only S round-trips HBM
between scan iterations (four carried accumulator lanes in an earlier
design tripled the scan's HBM traffic).

Multi-word patterns (compiler/nfa.py pack_span) add a cross-word carry:
bit31 of a span word advances into bit0 of the next (`carry_mask`), and
optional-run closures that overflow a word re-inject there before an
extra propagation pass. Both the carry and the pass count are STATIC
bank properties (`has_carry`, `extra_passes`), so single-word banks —
the common case — trace to exactly the old 7-op step.

The reference behavior this replaces: per-request sequential regex
execution inside the rules loop (reference pingoo/listeners/
http_listener.rs:251-264 -> bel tree-walk with Rust regex).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.nfa import NfaBank


@dataclass(frozen=True)
class NfaTables:
    """Device-resident tables for one field's NFA bank.

    Registered as a pytree whose array fields are leaves and whose
    `has_carry` / `extra_passes` / `identity_accept` fields are STATIC
    metadata — they steer trace-time control flow (python ifs/loops in
    scan_chunk / extract_slots), never device data.
    """

    byte_table: jax.Array  # [256, W] uint32
    # Byte-class compression of byte_table: rows dedup to C <= 256
    # distinct classes (CRS-scale banks measure C in the tens). cls_map
    # sends a byte to its class id; cls_table is the deduped [C, W]
    # table; cls_u16 is the same table split into u16 halves as f32
    # [C, 2W] for the one-hot-matmul lookup (every value < 2^16 is exact
    # in f32, and a one-hot row selects exactly one table row, so the
    # MXU reduction is exact — see scan_chunk's `lookup` strategies).
    cls_map: jax.Array  # [256] int32
    cls_table: jax.Array  # [C, W] uint32
    cls_u16: jax.Array  # [C, 2W] float32
    init_anchored: jax.Array  # [W] injected at t == 0 only
    init_unanchored: jax.Array  # [W] injected every step
    opt: jax.Array  # [W]
    rep: jax.Array  # [W]
    carry_mask: jax.Array  # [W] uint32: 1 where word w continues word w-1
    sticky: jax.Array  # [W] uint32: sticky-accept accumulator bits
    # Accept extraction: J (word, mask) pairs; pattern p owns the pairs
    # member[:, p] selects (pairs are contiguous per pattern).
    accept_word: jax.Array  # [J] int32
    accept_mask: jax.Array  # [J] uint32
    accept_member: jax.Array  # [J, P] float32 OR-membership matrix
    slot_always: jax.Array  # [P] bool
    slot_empty_ok: jax.Array  # [P] bool
    # -- static metadata (not pytree leaves) --
    has_carry: bool = False
    extra_passes: int = 0  # opt-propagation passes beyond the first
    identity_accept: bool = True  # J == P with pair j belonging to slot j
    # Static word count and atom partition for the packed multi-bank
    # scan (pack_scan_groups): atoms are maximal carry-chained word runs
    # [lo, hi) that must stay contiguous inside one lane group (the
    # cross-word carry shifts between adjacent lanes).
    num_words: int = 1
    atoms: tuple[tuple[int, int], ...] = ((0, 1),)
    # Bounded-memory property: every self-loop is a sticky accept
    # accumulator, so the non-accept state at position t depends only on
    # the last `max_footprint` bytes — the precondition for the
    # halo-parallel sequence scan (parallel/ring.py halo_nfa_scan).
    halo_ok: bool = False
    max_footprint: int = 0


jax.tree_util.register_dataclass(
    NfaTables,
    data_fields=["byte_table", "cls_map", "cls_table", "cls_u16",
                 "init_anchored", "init_unanchored", "opt",
                 "rep", "carry_mask", "sticky", "accept_word", "accept_mask",
                 "accept_member", "slot_always", "slot_empty_ok"],
    meta_fields=["has_carry", "extra_passes", "identity_accept", "halo_ok",
                 "max_footprint", "num_words", "atoms"],
)


def class_compress(byte_table: np.ndarray):
    """Dedup a [256, W] byte table into (cls_map [256] i32, cls_table
    [C, W] u32, cls_u16 [C, 2W] f32 u16-halves). Single source of truth
    for the class encoding — bank_to_tables and the tp padding path
    (parallel/mesh.py) must produce bit-identical tables."""
    cls_table, cls_map = np.unique(byte_table, axis=0, return_inverse=True)
    cls_u16 = np.concatenate(
        [(cls_table & 0xFFFF).astype(np.float32),
         (cls_table >> 16).astype(np.float32)], axis=1)
    return cls_map.astype(np.int32), cls_table, cls_u16


def bank_to_tables(bank: NfaBank) -> NfaTables:
    slots = bank.slots
    W = max(bank.num_words, 1)  # keep shapes non-empty for jit

    def pad(a: np.ndarray) -> np.ndarray:
        if a.shape[0] == W:
            return a
        out = np.zeros(W, dtype=np.uint32)
        out[: a.shape[0]] = a
        return out

    byte_table = bank.byte_table
    if byte_table.shape[1] != W:
        bt = np.zeros((256, W), dtype=np.uint32)
        bt[:, : byte_table.shape[1]] = byte_table
        byte_table = bt

    # Byte-class compression (trace-free: computed on host numpy).
    cls_map, cls_table, cls_u16 = class_compress(byte_table)

    # Flatten accept pairs in slot order; never-match slots contribute a
    # dead pair (word 0, mask 0) so the identity fast path (J == P, pair
    # j <-> slot j) survives banks that mix in always/never patterns.
    acc_word: list[int] = []
    acc_mask: list[int] = []
    pair_slot: list[int] = []
    for p, slot in enumerate(slots):
        pairs = slot.accepts or ((0, 0),)
        for w, mask in pairs:
            acc_word.append(w)
            acc_mask.append(mask)
            pair_slot.append(p)
    J, P = len(acc_word), len(slots)
    identity = J == P and all(pair_slot[j] == j for j in range(J))
    # Rows follow the (possibly padded-to-1) accept arrays; columns are
    # exactly P so the matmul output shape is [B, P] even when P == 0.
    member = np.zeros((max(J, 1), P), dtype=np.float32)
    for j, p in enumerate(pair_slot):
        member[j, p] = 1.0

    halo_ok = bool(np.all((bank.rep & ~bank.sticky_mask) == 0)) \
        if bank.num_words else True
    # Atom partition: word w with carry 0 starts a new atom; carry-1
    # words extend the previous word's span.
    atoms: list[tuple[int, int]] = []
    carry_flags = pad(bank.carry_mask)
    for w in range(W):
        if carry_flags[w] == 0 or not atoms:
            atoms.append((w, w + 1))
        else:
            atoms[-1] = (atoms[-1][0], w + 1)
    return NfaTables(
        byte_table=jnp.asarray(byte_table),
        cls_map=jnp.asarray(cls_map),
        cls_table=jnp.asarray(cls_table),
        cls_u16=jnp.asarray(cls_u16),
        init_anchored=jnp.asarray(pad(bank.init_anchored)),
        init_unanchored=jnp.asarray(pad(bank.init_unanchored)),
        opt=jnp.asarray(pad(bank.opt)),
        rep=jnp.asarray(pad(bank.rep)),
        carry_mask=jnp.asarray(pad(bank.carry_mask)),
        sticky=jnp.asarray(pad(bank.sticky_mask)),
        accept_word=jnp.asarray(np.array(acc_word or [0], dtype=np.int32)),
        accept_mask=jnp.asarray(np.array(acc_mask or [0], dtype=np.uint32)),
        accept_member=jnp.asarray(member),
        slot_always=jnp.asarray(
            np.array([s.always_match for s in slots], dtype=bool)),
        slot_empty_ok=jnp.asarray(
            np.array([s.empty_ok for s in slots], dtype=bool)),
        has_carry=bank.has_carry,
        extra_passes=max(bank.prop_passes - 1, 0),
        identity_accept=identity,
        halo_ok=halo_ok,
        max_footprint=int(bank.max_footprint),
        num_words=W,
        atoms=tuple(atoms),
    )


# Byte-class lookup strategy for the scan step (measured on the v5e —
# see the knob notes in engine/verdict.py):
#   take     — bc = byte_table[c]: one [256, W] row gather per step.
#   cls_take — map bytes to class ids once outside the loop, then gather
#              from the deduped [C, W] table per step.
#   oh_f32   — class ids once outside the loop, then per step a one-hot
#              [B, C] f32 matmul against cls_u16 [C, 2W]: the MXU does
#              the row selection (exact: one-hot x u16-valued f32), the
#              VPU only recombines the halves.
#   pair     — class ids outside the loop, then ONE gather from a
#              [C^2, 2W] pair table per TWO bytes: halves the serial
#              scan length at the cost of a bigger (trace-derived)
#              table; falls back to cls_take when the table would
#              exceed PAIR_TABLE_MAX_BYTES.
#   auto     — take, everywhere. Re-measured round 3 with the forced-
#              alternating salt (the earlier "oh_f32 wins on TPU" call
#              came from the hoistable loop): on the v5e the [256, W]
#              row gather beats every other strategy on all three
#              CRS-corpus banks — oh_f32 by 3.3x on a small-W bank
#              (user_agent W=5: 0.54 vs 2.77 ms), by 1.7x on the widest
#              (url W=140: 0.94 vs 1.56 ms) — and on the CPU test
#              backend take was already the choice.
LOOKUP_MODE = os.environ.get("PINGOO_NFA_LOOKUP", "auto")
PAIR_TABLE_MAX_BYTES = 16 << 20  # C^2 x 2W u32 pair table cap


def _resolve_lookup(lookup: str | None) -> str:
    mode = lookup or LOOKUP_MODE
    if mode == "auto":
        return "take"
    return mode


def _class_data(tables: NfaTables, data: jax.Array, lookup: str) -> jax.Array:
    """Pre-transform the byte tensor for the chosen lookup: class-id
    strategies map bytes -> class ids ONCE, outside the scan loop."""
    if lookup == "take":
        return data
    return jnp.take(tables.cls_map, data.astype(jnp.int32))


def _bc_fn(tables: NfaTables, lookup: str):
    """Per-step byte-class mask: class-ids/bytes [B] -> bc [B, W]."""
    if lookup == "take":
        return lambda c: jnp.take(
            tables.byte_table, c.astype(jnp.int32), axis=0)
    if lookup == "cls_take":
        return lambda c: jnp.take(tables.cls_table, c, axis=0)
    if lookup == "oh_f32":
        C = tables.cls_u16.shape[0]
        W = tables.opt.shape[0]

        def bc(c):
            oh = (c[:, None] == jnp.arange(C, dtype=c.dtype)[None, :]
                  ).astype(jnp.float32)
            halves = jnp.dot(oh, tables.cls_u16,
                             preferred_element_type=jnp.float32)
            return (halves[:, :W].astype(jnp.uint32)
                    | (halves[:, W:].astype(jnp.uint32) << jnp.uint32(16)))

        return bc
    raise ValueError(f"unknown nfa lookup {lookup!r}")


def scan_chunk(
    tables: NfaTables,
    data: jax.Array,
    lengths: jax.Array,
    state: jax.Array,
    t_offset,
    lookup: str | None = None,
    backend: str | None = None,
) -> jax.Array:
    """Advance the NFA over one [B, Lc] byte chunk whose first column sits
    at global position `t_offset`; returns the new [B, W] state. Chunks
    compose — the sp ring (parallel/ring.py) passes the state between
    devices via ppermute. `t_offset` may also be a PER-ROW [B] array
    (the within-device halo split stacks chunks as extra rows, each with
    its own global offset).

    `backend="pallas"` routes the loop through the fused Pallas kernel
    (ops/pallas_scan.py) — bit-identical semantics, state resident in
    VMEM across the whole chunk; `lookup == "pair"` there selects the
    two-bytes-per-iteration stepping.
    """
    lookup = _resolve_lookup(lookup)
    if backend == "pallas":
        from .pallas_scan import fused_scan_chunk

        return fused_scan_chunk(tables, data, lengths, state, t_offset,
                                pair=lookup == "pair")
    if lookup == "pair":
        C_, W_ = tables.cls_table.shape
        if C_ * C_ * 2 * W_ * 4 > PAIR_TABLE_MAX_BYTES:
            lookup = "cls_take"  # pair table would blow HBM; same data prep
    data = _class_data(tables, data, lookup)
    Lc = data.shape[1]
    one = jnp.uint32(1)
    opt = tables.opt
    rep = tables.rep
    carry_mask = tables.carry_mask
    lengths = lengths.astype(jnp.int32)
    has_carry = tables.has_carry
    passes = 1 + tables.extra_passes
    per_row = not isinstance(t_offset, int) and getattr(
        t_offset, "ndim", 0) == 1
    # Only the halo scans pass (traced, possibly negative) t_offsets;
    # the plain/ring paths pass a non-negative Python int, so the t >= 0
    # warm-up gate stays OUT of their traced hot step.
    t_can_be_negative = not (isinstance(t_offset, int) and t_offset >= 0)

    def shift_words(x):
        """[B, W] -> value of word w-1 moved into word w (word 0 gets 0)."""
        return jnp.pad(x[:, :-1], ((0, 0), (1, 0)))

    def advance(S, bc, t):
        """One byte of the sticky-accept algebra at global position t."""
        if per_row:
            inj = tables.init_unanchored[None, :] | jnp.where(
                (t == 0)[:, None], tables.init_anchored[None, :],
                jnp.uint32(0))
            adv = (S << one) | inj
        else:
            inj = jnp.where(
                t == 0, tables.init_unanchored | tables.init_anchored,
                tables.init_unanchored)
            adv = (S << one) | inj[None, :]
        if has_carry:
            # bit31 of span word w-1 advances into bit0 of word w.
            adv = adv | (shift_words((S >> jnp.uint32(31)) & one) & carry_mask)
        for p in range(passes):
            x = (adv & opt) + opt  # wraps (mod 2^32) when closure escapes
            adv = adv | (x ^ opt)
            if has_carry and p + 1 < passes:
                esc = (x < opt).astype(jnp.uint32)
                adv = adv | (shift_words(esc) & carry_mask)
        S_new = (adv | (S & rep)) & bc
        live = t < lengths
        if t_can_be_negative:  # halo warm-up prefix on device 0
            live = (t >= 0) & live
        return jnp.where(live[:, None], S_new, S)

    if lookup == "pair":
        # Two bytes per iteration: ONE gather from the [C^2, 2W] pair
        # table feeds two advance() half-steps, halving the serial loop
        # length (the gather is the per-step cost driver; see the knob
        # notes in engine/verdict.py). The pair table is derived from
        # cls_table INSIDE the trace — loop-invariant, so XLA builds it
        # once per call, and NfaTables needs no extra (possibly huge)
        # persistent field.
        C = tables.cls_table.shape[0]
        W = tables.opt.shape[0]
        odd = bool(Lc % 2)
        if odd:
            # The pad column is SYNTHETIC, not request data: in chunked
            # callers (ring / halo) its global position can lie inside
            # the request — the next chunk owns that byte — so the live
            # gate alone must NOT be trusted to kill it; the last pair's
            # second half-step is skipped structurally below.
            data = jnp.pad(data, ((0, 0), (0, 1)))
            Lc += 1
        Lp = Lc // 2
        pairs = (data[:, 0::2].astype(jnp.int32) * C
                 + data[:, 1::2].astype(jnp.int32))  # [B, Lp]
        pair_table = jnp.concatenate(
            [jnp.repeat(tables.cls_table, C, axis=0),
             jnp.tile(tables.cls_table, (C, 1))], axis=1)  # [C^2, 2W]

        def pstep(S, xs):
            pc, tp = xs  # pc: [B] pair id, tp: pair index
            t = 2 * tp + t_offset
            bc2 = jnp.take(pair_table, pc, axis=0)  # [B, 2W]
            S1 = advance(S, bc2[:, :W], t)
            S2 = advance(S1, bc2[:, W:], t + 1)
            if odd:
                S2 = jnp.where(tp == Lp - 1, S1, S2)  # pad byte: no-op
            return S2, None

        state, _ = jax.lax.scan(
            pstep, state, (pairs.T, jnp.arange(Lp, dtype=jnp.int32)),
            unroll=8 if Lp >= 8 else 1)
        return state

    bc_of = _bc_fn(tables, lookup)

    def step(S, xs):
        c, t_local = xs  # c: [B] byte or class id
        t = t_local + t_offset  # global byte position ([B] when per_row)
        return advance(S, bc_of(c), t), None

    # unroll amortizes loop bookkeeping and lets XLA fuse across steps
    # while the single carry stays register/VMEM-resident (~20% on the
    # dominant bank; measured in-process with floor subtraction).
    state, _ = jax.lax.scan(
        step, state, (data.T, jnp.arange(Lc, dtype=jnp.int32)),
        unroll=8 if Lc >= 8 else 1)
    return state


def init_scan_state(B: int, W: int) -> jax.Array:
    return jnp.zeros((B, W), dtype=jnp.uint32)


def extract_slots(tables: NfaTables, state: jax.Array, lengths: jax.Array,
                  pair_hit: jax.Array | None = None) -> jax.Array:
    """Per-pattern verdicts [B, P] from the final state.

    `pair_hit` overrides the default per-accept-pair hit matrix — the
    halo scan passes its sticky/owner-gated variant and reuses the
    identical pair->slot reduction and empty/always lanes here."""
    lengths = lengths.astype(jnp.int32)
    if pair_hit is None:
        lanes = jnp.take(state, tables.accept_word, axis=1)  # [B, J]
        pair_hit = (lanes & tables.accept_mask[None, :]) != 0
    if tables.identity_accept:
        hit = pair_hit  # J == P, pair j IS slot j
    else:
        # OR pairs into slots with one [B, J] x [J, P] matmul (MXU does
        # the reduction; same trick as the leaf-span extraction in
        # engine/verdict.py).
        counts = jnp.dot(pair_hit.astype(jnp.float32), tables.accept_member,
                         preferred_element_type=jnp.float32)
        hit = counts > 0.0
    hit = hit | (tables.slot_empty_ok[None, :] & (lengths == 0)[:, None])
    return hit | tables.slot_always[None, :]


def nfa_scan(tables: NfaTables, data: jax.Array, lengths: jax.Array,
             lookup: str | None = None,
             backend: str | None = None) -> jax.Array:
    """Run the bank over a byte batch.

    data: [B, L] uint8 (zero-padded), lengths: [B] int32
    returns: matched [B, P] bool  (P = number of packed patterns)
    """
    B, L = data.shape
    state = scan_chunk(
        tables, data, lengths, init_scan_state(B, tables.opt.shape[0]), 0,
        lookup=lookup, backend=backend)
    return extract_slots(tables, state, lengths)


# -- within-device halo split -------------------------------------------------


def halo_split_k(tables: NfaTables, L: int, max_k: int = 8) -> int:
    """Largest power-of-2 split factor that shortens the scan: k chunks
    of L/k (+H halo) steps each, valid while the halo fits in a chunk.
    Returns 1 when splitting is ineligible or not profitable."""
    if not tables.halo_ok:
        return 1
    H = int(tables.max_footprint)
    best = 1
    k = 2
    while k <= max_k and L % k == 0 and H <= L // k:
        best = k
        k *= 2
    # profitable only if strictly fewer steps than the plain scan
    return best if best > 1 and (L // best + H) < L else 1


def halo_split_scan(tables: NfaTables, data: jax.Array, lengths: jax.Array,
                    k: int, lookup: str | None = None,
                    backend: str | None = None) -> jax.Array:
    """Sequence-split scan WITHIN one device: the length axis is cut into
    k chunks that become extra BATCH rows, each prefixed by an H-byte
    halo of its predecessor — the same construction as the sp halo scan
    (parallel/ring.py halo_nfa_scan) with rows instead of devices. The
    scan loop shrinks from L to L/k + H serial steps; the accept split
    is identical: sticky accumulator bits OR across chunks, positional
    accepts read from the chunk owning each request's final byte.
    Exact under the same conditions (halo_ok, H <= L/k)."""
    B, L = data.shape
    assert L % k == 0
    Lc = L // k
    H = int(tables.max_footprint)
    assert tables.halo_ok and H <= Lc
    lengths32 = lengths.astype(jnp.int32)
    padded = jnp.pad(data, ((0, 0), (H, 0)))  # zeros before position 0
    chunks = jnp.stack(
        [padded[:, i * Lc:i * Lc + H + Lc] for i in range(k)],
        axis=1)  # [B, k, H + Lc]
    rows = chunks.reshape(B * k, H + Lc)
    row_lens = jnp.broadcast_to(lengths32[:, None], (B, k)).reshape(-1)
    # Chunk i's first column sits at global byte i*Lc - H (negative
    # warm-up bytes are live-gated off in scan_chunk, so chunk 0's
    # zero-prefix is a no-op and t == 0 injection happens exactly once).
    offs = jnp.broadcast_to(
        (jnp.arange(k, dtype=jnp.int32) * Lc - H)[None, :], (B, k)
    ).reshape(-1)
    state = scan_chunk(tables, rows, row_lens,
                       init_scan_state(B * k, tables.opt.shape[0]), offs,
                       lookup=lookup, backend=backend)
    lanes = jnp.take(state, tables.accept_word, axis=1)  # [B*k, J]
    lanes = lanes.reshape(B, k, -1)
    masks = tables.accept_mask[None, None, :]
    sticky_j = jnp.take(tables.sticky, tables.accept_word)[None, None, :]
    sticky_hit = ((lanes & masks & sticky_j) != 0).any(axis=1)  # [B, J]
    owner = jnp.clip((lengths32 - 1) // Lc, 0, k - 1)  # [B]
    end_lanes = jnp.take_along_axis(
        lanes, owner[:, None, None], axis=1)[:, 0]  # [B, J]
    end_hit = (end_lanes & masks[:, 0] & ~sticky_j[:, 0]) != 0
    return extract_slots(tables, state, lengths32,
                         pair_hit=sticky_hit | end_hit)


# -- packed multi-bank scan ---------------------------------------------------
#
# The VPU lane dimension tiles at 128: a bank with W < 128 words pays for
# 128 lanes anyway, and per-step cost is dominated by the scan loop, not
# lane width. Packing several fields' words into shared <=128-lane groups
# (grouped by the fields' trace-time bucketed lengths) turns that padding
# into useful work: one scan step advances url AND path words instead of
# two scans advancing each behind a wall of dead lanes. VERDICT r2 item 3.

LANE_GROUP = 128


@dataclass(frozen=True)
class GroupMember:
    """A contiguous word slice [w_lo, w_hi) of one bank inside a group.
    Slices are unions of whole atoms, so cross-word carry never crosses
    a member boundary (the concatenated carry mask is 0 at w_lo)."""

    key: str
    w_lo: int
    w_hi: int


def pack_scan_groups(
    sizes: list[tuple[str, int, tuple[tuple[int, int], ...]]],
    mode: str = "length",
) -> list[tuple[int, list[GroupMember]]]:
    """Assign bank words to lane groups. `sizes` is a list of
    (key, L_bucket, atoms) in a deterministic order; returns
    [(L_group, members)]. Modes:

      field  — one group per bank (the pre-packing behavior)
      length — pack banks whose bucketed L is equal into shared groups
      fill   — sort by L desc, stream-fill groups to 128 lanes (shorter
               fields ride longer groups' free lanes; their rows are
               length-masked after their own L)
      single — everything in one group at max L (no lane cap)
    """
    if mode == "field":
        return [(L, [GroupMember(key, 0, atoms[-1][1] if atoms else 1)])
                for key, L, atoms in sizes]
    if mode == "single":
        Lg = max((L for _, L, _ in sizes), default=0)
        return [(Lg, [GroupMember(key, 0, atoms[-1][1] if atoms else 1)
                      for key, _, atoms in sizes])]

    def stream(entries):
        """First-fit streaming of atoms into <=128-word groups."""
        groups: list[tuple[int, list[GroupMember]]] = []
        cur: list[GroupMember] = []
        cur_w = 0
        cur_l = 0
        for key, L, atoms in entries:
            for lo, hi in atoms:
                n = hi - lo
                if cur_w + n > LANE_GROUP and cur:
                    groups.append((cur_l, cur))
                    cur, cur_w, cur_l = [], 0, 0
                if cur and cur[-1].key == key and cur[-1].w_hi == lo:
                    cur[-1] = GroupMember(key, cur[-1].w_lo, hi)
                else:
                    cur.append(GroupMember(key, lo, hi))
                cur_w += n
                cur_l = max(cur_l, L)
        if cur:
            groups.append((cur_l, cur))
        return groups

    if mode == "fill":
        order = sorted(sizes, key=lambda s: (-s[1], s[0]))
        return stream(order)
    if mode == "length":
        out: list[tuple[int, list[GroupMember]]] = []
        by_len: dict[int, list] = {}
        for entry in sizes:
            by_len.setdefault(entry[1], []).append(entry)
        for L in sorted(by_len, reverse=True):
            out.extend(stream(by_len[L]))
        return out
    raise ValueError(f"unknown pack mode {mode!r}")


def _run_group(banks: dict[str, NfaTables], data, lengths, B: int,
               Lg: int, members: list[GroupMember]) -> jax.Array:
    """Scan one packed lane group; returns its [B, Wg] final state."""
    fields: list[str] = []
    for m in members:
        if m.key not in fields:
            fields.append(m.key)
    fidx = {k: i for i, k in enumerate(fields)}

    def cat(attr):
        return jnp.concatenate(
            [getattr(banks[m.key], attr)[m.w_lo:m.w_hi] for m in members])

    init_a, init_u, opt, rep, carry = (
        cat(a) for a in ("init_anchored", "init_unanchored", "opt", "rep",
                         "carry_mask"))
    bts = [banks[m.key].byte_table[:, m.w_lo:m.w_hi] for m in members]
    Wg = sum(m.w_hi - m.w_lo for m in members)
    sel = np.concatenate([
        np.full(m.w_hi - m.w_lo, fidx[m.key], dtype=np.int32)
        for m in members])
    has_carry = any(banks[m.key].has_carry for m in members)
    passes = 1 + max(banks[m.key].extra_passes for m in members)

    feeds = []
    for k in fields:
        d = data[k]
        if d.shape[1] < Lg:
            d = jnp.pad(d, ((0, 0), (0, Lg - d.shape[1])))
        feeds.append(d)
    feed = jnp.stack(feeds, axis=0)  # [F, B, Lg]
    len_stack = jnp.stack(
        [lengths[k].astype(jnp.int32) for k in fields], axis=1)  # [B, F]
    sel_j = jnp.asarray(sel)
    one = jnp.uint32(1)

    def shift_words(x):
        return jnp.pad(x[:, :-1], ((0, 0), (1, 0)))

    def step(S, xs):
        c, t = xs  # c: [F, B] uint8
        bc = jnp.concatenate(
            [jnp.take(bts[i], c[fidx[members[i].key]].astype(jnp.int32),
                      axis=0)
             for i in range(len(members))], axis=1)  # [B, Wg]
        inj = jnp.where(t == 0, init_u | init_a, init_u)
        adv = (S << one) | inj[None, :]
        if has_carry:
            adv = adv | (shift_words((S >> jnp.uint32(31)) & one) & carry)
        for p in range(passes):
            x = (adv & opt) + opt
            adv = adv | (x ^ opt)
            if has_carry and p + 1 < passes:
                esc = (x < opt).astype(jnp.uint32)
                adv = adv | (shift_words(esc) & carry)
        S_new = (adv | (S & rep)) & bc
        live = jnp.take(t < len_stack, sel_j, axis=1)  # [B, Wg]
        return jnp.where(live, S_new, S), None

    xs = (jnp.moveaxis(feed, 2, 0), jnp.arange(Lg, dtype=jnp.int32))
    state, _ = jax.lax.scan(
        step, jnp.zeros((B, Wg), dtype=jnp.uint32), xs,
        unroll=8 if Lg >= 8 else 1)
    return state


def _batch_stacked_states(
    banks: dict[str, NfaTables],
    data: dict[str, jax.Array],
    lengths: dict[str, jax.Array],
) -> dict[str, jax.Array]:
    """Row-stacking fusion: banks whose bucketed L is equal share ONE
    scan over the UNION of their words, with their byte batches
    concatenated along the batch axis. One gather per step (vs one per
    member field in lane-packing) and half the serial steps for two
    same-L fields — the trade is lane waste (each row advances every
    bank's words) against scan-loop latency."""
    from dataclasses import replace

    B = next(iter(data.values())).shape[0]
    by_len: dict[int, list[str]] = {}
    for k in sorted(banks):
        by_len.setdefault(int(data[k].shape[1]), []).append(k)
    out: dict[str, jax.Array] = {}
    for L, keys in by_len.items():
        if len(keys) == 1:
            k = keys[0]
            out[k] = scan_chunk(banks[k], data[k], lengths[k],
                                init_scan_state(B, banks[k].opt.shape[0]), 0)
            continue
        offs = [0]
        for k in keys:
            offs.append(offs[-1] + banks[k].opt.shape[0])

        def cat(attr):
            return jnp.concatenate([getattr(banks[k], attr) for k in keys])

        union = replace(
            banks[keys[0]],
            byte_table=jnp.concatenate(
                [banks[k].byte_table for k in keys], axis=1),
            init_anchored=cat("init_anchored"),
            init_unanchored=cat("init_unanchored"),
            opt=cat("opt"), rep=cat("rep"), carry_mask=cat("carry_mask"),
            sticky=cat("sticky"),
            has_carry=any(banks[k].has_carry for k in keys),
            extra_passes=max(banks[k].extra_passes for k in keys),
            num_words=offs[-1],
        )
        rows = jnp.concatenate([data[k] for k in keys], axis=0)  # [F*B, L]
        lens = jnp.concatenate(
            [lengths[k].astype(jnp.int32) for k in keys])
        # The union table's class-compression fields are stale (they are
        # the first member's); force the raw byte_table lookup here.
        state = scan_chunk(union, rows, lens,
                           init_scan_state(rows.shape[0], offs[-1]), 0,
                           lookup="take")
        for i, k in enumerate(keys):
            out[k] = state[i * B:(i + 1) * B, offs[i]:offs[i + 1]]
    return out


def packed_scan_states(
    banks: dict[str, NfaTables],
    data: dict[str, jax.Array],
    lengths: dict[str, jax.Array],
    mode: str = "length",
) -> dict[str, jax.Array]:
    """Run every bank's scan through packed lane groups; returns each
    bank's final [B, W] state (feed to extract_slots as usual)."""
    if mode == "field" or len(banks) <= 1:
        return {
            k: scan_chunk(t, data[k], lengths[k],
                          init_scan_state(data[k].shape[0], t.opt.shape[0]), 0)
            for k, t in banks.items()
        }
    if mode == "batch":
        return _batch_stacked_states(banks, data, lengths)
    sizes = [(k, int(data[k].shape[1]), banks[k].atoms)
             for k in sorted(banks)]
    groups = pack_scan_groups(sizes, mode)
    B = next(iter(data.values())).shape[0]
    slices: dict[str, dict[int, jax.Array]] = {k: {} for k in banks}
    for Lg, members in groups:
        state = _run_group(banks, data, lengths, B, Lg, members)
        off = 0
        for m in members:
            w = m.w_hi - m.w_lo
            slices[m.key][m.w_lo] = state[:, off:off + w]
            off += w
    out = {}
    for k in banks:
        pieces = [slices[k][lo] for lo in sorted(slices[k])]
        out[k] = pieces[0] if len(pieces) == 1 else jnp.concatenate(
            pieces, axis=1)
    return out
