"""IP / CIDR membership ops.

Lowerings for `client.ip == <ip>` and `lists["..."].contains(client.ip)`
(reference pingoo/lists.rs parses list entries as IpNetwork; docs/
rules.md:110). IPs travel as 4 big-endian uint32 words [B, 4]
(v4 addresses are v6-mapped ::ffff:a.b.c.d, matching Python ipaddress
equivalence used by the interpreter via Ip.contains).

Two lowerings:
  * masked-compare table for small/medium CIDR lists: [B, N] compare.
  * sorted-prefix buckets for large v4 lists (the 1M-entry blocklist in
    BASELINE.md config 3): per distinct prefix length, a sorted uint32
    array searched with jnp.searchsorted (log2 N gathers, HBM-resident).
"""

from __future__ import annotations

import ipaddress
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.values import Ip

V4_PREFIX_OFFSET = 96  # ::ffff:0:0/96


def ip_to_words(ip: Ip) -> tuple[np.ndarray, int]:
    """-> (4 big-endian uint32 words, prefix length in 128-bit space)."""
    if ip.addr is not None:
        packed_int = int(ip.addr)
        version = ip.addr.version
        prefix = 128
    else:
        packed_int = int(ip.net.network_address)
        version = ip.net.version
        prefix = ip.net.prefixlen + (V4_PREFIX_OFFSET if version == 4 else 0)
    if version == 4:
        packed_int |= 0xFFFF << 32  # v6-map
    words = np.array(
        [(packed_int >> shift) & 0xFFFFFFFF for shift in (96, 64, 32, 0)],
        dtype=np.uint32,
    )
    return words, prefix


def encode_ip_batch(ips: list[Ip]) -> np.ndarray:
    """[B, 4] uint32 for a batch of addresses."""
    out = np.zeros((len(ips), 4), dtype=np.uint32)
    for i, ip in enumerate(ips):
        out[i], _ = ip_to_words(ip)
    return out


def _prefix_masks(prefix: int) -> np.ndarray:
    """4 uint32 masks covering the first `prefix` bits of a 128-bit key."""
    masks = np.zeros(4, dtype=np.uint32)
    remaining = prefix
    for w in range(4):
        bits = min(32, max(0, remaining))
        if bits > 0:
            masks[w] = np.uint32(0xFFFFFFFF << (32 - bits) & 0xFFFFFFFF)
        remaining -= 32
    return masks


class CidrTable(NamedTuple):
    """Masked-compare CIDR list (exact, any list size; O(B*N))."""

    nets: jax.Array  # [N, 4] uint32 (pre-masked network words)
    masks: jax.Array  # [N, 4] uint32


def build_cidr_table(entries: list[Ip]) -> CidrTable:
    N = max(len(entries), 1)
    nets = np.zeros((N, 4), dtype=np.uint32)
    masks = np.zeros((N, 4), dtype=np.uint32)
    for i, ip in enumerate(entries):
        words, prefix = ip_to_words(ip)
        m = _prefix_masks(prefix)
        nets[i] = words & m
        masks[i] = m
    if not entries:
        # Unsatisfiable sentinel: net bits outside the mask can never
        # compare equal ((ip & 0) ^ 1 != 0 for every ip).
        masks[:] = 0
        nets[:] = 1
    return CidrTable(jnp.asarray(nets), jnp.asarray(masks))


def cidr_contains(table: CidrTable, ips: jax.Array) -> jax.Array:
    """ips [B, 4] -> [B] bool: ip in any list entry."""
    diff = (ips[:, None, :] & table.masks[None, :, :]) ^ table.nets[None, :, :]
    hit = jnp.all(diff == 0, axis=2)  # [B, N]
    return jnp.any(hit, axis=1)


def cidr_match_one(net_words: np.ndarray, prefix: int, ips: jax.Array) -> jax.Array:
    """Literal `client.ip == "x.y.z.w"` / single-CIDR predicate: [B] bool."""
    masks = jnp.asarray(_prefix_masks(prefix))
    nets = jnp.asarray(net_words) & masks
    diff = (ips & masks[None, :]) ^ nets[None, :]
    return jnp.all(diff == 0, axis=1)


SLOT_BITS = 16  # top-slot fan-out of the bucket index (65536 slots)


class V4PrefixBuckets(NamedTuple):
    """Large-list lowering: per-prefix-length sorted v4 key arrays.

    keys[i] holds entries of bucket i left-justified; bucket_prefix gives
    each bucket's prefix length; bucket_size the live entry count.
    Non-v4 entries go to an auxiliary CidrTable.

    `starts` (optional) indexes each bucket by the top SLOT_BITS bits of
    the key: starts[i, h] = first position in keys[i] whose top bits
    reach h. A probe then binary-searches only its slot's span — the
    serial gather chain drops from log2(N) steps (jnp.searchsorted: 17
    at 131k keys, ~16 us/step on the v5e) to a handful. `span_pad` is a
    DUMMY array whose SHAPE carries the static worst-case slot span in
    bits (shape is trace-static, so it can drive the Python loop count;
    the values are meaningless).
    """

    keys: jax.Array  # [NB, Nmax] uint32 sorted per bucket
    bucket_prefix: jax.Array  # [NB] int32
    bucket_size: jax.Array  # [NB] int32
    aux: CidrTable  # non-v4 (or odd) entries
    starts: jax.Array | None = None  # [NB, 2^SLOT_BITS + 1] int32
    span_pad: jax.Array | None = None  # [max_span.bit_length()] uint8


def index_v4_buckets(
    keys: np.ndarray, bucket_prefix: np.ndarray, bucket_size: np.ndarray,
    aux: CidrTable,
) -> V4PrefixBuckets:
    """Attach the top-bit slot index to raw bucket arrays (keys must be
    sorted per bucket with live entries left-justified)."""
    NB = keys.shape[0]
    nslots = 1 << SLOT_BITS
    starts = np.zeros((NB, nslots + 1), dtype=np.int32)
    max_span = 1
    for i in range(NB):
        size = int(bucket_size[i])
        p = int(bucket_prefix[i])
        live = keys[i, :size].astype(np.uint64)
        his = live >> max(p - SLOT_BITS, 0)
        counts = np.bincount(his.astype(np.int64), minlength=nslots)
        starts[i, 1:] = np.cumsum(counts).astype(np.int32)
        if size:
            max_span = max(max_span, int(counts.max()))
    return V4PrefixBuckets(
        keys=jnp.asarray(keys),
        bucket_prefix=jnp.asarray(bucket_prefix),
        bucket_size=jnp.asarray(bucket_size),
        aux=aux,
        starts=jnp.asarray(starts),
        span_pad=jnp.zeros(int(max_span).bit_length(), dtype=jnp.uint8),
    )


def build_v4_buckets(entries: list[Ip]) -> V4PrefixBuckets:
    by_prefix: dict[int, list[int]] = {}
    aux: list[Ip] = []
    for ip in entries:
        if ip.addr is not None and ip.addr.version == 4:
            by_prefix.setdefault(32, []).append(int(ip.addr))
        elif ip.net is not None and ip.net.version == 4:
            by_prefix.setdefault(ip.net.prefixlen, []).append(
                int(ip.net.network_address)
            )
        else:
            aux.append(ip)
    prefixes = sorted(by_prefix)
    NB = max(len(prefixes), 1)
    Nmax = max((len(v) for v in by_prefix.values()), default=1)
    keys = np.full((NB, Nmax), 0xFFFFFFFF, dtype=np.uint32)
    bucket_prefix = np.zeros(NB, dtype=np.int32)
    bucket_size = np.zeros(NB, dtype=np.int32)
    for i, p in enumerate(prefixes):
        # Keys are right-justified top-p bits: key = addr >> (32 - p).
        vals = sorted({(v >> (32 - p)) if p < 32 else v for v in by_prefix[p]})
        keys[i, : len(vals)] = np.array(vals, dtype=np.uint32)
        bucket_prefix[i] = p
        bucket_size[i] = len(vals)
    return index_v4_buckets(keys, bucket_prefix, bucket_size,
                            build_cidr_table(aux))


def _bucket_key(prefix, v4: jax.Array) -> jax.Array:
    """Probe key for one bucket: the ip's right-justified top-p bits
    (shift-by->=32 for prefix 0 / 32 guarded via explicit selects)."""
    shift = (32 - prefix).astype(jnp.uint32)
    shifted = v4 >> jnp.clip(shift, 1, 31)
    return jnp.where(prefix >= 32, v4,
                     jnp.where(prefix <= 0, jnp.uint32(0), shifted))


def v4_buckets_contains(buckets: V4PrefixBuckets, ips: jax.Array) -> jax.Array:
    """ips [B, 4] (v6-mapped words) -> [B] bool membership."""
    is_v4 = (ips[:, 0] == 0) & (ips[:, 1] == 0) & (ips[:, 2] == 0xFFFF)
    v4 = ips[:, 3]  # [B] uint32

    if buckets.starts is not None:
        # Slot-indexed lower bound: 2 gathers locate the span, then a
        # static span_pad.bit_length-long binary search resolves it.
        steps = buckets.span_pad.shape[0]

        def check_bucket(prefix, size, keys_row, starts_row):
            key = _bucket_key(prefix, v4)
            hi = (key >> jnp.clip(prefix - SLOT_BITS, 0, 31).astype(
                jnp.uint32)).astype(jnp.int32)
            lo = jnp.take(starts_row, hi)
            n = jnp.take(starts_row, hi + 1) - lo
            for _ in range(steps):
                half = n >> 1
                mid = lo + half
                go_right = jnp.take(keys_row, mid) < key
                lo = jnp.where(go_right, mid + 1, lo)
                n = jnp.where(go_right, n - half - 1, half)
            found = (jnp.take(keys_row, jnp.minimum(
                lo, keys_row.shape[0] - 1)) == key) & (lo < size)
            return found  # [B]

        hits = jax.vmap(check_bucket)(
            buckets.bucket_prefix, buckets.bucket_size, buckets.keys,
            buckets.starts,
        )  # [NB, B]
    else:
        def check_bucket_ss(prefix, size, keys_row):
            key = _bucket_key(prefix, v4)
            idx = jnp.searchsorted(keys_row, key)
            idx = jnp.clip(idx, 0, keys_row.shape[0] - 1)
            return (jnp.take(keys_row, idx) == key) & (idx < size)

        hits = jax.vmap(check_bucket_ss)(
            buckets.bucket_prefix, buckets.bucket_size, buckets.keys
        )
    v4_hit = jnp.any(hits, axis=0) & is_v4
    aux_hit = cidr_contains(buckets.aux, ips)
    return v4_hit | aux_hit


class IntBitset(NamedTuple):
    """Dense-ish non-negative int set as an HBM bitset (BASELINE.md
    config 3): one uint32 word gather + bit test per probe — the ASN
    blocklist lowering. int64 never touches the hot path (it is emulated
    on TPU)."""

    bitset: jax.Array  # [ceil(max/32)] uint32


class SortedIntSet(NamedTuple):
    """Sparse / out-of-range int set: sorted array + searchsorted.
    Keys are int32 whenever every value fits, gated by an in-range check
    on the int64 probe lane."""

    keys: jax.Array  # [N] sorted (int32 when values fit, else int64)
    size: jax.Array  # scalar int32


BITSET_MAX_VALUE = 1 << 26  # 8 MB of bits covers the ASN space 16x over


def build_int_set(values: list[int]):
    vals = sorted(set(values))
    if vals and vals[0] >= 0 and vals[-1] < BITSET_MAX_VALUE:
        nwords = (vals[-1] >> 5) + 1
        bits = np.zeros(nwords, dtype=np.uint32)
        arr = np.array(vals, dtype=np.int64)
        np.bitwise_or.at(bits, arr >> 5, np.uint32(1) << (arr & 31).astype(np.uint32))
        return IntBitset(bitset=jnp.asarray(bits))
    fits32 = all(-(2**31) <= v < 2**31 for v in vals)
    dtype = np.int32 if fits32 else np.int64
    N = max(len(vals), 1)
    keys = np.full(N, np.iinfo(dtype).max, dtype=dtype)
    keys[: len(vals)] = np.array(vals, dtype=dtype)
    return SortedIntSet(
        keys=jnp.asarray(keys), size=jnp.asarray(np.int32(len(vals)))
    )


def int_set_contains(table, values: jax.Array) -> jax.Array:
    """values [B] int64 -> [B] bool. `table` is IntBitset or SortedIntSet
    (static structure, so the branch resolves at trace time)."""
    if isinstance(table, IntBitset):
        nbits = table.bitset.shape[0] << 5
        in_range = (values >= 0) & (values < nbits)
        idx = jnp.clip(values, 0, nbits - 1).astype(jnp.int32)
        word = jnp.take(table.bitset, idx >> 5)
        hit = (word >> (idx & 31).astype(jnp.uint32)) & jnp.uint32(1)
        return (hit != 0) & in_range
    if table.keys.dtype == jnp.int32:
        in_range = (values >= -(2**31)) & (values < 2**31)
        probe = jnp.clip(values, -(2**31), 2**31 - 1).astype(jnp.int32)
    else:
        in_range = jnp.ones(values.shape, dtype=bool)
        probe = values
    idx = jnp.searchsorted(table.keys, probe)
    idx = jnp.clip(idx, 0, table.keys.shape[0] - 1)
    return (jnp.take(table.keys, idx) == probe) & (idx < table.size) & in_range
