"""MXU windowed literal matcher — serial-free `contains`/`matches` for
fixed-shape patterns.

Most CRS-style signatures are (case-folded) literals: scanner
user-agents ("sqlmap", "nikto"), keyword `contains` rules ("<?php",
"${jndi:"), generated tokens. The bit-parallel NFA scan handles them,
but it pays one serial VPU step per byte position — ~4 us per 128-lane
tile-step on a v5e regardless of how few patterns ride the bank
(ops/nfa_scan.py). A fixed-length class-sequence needs none of that
machinery: matching it at EVERY window offset simultaneously is a
correlation, and correlations are matmuls — the MXU's home turf.

The trick: a window matches pattern p at offset o iff the weighted sum
of squared NIBBLE differences is zero:

    ssd[b, o, p] = sum_j w[p,j] * ((hi[b,o+j] - hip[p,j])^2
                                   + (lo[b,o+j] - lop[p,j])^2)

with hi = byte >> 4, lo = byte & 15. Expanding the squares turns the
data-dependent parts into ONE correlation of four streams per case
channel (hi^2, lo^2, hi, lo) against per-pattern kernels, lowered by
XLA onto the MXU; the pattern-only term is a constant. The nibble
split is what makes this exact at the TPU's DEFAULT precision: every
stream value is <= 225 and every kernel value is <= 30 — all integers
with <= 8 significant bits, bf16-representable — and bf16 x bf16
products accumulate exactly in f32 (16-bit products, sums < 2^24).
A whole-byte SSD would need byte^2 terms up to 65025 in the conv
INPUT, which bf16 cannot represent: that variant verifiably misfires
on a real v5e while passing on CPU. (Precision.HIGHEST also fixes it,
but costs ~3x the conv time for the same answer.)

Eight input channels carry the raw and ASCII-lowercased streams; each
pattern POSITION weights exactly one case channel (raw for
case-sensitive positions, folded for case-insensitive ones) or
neither (truly-any positions), so one conv serves any mix of case
sensitivity. Which patterns qualify is the compiler's call
(compiler/repat.py to_window): no anchors/boundaries, all positions
single-byte after optional folding (or any-byte), leading/trailing
optional runs stripped (sound for search semantics: an unanchored
pattern matches iff its mandatory core matches).

Replaces per-request Rust regex execution for these rules (reference
pingoo/rules.rs:37-51 via the bel `matches`/`contains` functions,
docs/rules.md:71-76) with one batched conv pair per field.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

RAW, FOLD, ANY = 0, 1, 2  # per-position channel codes (ANY: no channel)


class WindowPattern(NamedTuple):
    """One fixed-length window pattern: per-position (channel, byte)."""

    positions: tuple[tuple[int, int], ...]  # (RAW/FOLD/ANY, byte)


class WindowTable(NamedTuple):
    """Device tables for one field's window-pattern group.

    Kernel channel layout (4 per case channel, raw then folded):
    [hi^2, lo^2, hi, lo] weights — see the module docstring's
    expansion of the nibble SSD.
    """

    kernel: jax.Array  # [P, 8, M] f32
    const: jax.Array  # [P] f32: sum of w * (hip^2 + lop^2)
    min_len: jax.Array  # [P] int32 pattern length (windows must fit)


def build_window_table(patterns: list[WindowPattern]) -> WindowTable:
    P = max(len(patterns), 1)
    M = max((len(p.positions) for p in patterns), default=1)
    M = max(M, 1)
    kernel = np.zeros((P, 8, M), dtype=np.float32)
    const = np.zeros(P, dtype=np.float32)
    min_len = np.zeros(P, dtype=np.int32)
    if not patterns:
        # Dead table: an impossible min_len keeps the one pad pattern
        # from ever matching.
        min_len[0] = 1 << 20
    for i, pat in enumerate(patterns):
        min_len[i] = len(pat.positions)
        for j, (chan, b) in enumerate(pat.positions):
            if chan == ANY:
                continue
            hp, lp = b >> 4, b & 15
            base = 4 * chan
            kernel[i, base + 0, j] = 1.0  # x hi^2
            kernel[i, base + 1, j] = 1.0  # x lo^2
            kernel[i, base + 2, j] = -2.0 * hp  # x hi
            kernel[i, base + 3, j] = -2.0 * lp  # x lo
            const[i] += float(hp * hp + lp * lp)
    return WindowTable(
        kernel=jnp.asarray(kernel),
        const=jnp.asarray(const),
        min_len=jnp.asarray(min_len),
    )


def _fold_lower(x: jax.Array) -> jax.Array:
    is_upper = (x >= 0x41) & (x <= 0x5A)
    return jnp.where(is_upper, x + 0x20, x)


def window_hits(table: WindowTable, data: jax.Array,
                lengths: jax.Array) -> jax.Array:
    """data [B, L] uint8 (zero-padded), lengths [B] -> hits [B, P] bool.

    hit[b, p] = exists o: data[b, o : o + m_p] matches pattern p and
    o + m_p <= lengths[b]. Zero-length patterns match everything
    (min_len 0 admits o = 0 for every row).
    """
    B, L = data.shape
    P, _, M = table.kernel.shape
    folded = _fold_lower(data)

    def nibble_streams(d):
        hi = (d >> 4).astype(jnp.float32)
        lo = (d & 15).astype(jnp.float32)
        return [hi * hi, lo * lo, hi, lo]

    x = jnp.stack(nibble_streams(data) + nibble_streams(folded),
                  axis=1)  # [B, 8, L]
    x = jnp.pad(x, ((0, 0), (0, 0), (0, M)))  # windows may start at L-1
    dn = ("NCH", "OIH", "NCH")  # 1-D conv: batch/channel/spatial
    # Default precision is exact here BY CONSTRUCTION (nibble streams;
    # see module docstring) — do not "optimize" the streams back to
    # whole bytes without restoring Precision.HIGHEST.
    ssd = jax.lax.conv_general_dilated(
        x, table.kernel, window_strides=(1,), padding="VALID",
        dimension_numbers=dn) + table.const[None, :, None]  # [B, P, O]
    O = ssd.shape[2]
    offs = jnp.arange(O, dtype=jnp.int32)
    fits = (offs[None, None, :] + table.min_len[None, :, None]
            <= lengths.astype(jnp.int32)[:, None, None])
    return ((ssd == 0.0) & fits).any(axis=2)  # [B, P]
