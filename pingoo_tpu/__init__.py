"""pingoo-tpu: a TPU-native edge-security framework.

A from-scratch rebuild of the capabilities of pingooio/pingoo (reference:
/root/reference) — load balancer / API gateway / reverse proxy with a
WAF/bot-protection rules engine — designed TPU-first: the per-request rule
evaluation (reference: pingoo/rules.rs:37-51, pingoo/listeners/
http_listener.rs:251-264) is lifted into batched JAX/XLA/Pallas kernels that
score thousands of buffered requests at once, with IP/ASN blocklists as
on-HBM bitsets (reference: pingoo/lists.rs) and a vectorized bot-score head
(reference: pingoo/captcha.rs).

Layout:
  expr/     — the rule expression language (CEL subset compatible with the
              reference's `bel` crate surface, docs/rules.md) + CPU
              interpreter (the parity oracle)
  compiler/ — rule AST -> typed predicate IR -> TPU lowering (pattern
              tables, bit-parallel NFAs, bitsets, boolean programs)
  ops/      — the JAX/Pallas device ops (byte-tensor matching, NFA scan,
              CIDR/bitset membership)
  engine/   — batched verdict engine: request encoding, jitted verdict
              step, adaptive batching service
  parallel/ — device mesh, dp/tp/sp shardings, ring sequence scan
  config/   — YAML config loading/validation (reference: pingoo/config/)
  host/     — host data plane: listeners, proxy services, discovery, TLS,
              captcha/JWT, GeoIP (reference: pingoo/listeners, services,
              service_discovery, tls, captcha.rs, geoip.rs)
  models/   — learned components (bot-score head)
"""

__version__ = "0.1.0"
