"""TLS manager: certificate store, SNI dispatch, self-signed default.

Reference parity (pingoo/tls/tls_manager.rs, certificate.rs): load
`*.pem`/`*.key` pairs from the TLS folder (/etc/pingoo/tls), index
certificates by SAN including wildcard SANs (tls_manager.rs:105-128 SNI
resolver), generate a self-signed default certificate for `*` on first
boot (tls_manager.rs:193-231, certificate.rs:146-192), TLS 1.3-only
(tls_manager.rs:95). Python's ssl module handles the handshake; SNI
dispatch uses `SSLContext.sni_callback` swapping per-domain contexts.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import Optional

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

DEFAULT_TLS_DIR = "/etc/pingoo/tls"
DEFAULT_CERT_NAME = "default.pingoo"


class TlsError(Exception):
    pass


def generate_self_signed(
    domains: list[str], valid_days: int = 3650
) -> tuple[bytes, bytes]:
    """-> (cert_pem, key_pem) (reference certificate.rs:146-192 rcgen)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, domains[0] if domains else "*")])
    sans = []
    for d in domains:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(d)))
        except ValueError:
            sans.append(x509.DNSName(d))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=valid_days))
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


def cert_sans(cert_pem: bytes) -> list[str]:
    """SAN DNS names of a PEM certificate (certificate.rs:74-144)."""
    cert = x509.load_pem_x509_certificate(cert_pem)
    try:
        ext = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName)
    except x509.ExtensionNotFound:
        return []
    names = [n.lower() for n in ext.value.get_values_for_type(x509.DNSName)]
    names += [str(ip) for ip in ext.value.get_values_for_type(x509.IPAddress)]
    return names


def _make_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_3  # TLS 1.3-only
    ctx.load_cert_chain(cert_path, key_path)
    # Advertise h2 + http/1.1 like the reference's hyper auto builder
    # (http_listener.rs:276-278); the listener dispatches on the
    # negotiated protocol. Skipped when libnghttp2 is absent.
    try:
        from .h2 import available as h2_available

        ctx.set_alpn_protocols(
            ["h2", "http/1.1"] if h2_available() else ["http/1.1"])
    except (ImportError, NotImplementedError):
        pass
    return ctx


class TlsManager:
    """Cert store + SNI resolver (reference TlsManager)."""

    def __init__(self, tls_dir: str = DEFAULT_TLS_DIR,
                 create_default: bool = True):
        self.tls_dir = tls_dir
        self._by_domain: dict[str, ssl.SSLContext] = {}
        self._wildcards: dict[str, ssl.SSLContext] = {}  # "*.example.com"
        self._default: Optional[ssl.SSLContext] = None
        os.makedirs(tls_dir, exist_ok=True)
        self._load_all()
        if self._default is None and create_default:
            self._create_default()

    def _load_all(self) -> None:
        for fname in sorted(os.listdir(self.tls_dir)):
            if not fname.endswith(".pem"):
                continue
            base = fname[:-4]
            cert_path = os.path.join(self.tls_dir, fname)
            key_path = os.path.join(self.tls_dir, base + ".key")
            if not os.path.exists(key_path):
                continue
            try:
                self.add_certificate(cert_path, key_path)
            except (ssl.SSLError, ValueError, TlsError):
                continue

    def add_certificate(self, cert_path: str, key_path: str) -> None:
        with open(cert_path, "rb") as f:
            cert_pem = f.read()
        ctx = _make_context(cert_path, key_path)
        domains = cert_sans(cert_pem)
        if not domains:
            raise TlsError(f"{cert_path}: certificate has no SANs")
        for domain in domains:
            if domain == "*":
                self._default = ctx
            elif domain.startswith("*."):
                self._wildcards[domain[2:]] = ctx
            else:
                self._by_domain[domain] = ctx

    def _create_default(self) -> None:
        cert_pem, key_pem = generate_self_signed(["*"])
        cert_path = os.path.join(self.tls_dir, DEFAULT_CERT_NAME + ".pem")
        key_path = os.path.join(self.tls_dir, DEFAULT_CERT_NAME + ".key")
        with open(cert_path, "wb") as f:
            f.write(cert_pem)
        with open(key_path, "wb") as f:
            f.write(key_pem)
        self._default = _make_context(cert_path, key_path)

    # -- SNI dispatch (tls_manager.rs:105-128) -------------------------------

    def resolve(self, server_name: Optional[str]) -> Optional[ssl.SSLContext]:
        if server_name:
            name = server_name.lower()
            ctx = self._by_domain.get(name)
            if ctx is not None:
                return ctx
            parent = name.split(".", 1)[-1] if "." in name else None
            if parent and parent in self._wildcards:
                return self._wildcards[parent]
        return self._default

    def server_context(self) -> ssl.SSLContext:
        """The listener-facing context with SNI-based swapping."""
        base = self._default or next(
            iter(self._by_domain.values()), None)
        if base is None:
            raise TlsError("no certificates available")

        def sni_callback(sock, server_name, _ctx):
            resolved = self.resolve(server_name)
            if resolved is not None:
                sock.context = resolved
            return None

        base.sni_callback = sni_callback
        return base
