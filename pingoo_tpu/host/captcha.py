"""Proof-of-work captcha + bot gate.

Reference parity (pingoo/captcha.rs):
  * cookies `__pingoo_captcha` (challenge JWT, 10 min) and
    `__pingoo_captcha_verified` (24 h) signed EdDSA, issuer "pingoo"
    (captcha.rs:22-30); 5s JWT drift tolerance.
  * client id = base64url(SHA256(ip || user_agent || host))
    (captcha.rs:409-421), compared constant-time (crypto_utils.rs:3-5).
  * /__pingoo/captcha/api/init issues a 32-byte base64url challenge at
    difficulty 1 (captcha.rs:195-239).
  * /__pingoo/captcha/api/verify recomputes SHA-256(challenge || nonce),
    requires `difficulty` leading '0' hex chars, constant-time client-id
    match, then issues the verified cookie (captcha.rs:241-385).
  * Ed25519 signing key persisted as a JWKS at
    /etc/pingoo/captcha_jwks.json, auto-generated on first boot
    (captcha.rs:78-123).

The embedded frontend (reference: Preact+vite app embedded in the
binary, captcha/captcha.rs) is a single self-contained HTML page using
WebCrypto for the PoW loop.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import time
from typing import Optional

from . import jwt as jose

CAPTCHA_COOKIE = "__pingoo_captcha"
CAPTCHA_VERIFIED_COOKIE = "__pingoo_captcha_verified"
CAPTCHA_JWT_ISSUER = "pingoo"
CAPTCHA_VERIFIED_JWT_EXPIRATION_S = 24 * 3600
CAPTCHA_JWT_EXPIRATION_S = 600
PROOF_OF_WORK_DIFFICULTY = 1
JWT_DRIFT_S = 5
DEFAULT_JWKS_PATH = "/etc/pingoo/captcha_jwks.json"
CAPTCHA_PATH_PREFIX = "/__pingoo/captcha"


def generate_captcha_client_id(ip: str, user_agent: str, host: str) -> str:
    """base64url(SHA256(ip || ua || host)) (captcha.rs:409-421)."""
    digest = hashlib.sha256(
        ip.encode() + user_agent.encode("utf-8", "replace") + host.encode()
    ).digest()
    return base64.urlsafe_b64encode(digest).rstrip(b"=").decode()


class CaptchaManager:
    def __init__(self, jwks_path: str = DEFAULT_JWKS_PATH):
        self.jwks_path = jwks_path
        self.key = self._load_or_create_key()

    def _load_or_create_key(self) -> jose.Key:
        try:
            with open(self.jwks_path, "r", encoding="utf-8") as f:
                jwks = jose.Jwks.from_json(f.read())
            for key in jwks.keys:
                if key.algorithm == jose.ALG_EDDSA and key.private is not None:
                    return key
        except (OSError, jose.JwtError):
            pass
        key = jose.Key.generate(jose.ALG_EDDSA, kid=secrets.token_hex(8))
        try:
            os.makedirs(os.path.dirname(self.jwks_path) or ".", exist_ok=True)
            with open(self.jwks_path, "w", encoding="utf-8") as f:
                f.write(jose.Jwks(keys=[key]).to_json(include_private=True))
        except OSError:
            pass  # ephemeral key; still serviceable
        return key

    # -- verified-gate check (listener hot path) -----------------------------

    def is_verified(self, cookie_value: Optional[str], client_id: str) -> bool:
        """Check the __pingoo_captcha_verified cookie
        (captcha.rs:125-152, called from http_listener.rs:222-236)."""
        if not cookie_value:
            return False
        try:
            claims = jose.parse_and_verify(
                cookie_value, self.key, issuer=CAPTCHA_JWT_ISSUER,
                drift_tolerance_s=JWT_DRIFT_S)
        except jose.JwtError:
            return False
        return bool(claims.get("challenge_passed")) and hmac.compare_digest(
            str(claims.get("client_id", "")), client_id)

    # -- endpoints -----------------------------------------------------------

    def init_challenge(self, client_id: str) -> tuple[dict, str]:
        """-> (response body, Set-Cookie value) (captcha.rs:195-239)."""
        challenge = base64.urlsafe_b64encode(
            secrets.token_bytes(32)).rstrip(b"=").decode()
        now = int(time.time())
        token = jose.sign(self.key, {
            "iss": CAPTCHA_JWT_ISSUER,
            "iat": now,
            "exp": now + CAPTCHA_JWT_EXPIRATION_S,
            "client_id": client_id,
            "challenge": challenge,
            "difficulty": PROOF_OF_WORK_DIFFICULTY,
        })
        body = {"challenge": challenge, "difficulty": PROOF_OF_WORK_DIFFICULTY}
        cookie = (
            f"{CAPTCHA_COOKIE}={token}; Max-Age={CAPTCHA_JWT_EXPIRATION_S}; "
            "Path=/; HttpOnly; SameSite=Lax")
        return body, cookie

    def verify_challenge(
        self, body: dict, cookie_value: Optional[str], client_id: str
    ) -> tuple[bool, Optional[str]]:
        """-> (ok, Set-Cookie for verified token) (captcha.rs:241-385)."""
        if not cookie_value:
            return False, None
        try:
            claims = jose.parse_and_verify(
                cookie_value, self.key, issuer=CAPTCHA_JWT_ISSUER,
                drift_tolerance_s=JWT_DRIFT_S)
        except jose.JwtError:
            return False, None
        if not hmac.compare_digest(str(claims.get("client_id", "")), client_id):
            return False, None
        challenge = str(claims.get("challenge", ""))
        difficulty = int(claims.get("difficulty", PROOF_OF_WORK_DIFFICULTY))
        nonce = body.get("nonce")
        given_hash = str(body.get("hash", "")).lower()
        if not isinstance(nonce, str) or not challenge:
            return False, None
        digest = hashlib.sha256(
            challenge.encode() + nonce.encode()).hexdigest()
        # leading-zero check (captcha.rs:311-321) + exact hash match
        leading = len(digest) - len(digest.lstrip("0"))
        if leading < difficulty:
            return False, None
        if not hmac.compare_digest(digest, given_hash):
            return False, None
        now = int(time.time())
        token = jose.sign(self.key, {
            "iss": CAPTCHA_JWT_ISSUER,
            "iat": now,
            "exp": now + CAPTCHA_VERIFIED_JWT_EXPIRATION_S,
            "client_id": client_id,
            "challenge_passed": True,
        })
        cookie = (
            f"{CAPTCHA_VERIFIED_COOKIE}={token}; "
            f"Max-Age={CAPTCHA_VERIFIED_JWT_EXPIRATION_S}; "
            "Path=/; HttpOnly; SameSite=Lax")
        return True, cookie

    # -- request router (reference serve_captcha_request) --------------------

    def serve(self, method: str, path: str, body: bytes,
              cookies: dict[str, str], client_id: str):
        """Handle /__pingoo/captcha* -> (status, headers, body bytes)."""
        sub = path[len(CAPTCHA_PATH_PREFIX):] or "/"
        if sub in ("", "/") and method == "GET":
            return 200, [("content-type", "text/html; charset=utf-8")], \
                CAPTCHA_PAGE.encode()
        if sub == "/assets/index.js" and method == "GET":
            # The frontend's script asset (the reference serves its vite
            # bundle under /assets, captcha.rs serve_asset).
            from .captcha_frontend import APP_JS

            return 200, [("content-type", "text/javascript"),
                         ("cache-control",
                          "public, no-cache, must-revalidate")], \
                APP_JS.encode()
        # The reference routes /api/init by path only (captcha.rs:167) —
        # its frontend fetches it with GET; POST kept for existing
        # clients of this implementation.
        if sub == "/api/init" and method in ("GET", "POST"):
            payload, cookie = self.init_challenge(client_id)
            return 200, [("content-type", "application/json"),
                         ("set-cookie", cookie)], json.dumps(payload).encode()
        if sub == "/api/verify" and method == "POST":
            try:
                parsed = json.loads(body.decode("utf-8") or "{}")
            except ValueError:
                parsed = {}
            ok, cookie = self.verify_challenge(
                parsed, cookies.get(CAPTCHA_COOKIE), client_id)
            headers = [("content-type", "application/json")]
            if ok and cookie:
                headers.append(("set-cookie", cookie))
            return (200 if ok else 403), headers, json.dumps(
                {"ok": ok}).encode()
        return 404, [("content-type", "text/plain")], b"not found"


# The challenge frontend: built-app parity with the reference's
# Preact/vite bundle (see host/captcha_frontend.py for the derivation).
from .captcha_frontend import INDEX_HTML as CAPTCHA_PAGE  # noqa: E402
