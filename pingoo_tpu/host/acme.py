"""ACME (RFC 8555) automatic TLS certificates.

Reference parity (pingoo/tls/acme.rs): Let's Encrypt production
directory by default (acme.rs:29); a background loop every 6 h orders
certificates for configured domains that are missing or expiring within
30 days (acme.rs:67-178); the account (ES256 key + registration URL) is
persisted to `<tls_dir>/acme.json` as a versioned document
(AcmeConfig::V1, acme.rs:32-58,308-371); issued certificates are
hot-inserted into the TlsManager and written next to the other certs
with retries (acme.rs:124-169).

Challenge types: tls-alpn-01 (the reference's only type, acme.rs:180-242)
when an `alpn_dir` is configured — the ephemeral challenge certificate
(RFC 8737: SAN = domain, critical acmeIdentifier extension carrying
SHA256(key authorization)) is written as `<domain>.pem/.key` into the
dir the native TLS transport answers `acme-tls/1` handshakes from
(native/httpd.cc client_hello_cb; Python's ssl layer cannot select a
certificate by client ALPN, which is why this rides the C++ plane).
Fallback: http-01 — the HTTP listener serves
/.well-known/acme-challenge/<token> from `AcmeManager.challenges`.
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import json
import os
import time
from typing import Optional

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from ..logging_utils import get_logger
from . import jwt as jose

log = get_logger(__name__)

LETSENCRYPT_PRODUCTION_URL = "https://acme-v02.api.letsencrypt.org/directory"
RENEW_BEFORE_DAYS = 30
LOOP_INTERVAL_S = 6 * 3600
PERSIST_RETRIES = 5
PERSIST_RETRY_DELAY_S = 5.0
HTTP01_PATH_PREFIX = "/.well-known/acme-challenge/"


ACME_IDENTIFIER_OID = x509.ObjectIdentifier("1.3.6.1.5.5.7.1.31")


class AcmeError(Exception):
    pass


def make_tls_alpn_challenge_cert(domain: str,
                                 keyauth: str) -> tuple[bytes, bytes]:
    """RFC 8737 §3 challenge certificate: self-signed, SAN = [domain],
    critical id-pe-acmeIdentifier extension = DER OCTET STRING of
    SHA256(key authorization) (reference acme.rs:208-242)."""
    import hashlib

    digest = hashlib.sha256(keyauth.encode("ascii")).digest()
    acme_ext = x509.UnrecognizedExtension(
        ACME_IDENTIFIER_OID, b"\x04\x20" + digest)
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, domain)])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .public_key(key.public_key())
        .add_extension(x509.SubjectAlternativeName(
            [x509.DNSName(domain)]), critical=False)
        .add_extension(acme_ext, critical=True)
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    return cert_pem, key_pem


class AcmeClient:
    """One account against one directory."""

    def __init__(self, directory_url: str, account_key: jose.Key,
                 kid: Optional[str] = None, session=None):
        self.directory_url = directory_url
        self.key = account_key
        self.kid = kid  # account URL once registered
        self._session = session
        self._directory: Optional[dict] = None
        self._nonce: Optional[str] = None

    async def _http(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()

    async def directory(self) -> dict:
        if self._directory is None:
            session = await self._http()
            async with session.get(self.directory_url) as resp:
                if resp.status != 200:
                    raise AcmeError(f"directory fetch: {resp.status}")
                self._directory = await resp.json()
        return self._directory

    async def _new_nonce(self) -> str:
        directory = await self.directory()
        session = await self._http()
        async with session.head(directory["newNonce"]) as resp:
            nonce = resp.headers.get("Replay-Nonce")
            if not nonce:
                raise AcmeError("no Replay-Nonce")
            return nonce

    async def _post(self, url: str, payload: Optional[dict],
                    use_jwk: bool = False) -> tuple[int, dict, dict]:
        """Signed JWS POST (flattened JSON). payload None -> POST-as-GET."""
        if self._nonce is None:
            self._nonce = await self._new_nonce()
        protected = {"alg": "ES256", "nonce": self._nonce, "url": url}
        if use_jwk or self.kid is None:
            protected["jwk"] = {
                k: v for k, v in self.key.to_jwk().items()
                if k in ("kty", "crv", "x", "y")}
        else:
            protected["kid"] = self.kid
        protected_b64 = jose.b64url_encode(
            json.dumps(protected, separators=(",", ":")).encode())
        payload_b64 = ("" if payload is None else jose.b64url_encode(
            json.dumps(payload, separators=(",", ":")).encode()))
        signature = self.key.sign(
            (protected_b64 + "." + payload_b64).encode("ascii"))
        body = json.dumps({
            "protected": protected_b64,
            "payload": payload_b64,
            "signature": jose.b64url_encode(signature),
        })
        session = await self._http()
        async with session.post(
            url, data=body,
            headers={"content-type": "application/jose+json"},
        ) as resp:
            self._nonce = resp.headers.get("Replay-Nonce")
            headers = dict(resp.headers)
            try:
                data = await resp.json()
            except Exception:
                data = {"raw": await resp.text()}
            return resp.status, headers, data

    # -- account / order flow ------------------------------------------------

    async def register(self) -> str:
        directory = await self.directory()
        status, headers, data = await self._post(
            directory["newAccount"],
            {"termsOfServiceAgreed": True}, use_jwk=True)
        if status not in (200, 201):
            raise AcmeError(f"newAccount: {status} {data}")
        self.kid = headers.get("Location")
        if not self.kid:
            raise AcmeError("newAccount: no Location")
        return self.kid

    async def order_certificate(self, domains: list[str],
                                challenges: dict[str, str],
                                poll_interval_s: float = 1.0,
                                poll_tries: int = 30,
                                alpn_dir: Optional[str] = None
                                ) -> tuple[bytes, bytes]:
        """-> (cert_pem_chain, key_pem).

        With `alpn_dir` set, validates via tls-alpn-01 (the reference's
        only type, acme.rs:180-242): the RFC 8737 challenge certificate
        is written as <domain>.pem/.key for the native TLS transport to
        answer at accept time. Otherwise http-01: key authorizations are
        published into `challenges` (token -> keyauth) for the HTTP
        listener. (reference order_certificate, acme.rs:245-306.)
        """
        directory = await self.directory()
        status, headers, order = await self._post(
            directory["newOrder"],
            {"identifiers": [{"type": "dns", "value": d} for d in domains]})
        if status not in (200, 201):
            raise AcmeError(f"newOrder: {status} {order}")
        order_url = headers.get("Location", "")

        want_type = "tls-alpn-01" if alpn_dir else "http-01"
        thumbprint = jose.jwk_thumbprint(self.key)
        published: list[str] = []
        staged_files: list[str] = []
        try:
            for authz_url in order.get("authorizations", []):
                status, _, authz = await self._post(authz_url, None)
                if status != 200:
                    raise AcmeError(f"authz: {status}")
                if authz.get("status") == "valid":
                    continue
                challenge = next(
                    (c for c in authz.get("challenges", [])
                     if c.get("type") == want_type), None)
                if challenge is None:
                    raise AcmeError(f"no {want_type} challenge offered")
                token = challenge["token"]
                keyauth = f"{token}.{thumbprint}"
                if alpn_dir:
                    domain = authz.get("identifier", {}).get(
                        "value", domains[0])
                    cert_pem, key_pem = make_tls_alpn_challenge_cert(
                        domain, keyauth)
                    os.makedirs(alpn_dir, exist_ok=True)
                    cert_path = os.path.join(alpn_dir, domain + ".pem")
                    key_path = os.path.join(alpn_dir, domain + ".key")
                    fd = os.open(key_path,
                                 os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                                 0o600)
                    # O_CREAT's mode only applies to NEW files; clamp a
                    # pre-existing key file's mode too (cert renewals).
                    os.fchmod(fd, 0o600)
                    with os.fdopen(fd, "wb") as f:
                        f.write(key_pem)
                    with open(cert_path, "wb") as f:
                        f.write(cert_pem)
                    staged_files += [cert_path, key_path]
                else:
                    challenges[token] = keyauth
                    published.append(token)
                status, _, _ = await self._post(challenge["url"], {})
                if status not in (200, 202):
                    raise AcmeError(f"challenge ready: {status}")
                for _ in range(poll_tries):
                    status, _, authz = await self._post(authz_url, None)
                    if authz.get("status") == "valid":
                        break
                    if authz.get("status") == "invalid":
                        raise AcmeError(f"authorization failed: {authz}")
                    await asyncio.sleep(poll_interval_s)
                else:
                    raise AcmeError("authorization timed out")

            key = ec.generate_private_key(ec.SECP256R1())
            csr = (
                x509.CertificateSigningRequestBuilder()
                .subject_name(x509.Name(
                    [x509.NameAttribute(NameOID.COMMON_NAME, domains[0])]))
                .add_extension(x509.SubjectAlternativeName(
                    [x509.DNSName(d) for d in domains]), critical=False)
                .sign(key, hashes.SHA256())
            )
            csr_b64 = jose.b64url_encode(
                csr.public_bytes(serialization.Encoding.DER))
            status, _, order = await self._post(
                order["finalize"], {"csr": csr_b64})
            if status not in (200, 202):
                raise AcmeError(f"finalize: {status} {order}")
            for _ in range(poll_tries):
                if order.get("status") == "valid" and order.get("certificate"):
                    break
                if order.get("status") == "invalid":
                    raise AcmeError(f"order failed: {order}")
                await asyncio.sleep(poll_interval_s)
                status, _, order = await self._post(order_url, None)
            cert_url = order.get("certificate")
            if not cert_url:
                raise AcmeError("order never became valid")
            status, _, cert_doc = await self._post(cert_url, None)
            if status != 200:
                raise AcmeError(f"certificate download: {status}")
            cert_pem = cert_doc.get("raw", "").encode()
            key_pem = key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption())
            return cert_pem, key_pem
        finally:
            for token in published:
                challenges.pop(token, None)
            for path in staged_files:  # challenge certs are ephemeral
                try:
                    os.unlink(path)
                except OSError:
                    pass


class AcmeManager:
    """Account persistence + renewal loop + challenge store."""

    def __init__(self, tls_dir: str, domains: list[str],
                 directory_url: str = LETSENCRYPT_PRODUCTION_URL,
                 tls_manager=None, alpn_dir: Optional[str] = None):
        self.tls_dir = tls_dir
        self.domains = list(domains)
        self.directory_url = directory_url
        self.tls_manager = tls_manager
        # tls-alpn-01 challenge-cert dir (native TLS transport answers
        # from it); None -> http-01 via `challenges`.
        self.alpn_dir = alpn_dir
        self.challenges: dict[str, str] = {}  # token -> key authorization
        self._task: Optional[asyncio.Task] = None
        self.client = AcmeClient(directory_url, *self._load_account())

    # -- account persistence (acme.rs:308-371, AcmeConfig::V1) ---------------

    def _account_path(self) -> str:
        return os.path.join(self.tls_dir, "acme.json")

    def _load_account(self) -> tuple[jose.Key, Optional[str]]:
        try:
            with open(self._account_path(), "r", encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("version") == 1 and doc.get("directory_url") == \
                    self.directory_url:
                der = base64.b64decode(doc["private_key"])
                priv = serialization.load_der_private_key(der, None)
                key = jose.Key(jose.ALG_ES256, private=priv,
                               public=priv.public_key())
                return key, doc.get("account_url")
        except (OSError, ValueError, KeyError):
            pass
        return jose.Key.generate(jose.ALG_ES256), None

    def _persist_account(self) -> None:
        der = self.client.key.private.private_bytes(
            serialization.Encoding.DER,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())
        doc = {
            "version": 1,
            "directory_url": self.directory_url,
            "account_url": self.client.kid,
            "private_key": base64.b64encode(der).decode(),
        }
        os.makedirs(self.tls_dir, exist_ok=True)
        with open(self._account_path(), "w", encoding="utf-8") as f:
            json.dump(doc, f)

    # -- renewal loop (acme.rs:67-178) ---------------------------------------

    async def start_in_background(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self.client.close()

    def domains_needing_certificates(self, now=None) -> list[str]:
        now = now or datetime.datetime.now(datetime.timezone.utc)
        out = []
        for domain in self.domains:
            cert_path = os.path.join(self.tls_dir, domain + ".pem")
            if not os.path.exists(cert_path):
                out.append(domain)
                continue
            try:
                with open(cert_path, "rb") as f:
                    cert = x509.load_pem_x509_certificate(f.read())
                expiry = cert.not_valid_after_utc
            except (ValueError, OSError):
                out.append(domain)
                continue
            if expiry - now < datetime.timedelta(days=RENEW_BEFORE_DAYS):
                out.append(domain)
        return out

    async def _loop(self) -> None:
        while True:
            try:
                await self.renew_all()
            except Exception as exc:
                log.warning(f"acme: renewal pass failed: {exc}")
            await asyncio.sleep(LOOP_INTERVAL_S)

    async def renew_all(self) -> None:
        needed = self.domains_needing_certificates()
        if not needed:
            return
        if self.client.kid is None:
            await self.client.register()
            self._persist_account()
        for domain in needed:
            try:
                cert_pem, key_pem = await self.client.order_certificate(
                    [domain], self.challenges, alpn_dir=self.alpn_dir)
                await self._install(domain, cert_pem, key_pem)
                log.info("acme: certificate issued",
                         extra={"fields": {"domain": domain}})
            except AcmeError as exc:
                log.warning(f"acme: {domain}: {exc}")

    async def _install(self, domain: str, cert_pem: bytes,
                       key_pem: bytes) -> None:
        cert_path = os.path.join(self.tls_dir, domain + ".pem")
        key_path = os.path.join(self.tls_dir, domain + ".key")
        for attempt in range(PERSIST_RETRIES):
            try:
                with open(key_path, "wb") as f:
                    f.write(key_pem)
                with open(cert_path, "wb") as f:
                    f.write(cert_pem)
                break
            except OSError:
                await asyncio.sleep(PERSIST_RETRY_DELAY_S)
        if self.tls_manager is not None:
            self.tls_manager.add_certificate(cert_path, key_path)
