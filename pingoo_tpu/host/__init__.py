"""Host data plane: listeners, services, discovery, TLS, captcha, geoip.

Python asyncio implementation of the reference's Rust data plane
(pingoo/listeners, services, service_discovery, tls, captcha.rs,
geoip.rs); the C++ native plane (pingoo_tpu/native) carries the
shared-memory ring and high-throughput listener.
"""

# Lazy attribute resolution (PEP 562): several submodules need optional
# packages (`cryptography` for tlsmgr/acme x509, zstd for geoip blobs) —
# importing `pingoo_tpu.host.services` for e.g. route matching must not
# drag those in. Each public name resolves to its submodule on first
# access; a missing optional dependency surfaces where it is USED.
_EXPORTS = {
    "CaptchaManager": "captcha",
    "generate_captcha_client_id": "captcha",
    "ServiceRegistry": "discovery",
    "GeoipDB": "geoip",
    "GeoipRecord": "geoip",
    "HttpListener": "httpd",
    "Request": "httpd",
    "Server": "server",
    "run": "server",
    "HttpProxyService": "services",
    "StaticSiteService": "services",
    "TcpProxyService": "services",
    "build_http_services": "services",
    "TlsManager": "tlsmgr",
    "generate_self_signed": "tlsmgr",
}


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        val = getattr(mod, name)
        globals()[name] = val  # cache for subsequent lookups
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "CaptchaManager",
    "GeoipDB",
    "GeoipRecord",
    "HttpListener",
    "HttpProxyService",
    "Request",
    "Server",
    "ServiceRegistry",
    "StaticSiteService",
    "TcpProxyService",
    "TlsManager",
    "build_http_services",
    "generate_self_signed",
    "generate_captcha_client_id",
    "run",
]
