"""Host data plane: listeners, services, discovery, TLS, captcha, geoip.

Python asyncio implementation of the reference's Rust data plane
(pingoo/listeners, services, service_discovery, tls, captcha.rs,
geoip.rs); the C++ native plane (pingoo_tpu/native) carries the
shared-memory ring and high-throughput listener.
"""

from .captcha import CaptchaManager, generate_captcha_client_id
from .discovery import ServiceRegistry
from .geoip import GeoipDB, GeoipRecord
from .httpd import HttpListener, Request
from .server import Server, run
from .services import (
    HttpProxyService,
    StaticSiteService,
    TcpProxyService,
    build_http_services,
)
from .tlsmgr import TlsManager, generate_self_signed

__all__ = [
    "CaptchaManager",
    "GeoipDB",
    "GeoipRecord",
    "HttpListener",
    "HttpProxyService",
    "Request",
    "Server",
    "ServiceRegistry",
    "StaticSiteService",
    "TcpProxyService",
    "TlsManager",
    "build_http_services",
    "generate_self_signed",
    "generate_captcha_client_id",
    "run",
]
