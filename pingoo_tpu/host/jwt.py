"""Minimal JOSE: JWT sign/verify + JWK/JWKS.

Reference parity (jwt/ crate): algorithms HS512 / EdDSA(Ed25519) /
ES256 / ES512 (jwt.rs:141-155); compact serialization with
base64url-no-padding; registered claims iss/sub/aud/exp/nbf/iat/jti
(jwt.rs:37-124); verification checks signature then exp/nbf with
clock-drift tolerance and optional aud/iss matching (jwt.rs:213-327);
JWK kty OKP/EC/oct with Key<->Jwk conversion (jwk.rs:15-147,
key.rs:134-213). Crypto backed by the `cryptography` package instead of
aws-lc-rs.
"""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import json
import os
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec, ed25519
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )

    HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:  # gated: HS512 (hmac/hashlib) needs no backend
    # Environments without the `cryptography` package still get the
    # symmetric JWT path (captcha cookies use HS512); the asymmetric
    # algorithms raise JwtError at key-construction/use time instead of
    # breaking every importer of host.services at import time.
    HAVE_CRYPTOGRAPHY = False

    class InvalidSignature(Exception):  # type: ignore[no-redef]
        pass

    class _MissingCrypto:
        def __init__(self, name):
            self._name = name

        def __getattr__(self, attr):
            raise JwtError(
                f"{self._name}.{attr} requires the 'cryptography' package, "
                "which is not installed")

    hashes = _MissingCrypto("hashes")
    ec = _MissingCrypto("ec")
    ed25519 = _MissingCrypto("ed25519")

    def decode_dss_signature(*_a, **_k):  # type: ignore[no-redef]
        raise JwtError("ECDSA requires the 'cryptography' package")

    def encode_dss_signature(*_a, **_k):  # type: ignore[no-redef]
        raise JwtError("ECDSA requires the 'cryptography' package")

DEFAULT_DRIFT_TOLERANCE_S = 60


class JwtError(Exception):
    pass


def b64url_encode(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode("ascii")


def b64url_decode(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    try:
        return base64.urlsafe_b64decode(text + pad)
    except Exception as exc:
        raise JwtError(f"invalid base64url: {exc}")


# -- keys --------------------------------------------------------------------

ALG_HS512 = "HS512"
ALG_EDDSA = "EdDSA"
ALG_ES256 = "ES256"
ALG_ES512 = "ES512"

_EC_CURVES = {
    ALG_ES256: (ec.SECP256R1(), hashes.SHA256(), 32),
    ALG_ES512: (ec.SECP521R1(), hashes.SHA512(), 66),
} if HAVE_CRYPTOGRAPHY else {}


@dataclass
class Key:
    """A signing/verification key (reference key.rs:12-131)."""

    algorithm: str
    kid: Optional[str] = None
    secret: Optional[bytes] = None  # HS512
    private: object = None  # Ed25519PrivateKey | EllipticCurvePrivateKey
    public: object = None

    # -- generation ----------------------------------------------------------

    @staticmethod
    def generate(algorithm: str, kid: Optional[str] = None) -> "Key":
        if algorithm == ALG_HS512:
            return Key(algorithm, kid=kid, secret=os.urandom(64))
        if algorithm == ALG_EDDSA:
            priv = ed25519.Ed25519PrivateKey.generate()
            return Key(algorithm, kid=kid, private=priv,
                       public=priv.public_key())
        if algorithm in _EC_CURVES:
            curve, _, _ = _EC_CURVES[algorithm]
            priv = ec.generate_private_key(curve)
            return Key(algorithm, kid=kid, private=priv,
                       public=priv.public_key())
        raise JwtError(f"unsupported algorithm {algorithm}")

    # -- sign / verify -------------------------------------------------------

    def sign(self, message: bytes) -> bytes:
        if self.algorithm == ALG_HS512:
            if self.secret is None:
                raise JwtError("missing secret")
            return hmac_mod.new(self.secret, message, hashlib.sha512).digest()
        if self.private is None:
            raise JwtError("missing private key")
        if self.algorithm == ALG_EDDSA:
            return self.private.sign(message)
        curve, hash_alg, size = _EC_CURVES[self.algorithm]
        der = self.private.sign(message, ec.ECDSA(hash_alg))
        r, s = decode_dss_signature(der)
        return r.to_bytes(size, "big") + s.to_bytes(size, "big")

    def verify(self, message: bytes, signature: bytes) -> bool:
        try:
            if self.algorithm == ALG_HS512:
                if self.secret is None:
                    return False
                expected = hmac_mod.new(
                    self.secret, message, hashlib.sha512).digest()
                return hmac_mod.compare_digest(expected, signature)
            pub = self.public or (
                self.private.public_key() if self.private else None)
            if pub is None:
                return False
            if self.algorithm == ALG_EDDSA:
                pub.verify(signature, message)
                return True
            curve, hash_alg, size = _EC_CURVES[self.algorithm]
            if len(signature) != 2 * size:
                return False
            r = int.from_bytes(signature[:size], "big")
            s = int.from_bytes(signature[size:], "big")
            pub.verify(encode_dss_signature(r, s), message, ec.ECDSA(hash_alg))
            return True
        except InvalidSignature:
            return False

    # -- JWK conversion (reference jwk.rs) -----------------------------------

    def to_jwk(self, include_private: bool = False) -> dict:
        jwk: dict = {"alg": self.algorithm}
        if self.kid:
            jwk["kid"] = self.kid
        if self.algorithm == ALG_HS512:
            jwk["kty"] = "oct"
            if include_private:
                jwk["k"] = b64url_encode(self.secret or b"")
            return jwk
        if self.algorithm == ALG_EDDSA:
            jwk["kty"] = "OKP"
            jwk["crv"] = "Ed25519"
            pub = self.public or self.private.public_key()
            from cryptography.hazmat.primitives.serialization import (
                Encoding, PublicFormat, PrivateFormat, NoEncryption,
            )

            jwk["x"] = b64url_encode(
                pub.public_bytes(Encoding.Raw, PublicFormat.Raw))
            if include_private and self.private is not None:
                jwk["d"] = b64url_encode(self.private.private_bytes(
                    Encoding.Raw, PrivateFormat.Raw, NoEncryption()))
            return jwk
        curve, _, size = _EC_CURVES[self.algorithm]
        jwk["kty"] = "EC"
        jwk["crv"] = "P-256" if self.algorithm == ALG_ES256 else "P-521"
        pub = self.public or self.private.public_key()
        nums = pub.public_numbers()
        jwk["x"] = b64url_encode(nums.x.to_bytes(size, "big"))
        jwk["y"] = b64url_encode(nums.y.to_bytes(size, "big"))
        if include_private and self.private is not None:
            d = self.private.private_numbers().private_value
            jwk["d"] = b64url_encode(d.to_bytes(size, "big"))
        return jwk

    @staticmethod
    def from_jwk(jwk: dict) -> "Key":
        kty = jwk.get("kty")
        alg = jwk.get("alg")
        kid = jwk.get("kid")
        if kty == "oct":
            return Key(alg or ALG_HS512, kid=kid,
                       secret=b64url_decode(jwk.get("k", "")))
        if kty == "OKP":
            if jwk.get("crv") != "Ed25519":
                raise JwtError(f"unsupported OKP curve {jwk.get('crv')}")
            pub = ed25519.Ed25519PublicKey.from_public_bytes(
                b64url_decode(jwk["x"]))
            priv = None
            if "d" in jwk:
                priv = ed25519.Ed25519PrivateKey.from_private_bytes(
                    b64url_decode(jwk["d"]))
            return Key(ALG_EDDSA, kid=kid, private=priv, public=pub)
        if kty == "EC":
            crv = jwk.get("crv")
            algorithm = {"P-256": ALG_ES256, "P-521": ALG_ES512}.get(crv)
            if algorithm is None:
                raise JwtError(f"unsupported EC curve {crv}")
            curve, _, _ = _EC_CURVES[algorithm]
            x = int.from_bytes(b64url_decode(jwk["x"]), "big")
            y = int.from_bytes(b64url_decode(jwk["y"]), "big")
            pub_nums = ec.EllipticCurvePublicNumbers(x, y, curve)
            pub = pub_nums.public_key()
            priv = None
            if "d" in jwk:
                d = int.from_bytes(b64url_decode(jwk["d"]), "big")
                priv = ec.EllipticCurvePrivateNumbers(d, pub_nums).private_key()
            return Key(algorithm, kid=kid, private=priv, public=pub)
        raise JwtError(f"unsupported kty {kty}")


@dataclass
class Jwks:
    """A JWK set (reference jwk.rs Jwks)."""

    keys: list[Key] = field(default_factory=list)

    def to_json(self, include_private: bool = False) -> str:
        return json.dumps(
            {"keys": [k.to_jwk(include_private) for k in self.keys]})

    @staticmethod
    def from_json(text: str) -> "Jwks":
        try:
            raw = json.loads(text)
            return Jwks(keys=[Key.from_jwk(j) for j in raw.get("keys", [])])
        except (ValueError, KeyError, TypeError) as exc:
            raise JwtError(f"invalid JWKS: {exc}")

    def find(self, kid: Optional[str]) -> Optional[Key]:
        for key in self.keys:
            if key.kid == kid:
                return key
        return self.keys[0] if self.keys and kid is None else None


def jwk_thumbprint(key: Key) -> str:
    """RFC 7638 JWK thumbprint (SHA-256, base64url) — used for ACME key
    authorizations."""
    jwk = key.to_jwk()
    if jwk["kty"] == "EC":
        canonical = {"crv": jwk["crv"], "kty": "EC", "x": jwk["x"],
                     "y": jwk["y"]}
    elif jwk["kty"] == "OKP":
        canonical = {"crv": jwk["crv"], "kty": "OKP", "x": jwk["x"]}
    else:
        canonical = {"k": jwk.get("k", ""), "kty": "oct"}
    digest = hashlib.sha256(
        json.dumps(canonical, separators=(",", ":"),
                   sort_keys=True).encode()).digest()
    return b64url_encode(digest)


# -- tokens ------------------------------------------------------------------


def sign(key: Key, claims: dict, header_extra: Optional[dict] = None) -> str:
    """Compact JWT (reference jwt.rs:172-196)."""
    header = {"alg": key.algorithm, "typ": "JWT"}
    if key.kid:
        header["kid"] = key.kid
    if header_extra:
        header.update(header_extra)
    signing_input = (
        b64url_encode(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + b64url_encode(json.dumps(claims, separators=(",", ":")).encode())
    )
    sig = key.sign(signing_input.encode("ascii"))
    return signing_input + "." + b64url_encode(sig)


def parse_header(token: str) -> dict:
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("invalid token: expected 3 parts")
    try:
        return json.loads(b64url_decode(parts[0]))
    except ValueError as exc:
        raise JwtError(f"invalid token header: {exc}")


def parse_and_verify(
    token: str,
    key: Key,
    audience: Optional[str] = None,
    issuer: Optional[str] = None,
    now: Optional[float] = None,
    drift_tolerance_s: int = DEFAULT_DRIFT_TOLERANCE_S,
) -> dict:
    """Verify signature + registered claims; returns the claims
    (reference jwt.rs:213-327)."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("invalid token: expected 3 parts")
    header = parse_header(token)
    if header.get("alg") != key.algorithm:
        raise JwtError(
            f"algorithm mismatch: token {header.get('alg')}, key {key.algorithm}")
    signing_input = (parts[0] + "." + parts[1]).encode("ascii")
    if not key.verify(signing_input, b64url_decode(parts[2])):
        raise JwtError("invalid signature")
    try:
        claims = json.loads(b64url_decode(parts[1]))
    except ValueError as exc:
        raise JwtError(f"invalid claims: {exc}")
    if not isinstance(claims, dict):
        raise JwtError("invalid claims: not an object")

    now = time.time() if now is None else now
    exp = claims.get("exp")
    if exp is not None and float(exp) + drift_tolerance_s < now:
        raise JwtError("token expired")
    nbf = claims.get("nbf")
    if nbf is not None and float(nbf) - drift_tolerance_s > now:
        raise JwtError("token not yet valid")
    if audience is not None:
        aud = claims.get("aud")
        auds: Iterable = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise JwtError("audience mismatch")
    if issuer is not None and claims.get("iss") != issuer:
        raise JwtError("issuer mismatch")
    return claims
