"""Service discovery: static config + DNS + Docker, with a 2s refresh loop.

Reference parity (pingoo/service_discovery/):
  * ServiceRegistry (service_registry.rs:22-103): static upstreams from
    config merged with discovered ones; background loop every 2 s;
    diff-and-swap so readers always see a consistent snapshot; a failing
    discoverer keeps the last known state (:112-119).
  * DNS discoverer (dns.rs): resolve non-ip upstream hostnames; the
    reference's IPv6-loopback workaround (::1 -> 127.0.0.1, dns.rs:73-75)
    is preserved.
  * Docker discoverer (docker.rs + docker/ crate): containers labeled
    `pingoo.service` (+ optional `pingoo.port`) via the Docker Engine API
    over the unix socket, taking the bridge-network IP (docker.rs:56-156).
    Implemented against the same REST endpoint (/containers/json) with a
    minimal unix-socket HTTP client — the reference's whole `docker`
    crate collapses into _docker_list_containers.
"""

from __future__ import annotations

import asyncio
import json
import socket
from typing import Iterable, Optional

from ..config.schema import ServiceConfig, Upstream

REFRESH_INTERVAL_S = 2.0
DOCKER_SERVICE_LABEL = "pingoo.service"
DOCKER_PORT_LABEL = "pingoo.port"


class ServiceRegistry:
    def __init__(
        self,
        services: Iterable[ServiceConfig],
        docker_socket: str = "/var/run/docker.sock",
        enable_docker: bool = True,
        enable_dns: bool = True,
    ):
        self._static: dict[str, list[Upstream]] = {}
        self._dns_targets: dict[str, list[Upstream]] = {}
        for svc in services:
            ups = list(svc.http_proxy or ()) + list(svc.tcp_proxy or ())
            resolved = [u for u in ups if u.ip is not None]
            pending = [u for u in ups if u.ip is None]
            self._static[svc.name] = resolved
            if pending:
                self._dns_targets[svc.name] = pending
        self._current: dict[str, list[Upstream]] = dict(self._static)
        self.docker_socket = docker_socket
        self.enable_docker = enable_docker
        self.enable_dns = enable_dns
        self._task: Optional[asyncio.Task] = None
        self._dns_cache: dict[tuple, list[Upstream]] = {}

    # -- reads (hot path) ----------------------------------------------------

    def get_upstreams(self, service: str) -> list[Upstream]:
        return self._current.get(service, [])

    # -- background loop -----------------------------------------------------

    async def start_in_background(self) -> None:
        await self.discover()  # first resolution synchronously at boot
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(REFRESH_INTERVAL_S)
            try:
                await self.discover()
            except Exception:
                pass  # keep last state (service_registry.rs:112-119)

    async def discover(self) -> None:
        dns_result, docker_result = await asyncio.gather(
            self._discover_dns(), self._discover_docker(),
            return_exceptions=True)
        merged: dict[str, list[Upstream]] = {
            name: list(ups) for name, ups in self._static.items()
        }
        if isinstance(dns_result, dict):
            for name, ups in dns_result.items():
                merged.setdefault(name, []).extend(ups)
        if isinstance(docker_result, dict):
            for name, ups in docker_result.items():
                merged.setdefault(name, []).extend(ups)
        # Atomic swap per service (diff_upstreams + Arc swap in reference).
        self._current = merged

    # -- DNS -----------------------------------------------------------------

    async def _discover_dns(self) -> dict[str, list[Upstream]]:
        if not self.enable_dns or not self._dns_targets:
            return {}
        loop = asyncio.get_running_loop()
        out: dict[str, list[Upstream]] = {}
        for service, targets in self._dns_targets.items():
            ups: list[Upstream] = []
            for target in targets:
                cache_key = (target.hostname, target.port)
                try:
                    infos = await loop.getaddrinfo(
                        target.hostname, target.port, type=socket.SOCK_STREAM)
                except OSError:
                    # Transient resolver failure: keep the last known
                    # addresses for this hostname rather than dropping
                    # the upstream (reference keeps last state on
                    # discoverer failure, service_registry.rs:112-119).
                    ups.extend(self._dns_cache.get(cache_key, []))
                    continue
                resolved = []
                seen = set()
                for _family, _type, _proto, _canon, sockaddr in infos:
                    ip = sockaddr[0]
                    if ip == "::1":
                        ip = "127.0.0.1"  # dns.rs:73-75 workaround
                    if ip in seen:
                        continue
                    seen.add(ip)
                    resolved.append(Upstream(hostname=target.hostname,
                                             port=target.port, tls=target.tls,
                                             ip=ip, h2=target.h2))
                self._dns_cache[cache_key] = resolved
                ups.extend(resolved)
            if ups:
                out[service] = ups
        return out

    # -- Docker --------------------------------------------------------------

    async def _discover_docker(self) -> dict[str, list[Upstream]]:
        if not self.enable_docker:
            return {}
        try:
            containers = await _docker_list_containers(self.docker_socket)
        except OSError:
            return {}
        out: dict[str, list[Upstream]] = {}
        for container in containers:
            labels = container.get("Labels") or {}
            service = labels.get(DOCKER_SERVICE_LABEL)
            if not service:
                continue
            port = None
            if DOCKER_PORT_LABEL in labels:
                try:
                    port = int(labels[DOCKER_PORT_LABEL])
                except ValueError:
                    continue
            else:
                ports = container.get("Ports") or []
                private = [p.get("PrivatePort") for p in ports
                           if p.get("PrivatePort")]
                if len(private) == 1:
                    port = private[0]
            if port is None:
                continue
            networks = ((container.get("NetworkSettings") or {})
                        .get("Networks") or {})
            ip = None
            for net in networks.values():
                if net.get("IPAddress"):
                    ip = net["IPAddress"]
                    break
            if not ip:
                continue
            out.setdefault(service, []).append(
                Upstream(hostname=ip, port=port, tls=False, ip=ip))
        return out


async def _docker_list_containers(socket_path: str) -> list[dict]:
    """GET /containers/json over the Docker unix socket
    (reference docker/src/client.rs:41-145 + containers.rs:6-12)."""
    reader, writer = await asyncio.open_unix_connection(socket_path)
    try:
        writer.write(
            b"GET /v1.43/containers/json HTTP/1.1\r\n"
            b"Host: docker\r\nConnection: close\r\n\r\n")
        await writer.drain()
        raw = await reader.read()
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    if b" 200 " not in status_line:
        raise OSError(f"docker api: {status_line!r}")
    if b"chunked" in head.lower():
        body = _dechunk(body)
    return json.loads(body.decode("utf-8"))


def _dechunk(body: bytes) -> bytes:
    out = bytearray()
    while body:
        size_line, _, rest = body.partition(b"\r\n")
        try:
            size = int(size_line.split(b";")[0], 16)
        except ValueError:
            break
        if size == 0:
            break
        out += rest[:size]
        body = rest[size + 2:]
    return bytes(out)
