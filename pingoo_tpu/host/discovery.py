"""Service discovery: static config + DNS + Docker, with a 2s refresh loop.

Reference parity (pingoo/service_discovery/):
  * ServiceRegistry (service_registry.rs:22-103): static upstreams from
    config merged with discovered ones; background loop every 2 s;
    diff-and-swap so readers always see a consistent snapshot; a failing
    discoverer keeps the last known state (:112-119).
  * DNS discoverer (dns.rs): resolve non-ip upstream hostnames; the
    reference's IPv6-loopback workaround (::1 -> 127.0.0.1, dns.rs:73-75)
    is preserved.
  * Docker discoverer (docker.rs + docker/ crate): containers labeled
    `pingoo.service` (+ optional `pingoo.port`) via the Docker Engine API
    over the unix socket, taking the bridge-network IP (docker.rs:56-156).
    Implemented against the same REST endpoint (/containers/json) with a
    minimal unix-socket HTTP client — the reference's whole `docker`
    crate collapses into _docker_list_containers.
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
from typing import Iterable, Optional

from ..config.schema import ServiceConfig, Upstream
from ..logging_utils import get_logger

log = get_logger(__name__)

REFRESH_INTERVAL_S = 2.0
DOCKER_SERVICE_LABEL = "pingoo.service"
DOCKER_PORT_LABEL = "pingoo.port"
# The reference clamps resolver TTLs (dns.rs:97-105): positive answers
# live at least 60 s (no re-resolve on every 2 s tick) and at most 2 h;
# a failing resolver serves the last-known addresses for up to the
# negative cap before the upstream drops.
DNS_POSITIVE_MIN_TTL_S = 60.0
DNS_POSITIVE_MAX_TTL_S = 7200.0
DNS_NEGATIVE_MAX_TTL_S = 1800.0
# Problem containers are warned about once per idle window, via a cache
# so ids don't accumulate forever (docker.rs:20-22,39 moka time_to_idle).
DOCKER_WARN_IDLE_S = 600.0


class ServiceRegistry:
    def __init__(
        self,
        services: Iterable[ServiceConfig],
        docker_socket: str = "/var/run/docker.sock",
        enable_docker: bool = True,
        enable_dns: bool = True,
    ):
        self._static: dict[str, list[Upstream]] = {}
        self._dns_targets: dict[str, list[Upstream]] = {}
        for svc in services:
            ups = list(svc.http_proxy or ()) + list(svc.tcp_proxy or ())
            resolved = [u for u in ups if u.ip is not None]
            pending = [u for u in ups if u.ip is None]
            self._static[svc.name] = resolved
            if pending:
                self._dns_targets[svc.name] = pending
        self._current: dict[str, list[Upstream]] = dict(self._static)
        self.docker_socket = docker_socket
        self.enable_docker = enable_docker
        self.enable_dns = enable_dns
        self._task: Optional[asyncio.Task] = None
        # (hostname, port) -> (resolved bare IPs, resolved-at timestamp).
        # Bare IPs, NOT Upstream objects: two services may point at the
        # same host:port with different tls/h2 flags, and each target
        # must rebuild its own Upstreams from the shared addresses.
        self._dns_cache: dict[tuple, tuple[list[str], float]] = {}
        self._docker_warned: dict[str, float] = {}  # container id -> warned-at

    # -- reads (hot path) ----------------------------------------------------

    def get_upstreams(self, service: str) -> list[Upstream]:
        return self._current.get(service, [])

    # -- background loop -----------------------------------------------------

    async def start_in_background(self) -> None:
        await self.discover()  # first resolution synchronously at boot
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(REFRESH_INTERVAL_S)
            try:
                await self.discover()
            except Exception:
                pass  # keep last state (service_registry.rs:112-119)

    async def discover(self) -> None:
        dns_result, docker_result = await asyncio.gather(
            self._discover_dns(), self._discover_docker(),
            return_exceptions=True)
        merged: dict[str, list[Upstream]] = {
            name: list(ups) for name, ups in self._static.items()
        }
        if isinstance(dns_result, dict):
            for name, ups in dns_result.items():
                merged.setdefault(name, []).extend(ups)
        if isinstance(docker_result, dict):
            for name, ups in docker_result.items():
                merged.setdefault(name, []).extend(ups)
        # Atomic swap per service (diff_upstreams + Arc swap in reference).
        self._current = merged

    # -- DNS -----------------------------------------------------------------

    async def _getaddrinfo(self, hostname: str, port: int):
        """Resolver seam (stubbed in tests for TTL-behavior checks)."""
        loop = asyncio.get_running_loop()
        return await loop.getaddrinfo(hostname, port,
                                      type=socket.SOCK_STREAM)

    async def _discover_dns(self) -> dict[str, list[Upstream]]:
        if not self.enable_dns or not self._dns_targets:
            return {}
        out: dict[str, list[Upstream]] = {}
        now = time.monotonic()
        for service, targets in self._dns_targets.items():
            ups: list[Upstream] = []
            for target in targets:
                def build(ips):
                    return [Upstream(hostname=target.hostname,
                                     port=target.port, tls=target.tls,
                                     ip=ip, h2=target.h2) for ip in ips]

                cache_key = (target.hostname, target.port)
                cached, resolved_at = self._dns_cache.get(
                    cache_key, ([], -1e18))
                age = now - resolved_at
                if cached and age < DNS_POSITIVE_MIN_TTL_S:
                    # Positive-TTL floor: don't hammer the resolver on
                    # every 2 s tick (dns.rs positive_min_ttl = 60 s).
                    ups.extend(build(cached))
                    continue
                try:
                    infos = await self._getaddrinfo(target.hostname,
                                                    target.port)
                except OSError:
                    # Resolver failure: serve the last-known addresses up
                    # to the negative cap (dns.rs negative_max_ttl 1800 s;
                    # reference also keeps last state on discoverer
                    # failure, service_registry.rs:112-119).
                    if cached and age < DNS_NEGATIVE_MAX_TTL_S:
                        ups.extend(build(cached))
                    continue
                ips: list[str] = []
                for _family, _type, _proto, _canon, sockaddr in infos:
                    ip = sockaddr[0]
                    if ip == "::1":
                        ip = "127.0.0.1"  # dns.rs:73-75 workaround
                    if ip not in ips:
                        ips.append(ip)
                self._dns_cache[cache_key] = (ips, now)
                ups.extend(build(ips))
            if ups:
                out[service] = ups
        # Positive-TTL ceiling: entries never serve past 2 h without a
        # successful re-resolution (dns.rs positive_max_ttl = 7200 s).
        self._dns_cache = {
            k: v for k, v in self._dns_cache.items()
            if now - v[1] < DNS_POSITIVE_MAX_TTL_S
        }
        return out

    # -- Docker --------------------------------------------------------------

    async def _discover_docker(self) -> dict[str, list[Upstream]]:
        if not self.enable_docker:
            return {}
        try:
            containers = await _docker_list_containers(self.docker_socket)
        except OSError:
            return {}
        out: dict[str, list[Upstream]] = {}
        for container in containers:
            labels = container.get("Labels") or {}
            service = labels.get(DOCKER_SERVICE_LABEL)
            if not service:
                continue
            cid = container.get("Id", "?")
            port = None
            if DOCKER_PORT_LABEL in labels:
                try:
                    port = int(labels[DOCKER_PORT_LABEL])
                except ValueError:
                    self._warn_container(
                        cid, f"invalid {DOCKER_PORT_LABEL} label")
                    continue
            else:
                ports = container.get("Ports") or []
                private = [p.get("PrivatePort") for p in ports
                           if p.get("PrivatePort")]
                if len(private) == 1:
                    port = private[0]
            if port is None:
                self._warn_container(
                    cid, "no usable port (ambiguous or missing; set "
                         f"{DOCKER_PORT_LABEL})")
                continue
            networks = ((container.get("NetworkSettings") or {})
                        .get("Networks") or {})
            ip = None
            for net in networks.values():
                if net.get("IPAddress"):
                    ip = net["IPAddress"]
                    break
            if not ip:
                self._warn_container(cid, "no bridge-network IP address")
                continue
            out.setdefault(service, []).append(
                Upstream(hostname=ip, port=port, tls=False, ip=ip))
        return out

    def _warn_container(self, cid: str, problem: str) -> None:
        """Warn about a problem container once per idle window, with the
        cache pruned so departed container ids don't accumulate
        (reference docker.rs:20-22,39 warned_containers moka cache)."""
        now = time.monotonic()
        self._docker_warned = {
            k: ts for k, ts in self._docker_warned.items()
            if now - ts < DOCKER_WARN_IDLE_S
        }
        if cid in self._docker_warned:
            self._docker_warned[cid] = now  # refresh the idle timer
            return
        self._docker_warned[cid] = now
        log.warning(f"docker discovery: skipping container {cid[:12]}: "
                    f"{problem}")


async def _docker_list_containers(socket_path: str) -> list[dict]:
    """GET /containers/json over the Docker unix socket
    (reference docker/src/client.rs:41-145 + containers.rs:6-12)."""
    reader, writer = await asyncio.open_unix_connection(socket_path)
    try:
        writer.write(
            b"GET /v1.43/containers/json HTTP/1.1\r\n"
            b"Host: docker\r\nConnection: close\r\n\r\n")
        await writer.drain()
        raw = await reader.read()
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    if b" 200 " not in status_line:
        raise OSError(f"docker api: {status_line!r}")
    if b"chunked" in head.lower():
        body = _dechunk(body)
    return json.loads(body.decode("utf-8"))


def _dechunk(body: bytes) -> bytes:
    out = bytearray()
    while body:
        size_line, _, rest = body.partition(b"\r\n")
        try:
            size = int(size_line.split(b";")[0], 16)
        except ValueError:
            break
        if size == 0:
            break
        out += rest[:size]
        body = rest[size + 2:]
    return bytes(out)
