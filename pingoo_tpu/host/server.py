"""Server orchestrator: wire config -> registry, geoip, captcha, lists,
verdict engine, services, TLS, listeners; run until shutdown.

Reference parity (pingoo/server.rs:33-150 + main.rs:33-107): build the
service registry and start background discovery, load geoip (optional),
captcha manager, lists; construct per-listener service sets; TLS manager
for https/tcp+tls listeners; bind everything, then serve concurrently
with graceful shutdown. The addition over the reference is the
VerdictService between listeners and rules: the ruleset is compiled once
at boot (config errors fail fast, as in the reference where expressions
compile during config load) into the TPU plan + device tables.
"""

from __future__ import annotations

import asyncio
import os
import signal
from typing import Optional

from ..compiler import compile_ruleset
from ..config.schema import Config, ListenerProtocol
from ..engine.service import VerdictService
from ..lists import load_lists
from .captcha import CaptchaManager
from .discovery import ServiceRegistry
from .geoip import GeoipDB
from .httpd import HttpListener
from .services import TcpProxyService, build_http_services
from .tlsmgr import TlsManager


class Server:
    def __init__(
        self,
        config: Config,
        use_device: bool = True,
        geoip_paths: Optional[tuple] = None,
        captcha_jwks_path: str = "/etc/pingoo/captcha_jwks.json",
        tls_dir: str = "/etc/pingoo/tls",
        enable_docker: bool = True,
        cache_dir: Optional[str] = None,
        bot_score_params_path: Optional[str] = None,
        xff_token: Optional[str] = None,
        tls_alpn: bool = False,
    ):
        self.config = config
        self.use_device = use_device
        self.geoip_paths = geoip_paths
        self.captcha_jwks_path = captcha_jwks_path
        self.tls_dir = tls_dir
        self.enable_docker = enable_docker
        self.cache_dir = cache_dir
        self.bot_score_params_path = bot_score_params_path
        # Deployment flags the native-plane runner passes EXPLICITLY
        # (they used to travel via process-global env vars, which let
        # any co-resident Server instance inherit them):
        # - xff_token: per-boot token; the listeners trust
        #   x-forwarded-for ONLY on requests carrying it (the C++ data
        #   plane sends it on loopback control-plane hops).
        # - tls_alpn: the native TLS transport fronts the public ports,
        #   so ACME validates via tls-alpn-01 (http-01 would hit the
        #   native verdict path, not the challenge handler).
        self.xff_token = xff_token
        self.tls_alpn = tls_alpn
        self.registry: Optional[ServiceRegistry] = None
        self.verdict: Optional[VerdictService] = None
        self.http_listeners: list[HttpListener] = []
        self.tcp_servers: list[asyncio.AbstractServer] = []
        self.acme = None

    async def start(self) -> None:
        config = self.config
        self.registry = ServiceRegistry(
            config.services,
            docker_socket=config.service_discovery.docker_socket,
            enable_docker=self.enable_docker)
        await self.registry.start_in_background()

        geoip = (GeoipDB.load(self.geoip_paths) if self.geoip_paths
                 else GeoipDB.load())
        captcha = CaptchaManager(self.captcha_jwks_path)
        lists = load_lists(config.lists)
        # Exposed for the native-plane runner (host/native_plane.py):
        # its ring sidecar shares this plan/lists/geoip so the C++ front
        # door and the Python plane compute identical verdicts.
        self.geoip = geoip
        self.lists = lists

        # Probe the accelerator before table building touches jax at all;
        # a dead backend degrades to CPU XLA (or pure interpreter). With
        # --no-device, pin CPU outright: plan assembly below still
        # builds jax arrays, and an ambient accelerator plugin with a
        # wedged transport would otherwise hang that first device op.
        from ..engine.service import ensure_jax_backend, force_cpu_backend

        if self.use_device:
            use_device = ensure_jax_backend()
        else:
            force_cpu_backend()
            use_device = False
        from ..compiler.cache import compile_ruleset_cached

        # Serving-mesh + scheduler knobs (ISSUE 6, docs/SCHEDULER.md):
        # validate PINGOO_MESH here so a malformed spec fails the boot
        # with its message instead of silently serving unsharded, and
        # log the admission policy the engine planes will run under.
        from ..sched import SchedulerConfig, mesh_env_spec

        mesh_spec = mesh_env_spec()  # raises ValueError on a bad spec
        sched_cfg = SchedulerConfig.from_env(max_batch=1024)
        from ..logging_utils import get_logger

        get_logger("pingoo_tpu.server").info(
            "scheduler config", extra={"fields": {
                "mesh": "x".join(str(d) for d in mesh_spec),
                "mode": sched_cfg.mode,
                "deadline_ms": sched_cfg.deadline_ms,
                "failopen": sched_cfg.failopen,
            }})

        # Service route predicates compile into the same plan as extra
        # verdict columns (rules AND routing decided by one batch).
        routes = [(s.name, s.route) for s in config.services]
        plan = compile_ruleset_cached(
            list(config.rules), lists, cache_dir=self.cache_dir,
            routes=routes)
        self.plan = plan
        bot_params = None
        if self.bot_score_params_path:
            from ..models.botscore import load_params

            bot_params = load_params(self.bot_score_params_path)
        self.verdict = VerdictService(plan, lists, use_device=use_device,
                                      bot_score_params=bot_params)
        await self.verdict.start()
        # Boot-time degradation surface (ISSUE 10, docs/RESILIENCE.md):
        # rungs already demoted at startup (broken backend, mesh spec
        # too big) are easy to miss in counters — log them once, here.
        demoted = self.verdict.ladder.demoted()
        if demoted:
            get_logger("pingoo_tpu.server").warning(
                "boot with demoted rungs", extra={"fields": {
                    "demoted": demoted,
                    "ladder": self.verdict.ladder.snapshot()}})

        tls_manager: Optional[TlsManager] = None
        if any(l.protocol.is_tls for l in config.listeners) or \
                config.tls.acme is not None:
            tls_manager = TlsManager(self.tls_dir)

        acme_challenges = None
        if config.tls.acme is not None and config.tls.acme.domains:
            from .acme import AcmeManager

            # Challenge type is an EXPLICIT deployment choice:
            # tls_alpn=True means the native TLS transport fronts
            # port 443 and answers acme-tls/1 from <tls_dir>/alpn
            # (tls-alpn-01, the reference's only challenge type,
            # acme.rs:180-242). Without it, the Python-only deployment
            # uses http-01 — inferring the mode from directory existence
            # would silently break issuance either way.
            alpn_dir = None
            if self.tls_alpn:
                alpn_dir = os.path.join(self.tls_dir, "alpn")
                os.makedirs(alpn_dir, exist_ok=True)
            self.acme = AcmeManager(
                self.tls_dir, list(config.tls.acme.domains),
                directory_url=config.tls.acme.directory_url,
                tls_manager=tls_manager, alpn_dir=alpn_dir)
            acme_challenges = self.acme.challenges
            await self.acme.start_in_background()


        services_by_name = {s.name: s for s in config.services}
        for listener_cfg in config.listeners:
            listener_services = [services_by_name[n]
                                 for n in listener_cfg.services]
            if listener_cfg.protocol.is_http:
                http_services = build_http_services(
                    listener_services, self.registry)
                listener = HttpListener(
                    name=listener_cfg.name,
                    host=listener_cfg.host,
                    port=listener_cfg.port,
                    services=http_services,
                    verdict=self.verdict,
                    lists=lists,
                    rules_meta=plan.rules,
                    captcha=captcha,
                    geoip=geoip,
                    tls_context=(tls_manager.server_context()
                                 if listener_cfg.protocol.is_tls else None),
                    acme_challenges=acme_challenges,
                    xff_token=self.xff_token,
                    # Columns are looked up by the BUILT services' names:
                    # build_http_services may drop non-http entries, so a
                    # positional zip against the config list could hand a
                    # service another service's route column.
                    route_indices=[plan.route_index.get(s.name)
                                   for s in http_services],
                )
                await listener.bind()
                self.http_listeners.append(listener)
            else:
                svc = TcpProxyService(listener_services[0], self.registry)
                ssl_ctx = (tls_manager.server_context()
                           if listener_cfg.protocol.is_tls else None)
                server = await asyncio.start_server(
                    svc.serve_connection, listener_cfg.host,
                    listener_cfg.port, ssl=ssl_ctx, backlog=2048)
                self.tcp_servers.append(server)

    async def serve_forever(self) -> None:
        tasks = [asyncio.create_task(l.serve_forever())
                 for l in self.http_listeners]
        tasks += [asyncio.create_task(s.serve_forever())
                  for s in self.tcp_servers]
        if tasks:
            await asyncio.gather(*tasks)

    async def stop(self) -> None:
        for listener in self.http_listeners:
            await listener.close()
            for service in listener.services:
                close = getattr(service, "close", None)
                if close is not None:
                    await close()
        for server in self.tcp_servers:
            server.close()
            await server.wait_closed()
        if self.acme is not None:
            await self.acme.stop()
        if self.verdict is not None:
            await self.verdict.stop()
        if self.registry is not None:
            await self.registry.stop()


async def run(config: Config, **kwargs) -> None:
    """main() equivalent (reference main.rs:33-85): build, serve, and
    shut down gracefully on SIGINT/SIGTERM."""
    server = Server(config, **kwargs)
    await server.start()
    loop = asyncio.get_running_loop()
    stop_event = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_event.set)
        except NotImplementedError:
            pass
    serve_task = asyncio.create_task(server.serve_forever())
    await stop_event.wait()
    serve_task.cancel()
    # Graceful-shutdown cap (reference listeners/mod.rs:28: 20 s).
    try:
        await asyncio.wait_for(server.stop(), timeout=20)
    except asyncio.TimeoutError:
        pass
    finally:
        # The SIGTERM drain must flush any live jax.profiler trace even
        # when stop() hit the 20 s cap mid-way: without stop_trace the
        # PINGOO_PROFILE_DIR capture is buffered in memory and silently
        # lost on exit.
        if server.verdict is not None:
            server.verdict.ensure_trace_stopped()
            # Cost-ledger snapshot on drain (ISSUE 17): the measured
            # EWMAs are the next boot's admission costs — losing them
            # means re-seeding from BENCH_history, which is lossier.
            server.verdict.persist_cost_ledger()
        # ... and auto-dump the flight recorders (ISSUE 5): the last N
        # requests' provenance is exactly what a post-mortem of the
        # shutdown-adjacent traffic needs, and it lives only in memory.
        from ..obs.flightrecorder import dump_on_drain

        dump_on_drain("sigterm")
