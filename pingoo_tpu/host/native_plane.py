"""Production native-plane runner: the C++ front door as THE data plane.

Topology (reference pingoo runs one Rust process, main.rs:33-85; here
the data plane is a C++ epoll process per listener and this Python
process is the policy/control plane):

    client
      -> native/httpd            public bind, TLS + SNI + acme-tls/1,
                                 h1/h2, captcha cookie gate, per-request
                                 WAF verdict enforcement, native service
                                 routing over the services table,
                                 graceful SIGTERM drain (20 s cap)
           -> upstreams          direct, chosen by the on-device route
                                 verdict (http_listener.rs:266-270 +
                                 http_proxy_service.rs:101-118 semantics)
           -> python plane       fail-open target (ring full / verdict
              (loopback)         deadline), captcha endpoints, and any
                                 service the native plane cannot carry

This process runs:
  * the full Python host plane (host/server.py) REBASED to loopback
    ports — captcha `/__pingoo/captcha*`, static sites, and the
    fail-open path all land on a complete rules-enforcing server, so
    degradation never bypasses policy;
  * the ring sidecar (device verdicts, host-rule merge, geoip
    enrichment of the C++ plane's asn/country-unknown slots);
  * a discovery republisher: every 2 s (service_registry.rs:86) the
    registry snapshot is written to the services table file, which the
    C++ plane hot-reloads on mtime change;
  * child lifecycle: SIGTERM to each httpd starts its graceful drain.

Constraint: every HTTP listener must carry the same service ORDER (the
verdict byte's 5-bit route field indexes one global ordering); configs
that violate this are rejected at startup rather than mis-routed.
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
from typing import Optional

from ..config.schema import Config
from ..logging_utils import get_logger
from .server import Server

log = get_logger("pingoo_tpu.native_plane")

REPUBLISH_INTERVAL_S = 2.0  # reference discovery tick, service_registry.rs:86
DRAIN_CAP_S = 20.0  # reference graceful-shutdown cap, listeners/mod.rs:28


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _loopback_rebase(config: Config) -> tuple[Config, dict[str, int]]:
    """Copy the config with every listener moved to a loopback ephemeral
    port; returns (rebased config, original-listener-name -> new port).
    The native plane takes over the PUBLIC addresses."""
    import dataclasses

    from ..config.schema import ListenerProtocol

    ports: dict[str, int] = {}
    listeners = []
    for listener in config.listeners:
        if not listener.protocol.is_http:
            # TCP proxying stays on the Python plane AT ITS PUBLIC
            # address — the native front door only fronts HTTP(S), and
            # silently rebasing a tcp listener to loopback would strand
            # its clients.
            listeners.append(listener)
            continue
        port = _free_port()
        ports[listener.name] = port
        proto = listener.protocol
        # The Python plane sits behind the native proxy on loopback; TLS
        # terminates at the native edge, so the inner hop is plaintext.
        if proto == ListenerProtocol.HTTPS:
            proto = ListenerProtocol.HTTP
        listeners.append(dataclasses.replace(
            listener, host="127.0.0.1", port=port, protocol=proto))
    rebased = dataclasses.replace(config, listeners=type(config.listeners)(
        listeners))
    return rebased, ports


class NativePlane:
    """Owns the C++ httpd processes + ring sidecar + loopback plane."""

    def __init__(self, config: Config, state_dir: str,
                 use_device: bool = True, workers: int = 1,
                 httpd_bin: Optional[str] = None,
                 upstream_ca: Optional[str] = None, **server_kwargs):
        from .. import native_ring

        self.config = config
        self.state_dir = state_dir
        self.workers = max(1, workers)
        # Trust anchor for TLS upstream hops: system roots by default,
        # an explicit bundle for private-CA deployments (and tests).
        self.upstream_ca = upstream_ca or os.environ.get(
            "PINGOO_UPSTREAM_CA") or None
        self.httpd_bin = httpd_bin or os.path.join(
            native_ring.NATIVE_DIR, "httpd")
        rebased, self._loopback_ports = _loopback_rebase(config)
        self.server = Server(rebased, use_device=use_device,
                             **server_kwargs)
        self.sidecar = None
        self._sidecar_thread = None
        self.rings = []
        self.procs: list[subprocess.Popen] = []
        self._republish_task = None
        self._service_names: list[str] = []
        self.services_path = os.path.join(state_dir, "services.tbl")

    async def start(self) -> None:
        import threading

        from .. import native_ring
        from ..native_ring import Ring, RingSidecar

        if not native_ring.ensure_built():
            raise RuntimeError(
                "native data plane requested but the C++ toolchain is "
                "unavailable (make -C pingoo_tpu/native)")
        await asyncio.to_thread(
            subprocess.run, ["make", "-C", native_ring.NATIVE_DIR, "httpd"],
            check=True, capture_output=True)
        os.makedirs(self.state_dir, exist_ok=True)

        # Deployment env for the LOOPBACK plane, set here (not in
        # __init__) so merely constructing a NativePlane cannot leak
        # these into an unrelated internet-facing Server in the same
        # process. Server.start() reads both.
        # - TRUST_XFF: captcha client ids must bind the real client
        #   address the native gate injects via x-forwarded-for.
        # - TLS_ALPN: the native TLS transport fronts the public ports,
        #   so ACME must validate via tls-alpn-01 (http-01 would hit
        #   the native verdict/route path, not the challenge handler).
        os.environ["PINGOO_TRUST_XFF"] = "1"
        if self.config.tls.acme is not None and self.config.tls.acme.domains:
            os.environ["PINGOO_TLS_ALPN"] = "1"

        await self.server.start()

        if any(l.protocol.is_tls and l.protocol.is_http
               for l in self.config.listeners):
            # The rebased config has no TLS listener, so Server skipped
            # TlsManager — but the NATIVE edge terminates TLS and needs
            # the store populated (first boot: the self-signed `*`
            # default, tlsmgr.py; reference tls_manager.rs:193-231).
            from .tlsmgr import TlsManager

            TlsManager(self.server.tls_dir)

        http_listeners = [l for l in self.config.listeners
                          if l.protocol.is_http]
        if not http_listeners:
            raise RuntimeError("native plane needs at least one http(s) "
                               "listener")
        # One global service order: the route verdict's 5-bit field
        # indexes it (native_ring.write_services_file order).
        orders = {tuple(l.services) for l in http_listeners}
        if len(orders) > 1:
            raise RuntimeError(
                "native plane requires every HTTP listener to share one "
                f"service order; got {sorted(orders)} — run the Python "
                "plane for per-listener service sets")
        names = [n for n in http_listeners[0].services
                 if self._is_http_service(n)]
        self._service_names = names

        # One ring PER (listener, worker): the verdict queue is MPMC, so
        # two httpd processes sharing a ring would steal each other's
        # tickets (each discards tickets it does not own, and the victim
        # requests fail open at the verdict deadline).
        ring_paths: dict[tuple[str, int], str] = {}
        for listener in http_listeners:
            for w in range(self.workers):
                path = os.path.join(self.state_dir,
                                    f"ring_{listener.name}_{w}")
                ring_paths[(listener.name, w)] = path
                self.rings.append(Ring(path, capacity=16384, create=True))
        self.sidecar = RingSidecar(
            self.rings, self.server.plan, self.server.lists,
            max_batch=1024, services=names or None,
            geoip=self.server.geoip)
        self._sidecar_thread = threading.Thread(
            target=self.sidecar.run, daemon=True)
        self._sidecar_thread.start()

        await asyncio.to_thread(self._write_services)

        tls_dir = self.server.tls_dir
        alpn_dir = os.path.join(tls_dir, "alpn")
        for listener in http_listeners:
            fail_open_port = self._loopback_ports[listener.name]
            for w in range(self.workers):
                argv = [
                    self.httpd_bin, str(listener.port),
                    ring_paths[(listener.name, w)],
                    "127.0.0.1", str(fail_open_port),
                    "--captcha-upstream", f"127.0.0.1:{fail_open_port}",
                    "--jwks", self.server.captcha_jwks_path,
                    "--services", self.services_path,
                    "--bind", listener.host,
                ]
                if listener.protocol.is_tls:
                    argv += ["--tls-dir", tls_dir]
                    if os.path.isdir(alpn_dir):
                        argv += ["--alpn-dir", alpn_dir]
                if self.upstream_ca:
                    argv += ["--upstream-ca", self.upstream_ca]
                proc = subprocess.Popen(argv, stdout=subprocess.PIPE)
                self.procs.append(proc)  # before the bind check: a
                # failed worker must still be reaped by stop()
                try:
                    # The bind banner arrives only after cert/ring setup;
                    # a wedged child must not freeze the event loop (and
                    # with it the loopback plane + signal handling).
                    line = await asyncio.wait_for(
                        asyncio.to_thread(proc.stdout.readline), timeout=60)
                except asyncio.TimeoutError:
                    raise RuntimeError(
                        f"native httpd stalled before binding "
                        f"{listener.host}:{listener.port}")
                if b"listening" not in line:
                    raise RuntimeError(
                        f"native httpd failed to bind "
                        f"{listener.host}:{listener.port}: {line!r}")
            log.info("native listener up", extra={"fields": {
                "listener": listener.name,
                "address": f"{listener.host}:{listener.port}",
                "tls": listener.protocol.is_tls,
                "workers": self.workers,
                "fail_open": f"127.0.0.1:{fail_open_port}",
            }})
        self._republish_task = asyncio.create_task(self._republish_loop())

    def _is_http_service(self, name: str) -> bool:
        svc = next(s for s in self.config.services if s.name == name)
        return svc.tcp_proxy is None

    def _loopback_target(self, name: str) -> tuple[str, int]:
        listener = next(l for l in self.config.listeners
                        if name in l.services)
        return ("127.0.0.1", self._loopback_ports[listener.name])

    def _write_services(self) -> None:
        """Snapshot the registry into the native routing table (runs in
        a worker thread: gethostbyname blocks). Plain AND TLS upstreams
        are published natively (the C++ connector dials TLS targets with
        SNI + verification, httpd.cc up_tls_begin); targets the native
        connector cannot speak to — static sites, h2:// prior-knowledge
        upstreams — route to the loopback Python plane, which serves /
        proxies them with full policy; upstreams whose address cannot
        resolve are skipped."""
        from ..native_ring import write_services_file

        table = []
        for name in self._service_names:
            svc = next(s for s in self.config.services if s.name == name)
            ups = []
            via_python = False
            if svc.static is not None:
                via_python = True  # served by the Python plane
            else:
                for u in self.server.registry.get_upstreams(name):
                    if u.h2:
                        # h2:// prior-knowledge framing is a Python-
                        # plane capability for now.
                        via_python = True
                        continue
                    addr = u.ip or u.hostname
                    try:
                        addr = socket.gethostbyname(addr)
                    except OSError:
                        # Unresolvable here (or IPv6-only —
                        # gethostbyname is v4): the Python proxy can
                        # still reach it, so route via the loopback
                        # plane instead of publishing a dead service.
                        via_python = True
                        continue
                    if u.tls:
                        # Verify against the configured name when there
                        # is one; a literal-address upstream pins the
                        # address itself (IP SAN).
                        ups.append((addr, u.port, u.hostname or addr))
                    else:
                        ups.append((addr, u.port))
            if via_python:
                ups.append(self._loopback_target(name))
            table.append((name, ups))
        write_services_file(self.services_path, table)

    async def _republish_loop(self) -> None:
        last = None
        while True:
            await asyncio.sleep(REPUBLISH_INTERVAL_S)
            try:
                snapshot = [
                    (n, tuple(
                        (u.ip or u.hostname, u.port, u.tls)
                        for u in self.server.registry.get_upstreams(n)))
                    for n in self._service_names
                ]
                if snapshot != last:
                    await asyncio.to_thread(self._write_services)
                    last = snapshot
            except Exception as exc:  # keep the loop alive on blips
                log.warning("services republish failed",
                            extra={"fields": {"error": repr(exc)}})

    async def serve_forever(self) -> None:
        await self.server.serve_forever()

    async def stop(self) -> None:
        if self._republish_task is not None:
            self._republish_task.cancel()
        # Graceful drain: SIGTERM starts the C++ plane's connection
        # drain; it exits when idle or at its internal cap.
        for proc in self.procs:
            log.info("draining native worker", extra={"fields": {
                "pid": proc.pid, "poll": proc.poll()}})
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = asyncio.get_event_loop().time() + DRAIN_CAP_S
        for proc in self.procs:
            remaining = deadline - asyncio.get_event_loop().time()
            try:
                await asyncio.wait_for(
                    asyncio.to_thread(proc.wait),
                    timeout=max(0.5, remaining))
            except asyncio.TimeoutError:
                proc.kill()
        if self.sidecar is not None:
            self.sidecar.stop()
        if self._sidecar_thread is not None:
            self._sidecar_thread.join(timeout=10)
        for ring in self.rings:
            ring.close()
        await self.server.stop()


async def run_native(config: Config, state_dir: str, **kwargs) -> None:
    """Native-plane main(): build, serve, drain on SIGINT/SIGTERM."""
    plane = NativePlane(config, state_dir, **kwargs)
    try:
        await plane.start()
    except BaseException:
        # Partial startup must not orphan C++ workers holding public
        # ports (their ring would have no consumer once we exit).
        await plane.stop()
        raise
    loop = asyncio.get_running_loop()
    stop_event = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_event.set)
        except NotImplementedError:
            pass
    serve_task = asyncio.create_task(plane.serve_forever())
    await stop_event.wait()
    log.info("shutdown signal: draining native plane")
    serve_task.cancel()
    await plane.stop()
    log.info("native plane drained")
