"""Production native-plane runner: the C++ front door as THE data plane.

Topology (reference pingoo runs one Rust process, main.rs:33-85; here
the data plane is a C++ epoll process per listener and this Python
process is the policy/control plane):

    client
      -> native/httpd            public bind, TLS + SNI + acme-tls/1,
                                 h1/h2, captcha cookie gate, per-request
                                 WAF verdict enforcement, native service
                                 routing over the services table,
                                 graceful SIGTERM drain (20 s cap)
           -> upstreams          direct, chosen by the on-device route
                                 verdict (http_listener.rs:266-270 +
                                 http_proxy_service.rs:101-118 semantics)
           -> python plane       fail-open target (ring full / verdict
              (loopback)         deadline), captcha endpoints, and any
                                 service the native plane cannot carry

This process runs:
  * the full Python host plane (host/server.py) REBASED to loopback
    ports — captcha `/__pingoo/captcha*`, static sites, and the
    fail-open path all land on a complete rules-enforcing server, so
    degradation never bypasses policy;
  * the ring sidecar (device verdicts, host-rule merge, geoip
    enrichment of the C++ plane's asn/country-unknown slots);
  * a discovery republisher: every 2 s (service_registry.rs:86) the
    registry snapshot is written to the services table file, which the
    C++ plane hot-reloads on mtime change;
  * child lifecycle: SIGTERM to each httpd starts its graceful drain.

Each HTTP listener gets its OWN routing table + route lane (the
reference binds a service list per listener, config.rs:241-253);
TCP(+TLS) listeners are fronted by the same binary in --tcp-proxy mode.
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
from typing import Optional

from ..config.schema import Config
from ..logging_utils import get_logger
from .server import Server

log = get_logger("pingoo_tpu.native_plane")

REPUBLISH_INTERVAL_S = 2.0  # reference discovery tick, service_registry.rs:86
DRAIN_CAP_S = 20.0  # reference graceful-shutdown cap, listeners/mod.rs:28


def _loopback_rebase(config: Config) -> Config:
    """Copy the config with every HTTP listener moved to a loopback
    EPHEMERAL port (port 0 — the kernel assigns at bind time, so there
    is no pick-then-rebind race; the real ports are read back from the
    bound listeners after Server.start()). The native plane takes over
    the PUBLIC addresses."""
    import dataclasses

    from ..config.schema import ListenerProtocol

    listeners = []
    for listener in config.listeners:
        if not listener.protocol.is_http:
            # TCP(+TLS) listeners are fronted by the C++ plane in
            # tcp-proxy mode (round 5) — drop them from the Python
            # plane entirely (a loopback tcp stand-in would be a second
            # bind for no traffic; there is no fail-open for tcp).
            continue
        proto = listener.protocol
        # The Python plane sits behind the native proxy on loopback; TLS
        # terminates at the native edge, so the inner hop is plaintext.
        if proto == ListenerProtocol.HTTPS:
            proto = ListenerProtocol.HTTP
        listeners.append(dataclasses.replace(
            listener, host="127.0.0.1", port=0, protocol=proto))
    return dataclasses.replace(config, listeners=type(config.listeners)(
        listeners))


class NativePlane:
    """Owns the C++ httpd processes + ring sidecar + loopback plane."""

    def __init__(self, config: Config, state_dir: str,
                 use_device: bool = True, workers: int = 1,
                 httpd_bin: Optional[str] = None,
                 upstream_ca: Optional[str] = None, **server_kwargs):
        from .. import native_ring

        self.config = config
        self.state_dir = state_dir
        self.workers = max(1, workers)
        # Trust anchor for TLS upstream hops: system roots by default,
        # an explicit bundle for private-CA deployments (and tests).
        self.upstream_ca = upstream_ca or os.environ.get(
            "PINGOO_UPSTREAM_CA") or None
        self.httpd_bin = httpd_bin or os.path.join(
            native_ring.NATIVE_DIR, "httpd")
        # Per-boot token binding x-forwarded-for trust to THIS data
        # plane: the C++ workers send it on loopback control-plane hops
        # and the Python listeners trust XFF only when it matches.
        import secrets

        self._internal_token = secrets.token_hex(16)
        self._token_path = os.path.join(state_dir, "internal.token")
        tls_alpn = bool(config.tls.acme is not None
                        and config.tls.acme.domains)
        self.server = Server(_loopback_rebase(config),
                             use_device=use_device,
                             xff_token=self._internal_token,
                             tls_alpn=tls_alpn, **server_kwargs)
        self._loopback_ports: dict[str, int] = {}
        self.sidecar = None
        self._sidecar_thread = None
        self.rings = []
        self.procs: list[subprocess.Popen] = []
        self._republish_task = None
        # Per HTTP listener: its ordered http-service names and its own
        # routing-table file (the reference binds a service list PER
        # listener, config.rs:241-253 — each listener's verdict route
        # field indexes ITS table, so listeners may front different
        # service sets).
        self._listener_services: dict[str, list[str]] = {}
        self.services_paths: dict[str, str] = {}

    async def start(self) -> None:
        import threading

        from .. import native_ring
        from ..native_ring import Ring, RingSidecar

        if not native_ring.ensure_built():
            raise RuntimeError(
                "native data plane requested but the C++ toolchain is "
                "unavailable (make -C pingoo_tpu/native)")
        await asyncio.to_thread(
            subprocess.run, ["make", "-C", native_ring.NATIVE_DIR, "httpd"],
            check=True, capture_output=True)
        os.makedirs(self.state_dir, exist_ok=True)
        # 0600 + file (not argv): /proc/<pid>/cmdline is world-readable.
        fd = os.open(self._token_path,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(self._internal_token)

        await self.server.start()
        # The rebased listeners bound port 0; read the kernel-assigned
        # ports back (no pick-then-rebind TOCTOU).
        self._loopback_ports = {l.name: l.bound_port
                                for l in self.server.http_listeners}

        if any(l.protocol.is_tls for l in self.config.listeners):
            # The rebased config has no TLS listener, so Server skipped
            # TlsManager — but the NATIVE edge terminates TLS and needs
            # the store populated (first boot: the self-signed `*`
            # default, tlsmgr.py; reference tls_manager.rs:193-231).
            from .tlsmgr import TlsManager

            TlsManager(self.server.tls_dir)

        http_listeners = [l for l in self.config.listeners
                          if l.protocol.is_http]
        if not http_listeners:
            raise RuntimeError("native plane needs at least one http(s) "
                               "listener")
        for listener in http_listeners:
            self._listener_services[listener.name] = [
                n for n in listener.services if self._is_http_service(n)]
            self.services_paths[listener.name] = os.path.join(
                self.state_dir, f"services_{listener.name}.tbl")

        # One ring PER (listener, worker): the verdict queue is MPMC, so
        # two httpd processes sharing a ring would steal each other's
        # tickets (each discards tickets it does not own, and the victim
        # requests fail open at the verdict deadline).
        ring_paths: dict[tuple[str, int], str] = {}
        ring_services: list = []  # aligned with self.rings
        for listener in http_listeners:
            for w in range(self.workers):
                path = os.path.join(self.state_dir,
                                    f"ring_{listener.name}_{w}")
                ring_paths[(listener.name, w)] = path
                self.rings.append(Ring(path, capacity=16384, create=True))
                ring_services.append(
                    self._listener_services[listener.name] or None)
        self.sidecar = RingSidecar(
            self.rings, self.server.plan, self.server.lists,
            max_batch=1024, ring_services=ring_services,
            geoip=self.server.geoip)
        self._sidecar_thread = threading.Thread(
            target=self.sidecar.run, daemon=True)
        self._sidecar_thread.start()

        await asyncio.to_thread(self._write_services)

        tls_dir = self.server.tls_dir
        alpn_dir = os.path.join(tls_dir, "alpn")
        for listener in http_listeners:
            fail_open_port = self._loopback_ports[listener.name]
            for w in range(self.workers):
                argv = [
                    self.httpd_bin, str(listener.port),
                    ring_paths[(listener.name, w)],
                    "127.0.0.1", str(fail_open_port),
                    "--captcha-upstream", f"127.0.0.1:{fail_open_port}",
                    "--jwks", self.server.captcha_jwks_path,
                    "--services", self.services_paths[listener.name],
                    "--bind", listener.host,
                    "--internal-token-file", self._token_path,
                ]
                if listener.protocol.is_tls:
                    argv += ["--tls-dir", tls_dir]
                    if os.path.isdir(alpn_dir):
                        argv += ["--alpn-dir", alpn_dir]
                if self.upstream_ca:
                    argv += ["--upstream-ca", self.upstream_ca]
                proc = subprocess.Popen(argv, stdout=subprocess.PIPE)
                self.procs.append(proc)  # before the bind check: a
                # failed worker must still be reaped by stop()
                try:
                    # The bind banner arrives only after cert/ring setup;
                    # a wedged child must not freeze the event loop (and
                    # with it the loopback plane + signal handling).
                    line = await asyncio.wait_for(
                        asyncio.to_thread(proc.stdout.readline), timeout=60)
                except asyncio.TimeoutError:
                    raise RuntimeError(
                        f"native httpd stalled before binding "
                        f"{listener.host}:{listener.port}")
                if b"listening" not in line:
                    raise RuntimeError(
                        f"native httpd failed to bind "
                        f"{listener.host}:{listener.port}: {line!r}")
                # Keep draining the pipe for the child's lifetime: a
                # chatty worker against a full, never-read pipe would
                # block inside the data plane.
                threading.Thread(target=self._pump_child_output,
                                 args=(proc,), daemon=True).start()
            log.info("native listener up", extra={"fields": {
                "listener": listener.name,
                "address": f"{listener.host}:{listener.port}",
                "tls": listener.protocol.is_tls,
                "workers": self.workers,
                "fail_open": f"127.0.0.1:{fail_open_port}",
            }})

        # TCP(+TLS) listeners: same binary in --tcp-proxy mode — accept
        # (+TLS terminate), pick a random upstream from the table
        # (3 tries / 3 s, tcp_proxy_service.rs:30-84), splice bytes.
        tcp_listeners = [l for l in self.config.listeners
                         if not l.protocol.is_http]
        for listener in tcp_listeners:
            # exactly one service per tcp listener (config validation)
            self._listener_services[listener.name] = list(listener.services)
            self.services_paths[listener.name] = os.path.join(
                self.state_dir, f"services_{listener.name}.tbl")
        if tcp_listeners:
            await asyncio.to_thread(self._write_services)
        for listener in tcp_listeners:
            ring_path = os.path.join(self.state_dir,
                                     f"ring_{listener.name}_tcp")
            # The ring argv is mandatory but unused in tcp mode (no
            # verdicts on raw streams — the reference evaluates rules
            # only on HTTP listeners).
            self.rings.append(Ring(ring_path, capacity=64, create=True))
            for w in range(self.workers):
                argv = [
                    self.httpd_bin, str(listener.port), ring_path,
                    "127.0.0.1", "9",  # unused: table routes instead
                    "--services", self.services_paths[listener.name],
                    "--bind", listener.host,
                    "--tcp-proxy",
                ]
                if listener.protocol.is_tls:
                    argv += ["--tls-dir", tls_dir]
                    if os.path.isdir(alpn_dir):
                        argv += ["--alpn-dir", alpn_dir]
                proc = subprocess.Popen(argv, stdout=subprocess.PIPE)
                self.procs.append(proc)
                try:
                    line = await asyncio.wait_for(
                        asyncio.to_thread(proc.stdout.readline), timeout=60)
                except asyncio.TimeoutError:
                    raise RuntimeError(
                        f"native tcp httpd stalled before binding "
                        f"{listener.host}:{listener.port}")
                if b"listening" not in line:
                    raise RuntimeError(
                        f"native tcp httpd failed to bind "
                        f"{listener.host}:{listener.port}: {line!r}")
                threading.Thread(target=self._pump_child_output,
                                 args=(proc,), daemon=True).start()
            log.info("native tcp listener up", extra={"fields": {
                "listener": listener.name,
                "address": f"{listener.host}:{listener.port}",
                "tls": listener.protocol.is_tls,
                "workers": self.workers,
            }})
        self._republish_task = asyncio.create_task(self._republish_loop())

    @staticmethod
    def _pump_child_output(proc) -> None:
        for raw in proc.stdout:
            line = raw.decode("utf-8", "replace").rstrip()
            if line:
                log.info("native httpd", extra={"fields": {
                    "pid": proc.pid, "line": line}})

    def _is_http_service(self, name: str) -> bool:
        svc = next(s for s in self.config.services if s.name == name)
        return svc.tcp_proxy is None

    def _loopback_target(self, lname: str) -> tuple:
        """The loopback control-plane hop for LISTENER lname — the
        fallback must land on the listener's OWN rebased Python
        listener (its route set), never another listener's."""
        from ..native_ring import INTERNAL

        return ("127.0.0.1", self._loopback_ports[lname], INTERNAL)

    def _service_upstreams(self, name: str) -> tuple:
        """One service's publishable (upstreams, static_root,
        needs_loopback). Plain, TLS and h2 upstreams are published
        natively; static services publish their root for in-binary
        serving of <=500KB files with the loopback Python plane as the
        streaming fallback for bigger ones; upstreams whose address
        cannot resolve are skipped (the loopback plane can still proxy
        them). The loopback entry itself is appended PER LISTENER by
        _write_services — each listener's fallback must be its own
        rebased Python listener."""
        svc = next(s for s in self.config.services if s.name == name)
        ups: list = []
        via_python = False
        static_root = None
        if svc.tcp_proxy is not None:
            # Raw TCP: no Python-plane fallback exists (and none is
            # needed — there is no verdict path to fail open from).
            # Unresolvable upstreams are simply skipped this tick; the
            # registry keeps them discovered (DNS/Docker) like any
            # other service (service_registry.rs:86).
            for u in self.server.registry.get_upstreams(name):
                addr = u.ip or u.hostname
                try:
                    addr = socket.gethostbyname(addr)
                except OSError:
                    continue
                ups.append((addr, u.port))
            return ups, None, False
        if svc.static is not None:
            root = svc.static.root
            if root and len(root) <= 383 and not any(
                    ch.isspace() for ch in root):
                static_root = root
            # the loopback plane streams >500KB files (and serves
            # everything when the root cannot be published)
            via_python = True
        else:
            from ..native_ring import H2

            for u in self.server.registry.get_upstreams(name):
                addr = u.ip or u.hostname
                try:
                    addr = socket.gethostbyname(addr)
                except OSError:
                    # Unresolvable here (or IPv6-only —
                    # gethostbyname is v4): the Python proxy can
                    # still reach it, so route via the loopback
                    # plane instead of publishing a dead service.
                    via_python = True
                    continue
                if u.h2:
                    # h2:// prior-knowledge: the C++ connector frames
                    # requests over an nghttp2 client session (round 5;
                    # TLS upstreams negotiate h2 via ALPN instead).
                    ups.append((addr, u.port, H2))
                elif u.tls:
                    # Verify against the configured name when there
                    # is one; a literal-address upstream pins the
                    # address itself (IP SAN). Unambiguous 4-tuple
                    # form: a hostname that collides with a table
                    # marker ("internal"/"h2-...") must never re-tag
                    # the hop.
                    ups.append((addr, u.port, "tls", u.hostname or addr))
                else:
                    ups.append((addr, u.port))
        return ups, static_root, via_python

    def _write_services(self) -> None:
        """Snapshot the registry into each listener's OWN routing table
        (runs in a worker thread: gethostbyname blocks). A listener's
        verdict route field indexes the order of ITS service list, so
        every table is written in that listener's order (reference:
        per-listener service binding, config.rs:241-253)."""
        from ..native_ring import write_services_file

        resolved = {name: self._service_upstreams(name)
                    for names in self._listener_services.values()
                    for name in names}
        for lname, names in self._listener_services.items():
            table = []
            for n in names:
                ups, static_root, needs_loopback = resolved[n]
                if needs_loopback and lname in self._loopback_ports:
                    ups = ups + [self._loopback_target(lname)]
                table.append((n, ups, static_root))
            write_services_file(self.services_paths[lname], table)

    async def _republish_loop(self) -> None:
        last = None
        while True:
            await asyncio.sleep(REPUBLISH_INTERVAL_S)
            try:
                snapshot = [
                    (n, tuple(
                        (u.ip or u.hostname, u.port, u.tls)
                        for u in self.server.registry.get_upstreams(n)))
                    for names in self._listener_services.values()
                    for n in names
                ]
                if snapshot != last:
                    await asyncio.to_thread(self._write_services)
                    last = snapshot
            except Exception as exc:  # keep the loop alive on blips
                log.warning("services republish failed",
                            extra={"fields": {"error": repr(exc)}})

    async def serve_forever(self) -> None:
        await self.server.serve_forever()

    async def stop(self) -> None:
        if self._republish_task is not None:
            self._republish_task.cancel()
        # Graceful drain: SIGTERM starts the C++ plane's connection
        # drain; it exits when idle or at its internal cap.
        for proc in self.procs:
            log.info("draining native worker", extra={"fields": {
                "pid": proc.pid, "poll": proc.poll()}})
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = asyncio.get_event_loop().time() + DRAIN_CAP_S
        for proc in self.procs:
            remaining = deadline - asyncio.get_event_loop().time()
            try:
                await asyncio.wait_for(
                    asyncio.to_thread(proc.wait),
                    timeout=max(0.5, remaining))
            except asyncio.TimeoutError:
                proc.kill()
        if self.sidecar is not None:
            self.sidecar.stop()
        if self._sidecar_thread is not None:
            self._sidecar_thread.join(timeout=10)
        for ring in self.rings:
            ring.close()
        await self.server.stop()


async def run_native(config: Config, state_dir: str, **kwargs) -> None:
    """Native-plane main(): build, serve, drain on SIGINT/SIGTERM."""
    plane = NativePlane(config, state_dir, **kwargs)
    try:
        await plane.start()
    except BaseException:
        # Partial startup must not orphan C++ workers holding public
        # ports (their ring would have no consumer once we exit).
        await plane.stop()
        raise
    loop = asyncio.get_running_loop()
    stop_event = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop_event.set)
        except NotImplementedError:
            pass
    serve_task = asyncio.create_task(plane.serve_forever())
    await stop_event.wait()
    log.info("shutdown signal: draining native plane")
    serve_task.cancel()
    await plane.stop()
    log.info("native plane drained")
