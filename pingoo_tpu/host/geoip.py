"""GeoIP: a self-contained MaxMind-DB (mmdb) decoder + lookup cache.

Reference parity (pingoo/geoip.rs): load from the fixed candidate paths
(config.rs:31-36), optionally zstd-compressed (.zst); per-IP record
{asn: u32, country: 2-letter code} where asn may be serialized as
"AS123" (serde_utils.rs:1-9); loopback/multicast short-circuit to
not-found (geoip.rs:74-77); 50k-entry 1h-TTL cache (geoip.rs:59-63);
a missing database just disables geoip (server.rs:41-43).

The decoder implements the MaxMind DB file format v2.0 (binary search
tree over address bits + typed data section) natively — no maxminddb
dependency. Both the reference's flat schema ({asn, country}) and the
standard GeoLite2 schema (country.iso_code / autonomous_system_number)
are understood. `build_mmdb` writes a minimal valid database for tests.
"""

from __future__ import annotations

import ipaddress
import struct
import time
from typing import Optional

GEOIP_DATABASE_PATHS = (
    "/etc/pingoo/geoip.mmdb",
    "/etc/pingoo/geoip.mmdb.zst",
    "/usr/share/pingoo/geoip.mmdb",
    "/usr/share/pingoo/geoip.mmdb.zst",
)

_METADATA_MARKER = b"\xab\xcd\xefMaxMind.com"
_DATA_SEPARATOR_SIZE = 16


class GeoipError(Exception):
    pass


class AddressNotFound(GeoipError):
    pass


class GeoipRecord:
    __slots__ = ("asn", "country")

    def __init__(self, asn: int = 0, country: str = "XX"):
        self.asn = asn
        self.country = country

    def __repr__(self) -> str:
        return f"GeoipRecord(asn={self.asn}, country={self.country!r})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, GeoipRecord)
                and (self.asn, self.country) == (other.asn, other.country))


# -- decoder -----------------------------------------------------------------


class _Decoder:
    """Typed data-section decoder (MaxMind DB spec §data section)."""

    def __init__(self, data: bytes, base: int):
        self.data = data
        self.base = base  # absolute offset of the data section

    def decode(self, offset: int):
        """offset is relative to the data section; returns (value, next)."""
        ctrl = self.data[self.base + offset]
        offset += 1
        dtype = ctrl >> 5
        if dtype == 0:  # extended type
            dtype = 7 + self.data[self.base + offset]
            offset += 1
        size = ctrl & 0x1F
        if dtype == 1:  # pointer
            ss = (size >> 3) & 0x3
            vbits = size & 0x7
            raw = self.data[self.base + offset : self.base + offset + ss + 1]
            offset += ss + 1
            value = int.from_bytes(raw, "big") | (vbits << (8 * (ss + 1)))
            ptr = value + (0, 2048, 526336, 0)[ss] if ss < 3 else value
            target, _ = self.decode(ptr)
            return target, offset
        if size == 29:
            size = 29 + self.data[self.base + offset]
            offset += 1
        elif size == 30:
            size = 285 + int.from_bytes(
                self.data[self.base + offset : self.base + offset + 2], "big")
            offset += 2
        elif size == 31:
            size = 65821 + int.from_bytes(
                self.data[self.base + offset : self.base + offset + 3], "big")
            offset += 3

        start = self.base + offset
        if dtype == 2:  # utf8 string
            return self.data[start : start + size].decode("utf-8"), offset + size
        if dtype == 3:  # double
            return struct.unpack(">d", self.data[start : start + 8])[0], offset + 8
        if dtype == 4:  # bytes
            return self.data[start : start + size], offset + size
        if dtype in (5, 6, 9, 10):  # uint16/32/64/128
            return int.from_bytes(self.data[start : start + size], "big"), offset + size
        if dtype == 7:  # map
            out = {}
            for _ in range(size):
                key, offset = self.decode(offset)
                val, offset = self.decode(offset)
                out[key] = val
            return out, offset
        if dtype == 8:  # int32
            raw = self.data[start : start + size]
            return int.from_bytes(raw, "big", signed=True), offset + size
        if dtype == 11:  # array
            out = []
            for _ in range(size):
                val, offset = self.decode(offset)
                out.append(val)
            return out, offset
        if dtype == 14:  # boolean (size encodes the value)
            return size != 0, offset
        if dtype == 15:  # float
            return struct.unpack(">f", self.data[start : start + 4])[0], offset + 4
        raise GeoipError(f"unsupported mmdb data type {dtype}")


class MmdbReader:
    """Binary-search-tree reader over the raw file bytes."""

    def __init__(self, data: bytes):
        idx = data.rfind(_METADATA_MARKER)
        if idx < 0:
            raise GeoipError("mmdb file is not valid: no metadata marker")
        meta_decoder = _Decoder(data, idx + len(_METADATA_MARKER))
        self.metadata, _ = meta_decoder.decode(0)
        try:
            self.node_count = int(self.metadata["node_count"])
            self.record_size = int(self.metadata["record_size"])
            self.ip_version = int(self.metadata["ip_version"])
        except KeyError as exc:
            raise GeoipError(f"mmdb metadata missing {exc}")
        if self.record_size not in (24, 28, 32):
            raise GeoipError(f"unsupported record size {self.record_size}")
        self.data = data
        self.tree_size = self.node_count * self.record_size * 2 // 8
        self.decoder = _Decoder(data, self.tree_size + _DATA_SEPARATOR_SIZE)

    def _read_record(self, node: int, side: int) -> int:
        rs = self.record_size
        base = node * rs * 2 // 8
        d = self.data
        if rs == 24:
            o = base + 3 * side
            return int.from_bytes(d[o : o + 3], "big")
        if rs == 32:
            o = base + 4 * side
            return int.from_bytes(d[o : o + 4], "big")
        # 28-bit records: 7 bytes per node; middle byte shared.
        if side == 0:
            return ((d[base + 3] >> 4) << 24) | int.from_bytes(
                d[base : base + 3], "big")
        return ((d[base + 3] & 0x0F) << 24) | int.from_bytes(
            d[base + 4 : base + 7], "big")

    def lookup_raw(self, ip) -> Optional[dict]:
        addr = ipaddress.ip_address(ip)
        if addr.version == 4 and self.ip_version == 6:
            bits = 96 * "0" + format(int(addr), "032b")
        elif addr.version == 6 and self.ip_version == 4:
            return None
        else:
            bits = format(int(addr), f"0{128 if addr.version == 6 else 32}b")
        node = 0
        for bit in bits:
            record = self._read_record(node, int(bit))
            if record == self.node_count:
                return None  # no data
            if record > self.node_count:
                offset = record - self.node_count - _DATA_SEPARATOR_SIZE
                value, _ = self.decoder.decode(offset)
                return value
            node = record
        return None


def parse_asn(value) -> int:
    """"AS123" or 123 -> 123 (reference serde_utils.rs:1-9)."""
    if isinstance(value, int):
        return value
    if isinstance(value, str):
        text = value[2:] if value[:2].upper() == "AS" else value
        try:
            return int(text)
        except ValueError:
            return 0
    return 0


def record_from_raw(raw: dict) -> GeoipRecord:
    """Understand both the reference's flat schema and GeoLite2."""
    asn = 0
    country = "XX"
    if "asn" in raw:
        asn = parse_asn(raw["asn"])
    elif "autonomous_system_number" in raw:
        asn = parse_asn(raw["autonomous_system_number"])
    c = raw.get("country")
    if isinstance(c, str):
        country = c
    elif isinstance(c, dict):
        country = str(c.get("iso_code", "XX"))
    if len(country) != 2 or not country.isascii():
        country = "XX"
    return GeoipRecord(asn=asn, country=country.upper())


class GeoipDB:
    """Reader + cache, mirroring GeoipDB in the reference."""

    CACHE_MAX = 50_000
    CACHE_TTL_S = 3600.0

    def __init__(self, reader: MmdbReader):
        import threading

        self.reader = reader
        self._cache: dict = {}
        # Shared between the asyncio listener thread and the ring-
        # sidecar thread; guards the promote/evict cache mutations.
        self._lock = threading.Lock()

    @staticmethod
    def load(paths=GEOIP_DATABASE_PATHS) -> Optional["GeoipDB"]:
        import os

        for path in paths:
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                content = f.read()
            if path.endswith(".zst"):
                import zstandard

                content = zstandard.ZstdDecompressor().decompress(
                    content, max_output_size=1 << 31)
            return GeoipDB(MmdbReader(content))
        return None

    def lookup(self, ip) -> GeoipRecord:
        addr = ipaddress.ip_address(ip)
        if addr.is_loopback or addr.is_multicast:
            raise AddressNotFound(str(ip))
        now = time.monotonic()
        # One GeoipDB is shared between the asyncio listener thread and
        # the ring-sidecar thread (native_plane wiring): the promote /
        # evict mutations below need the lock (the mmdb tree walk runs
        # outside it).
        with self._lock:
            hit = self._cache.get(addr)
            if hit is not None and hit[1] > now:
                # LRU promotion: re-insert at the dict tail so
                # sustained floods of unique addresses evict their own
                # stale misses before they evict live entries.
                del self._cache[addr]
                self._cache[addr] = hit
        if hit is not None and hit[1] > now:
            if hit[0] is None:  # cached miss
                raise AddressNotFound(str(ip))
            return hit[0]
        raw = self.reader.lookup_raw(addr)
        if raw is None or not isinstance(raw, dict):
            # Cache the MISS too: with a partial database, absent
            # addresses are the common case on hot serving paths (the
            # ring sidecar enriches every request), and re-walking the
            # mmdb tree per request would defeat the cache entirely.
            with self._lock:
                if len(self._cache) >= self.CACHE_MAX:
                    self._evict(now)
                self._cache[addr] = (None, now + self.CACHE_TTL_S)
            raise AddressNotFound(str(ip))
        record = record_from_raw(raw)
        with self._lock:
            if len(self._cache) >= self.CACHE_MAX:
                self._evict(now)
            self._cache[addr] = (record, now + self.CACHE_TTL_S)
        return record

    def _evict(self, now: float) -> None:
        """Bounded partial eviction (expired first, then the oldest
        eighth) — wholesale clear() would let a flood of unique absent
        IPs repeatedly wipe every live positive entry (moka, the
        reference's cache, evicts incrementally for the same reason)."""
        expired = [k for k, v in self._cache.items() if v[1] <= now]
        for k in expired:
            del self._cache[k]
        if len(self._cache) >= self.CACHE_MAX:
            import itertools

            drop = max(1, self.CACHE_MAX // 8)
            for k in list(itertools.islice(iter(self._cache), drop)):
                del self._cache[k]


# -- writer (test fixtures) --------------------------------------------------


def _encode_value(value) -> bytes:
    if isinstance(value, str):
        raw = value.encode("utf-8")
        assert len(raw) < 29
        return bytes([(2 << 5) | len(raw)]) + raw
    if isinstance(value, int):
        raw = value.to_bytes(max((value.bit_length() + 7) // 8, 1), "big")
        assert len(raw) <= 4
        return bytes([(6 << 5) | len(raw)]) + raw
    if isinstance(value, dict):
        out = bytearray([(7 << 5) | len(value)])
        for k, v in value.items():
            out += _encode_value(str(k))
            out += _encode_value(v)
        return bytes(out)
    raise GeoipError(f"writer: unsupported type {type(value)}")


def build_mmdb(entries: dict[str, dict], ip_version: int = 6) -> bytes:
    """Build a minimal valid mmdb: {network_cidr: record_dict}.

    Networks must be IPv4 (mapped under ::/96 when ip_version is 6,
    matching how readers traverse v4 lookups).
    """
    record_size = 32
    # Data section: concatenate encoded records, remember offsets.
    data_section = bytearray()
    offsets: dict[str, int] = {}
    nets = []
    for cidr, record in entries.items():
        offsets[cidr] = len(data_section)
        data_section += _encode_value(record)
        nets.append(ipaddress.ip_network(cidr, strict=False))

    # Build an explicit bit trie.
    nodes: list[list] = [[None, None]]  # each: [left, right]; int -> node idx

    def insert(bits: str, leaf_key: str):
        cur = 0
        for i, b in enumerate(bits):
            side = int(b)
            if i == len(bits) - 1:
                nodes[cur][side] = ("leaf", leaf_key)
                return
            nxt = nodes[cur][side]
            if not isinstance(nxt, int):
                nodes.append([None, None])
                nxt = len(nodes) - 1
                nodes[cur][side] = nxt
            cur = nxt

    for cidr, net in zip(entries.keys(), nets):
        assert net.version == 4, "test writer supports v4 networks"
        prefix_bits = format(int(net.network_address), "032b")[: net.prefixlen]
        if ip_version == 6:
            prefix_bits = "0" * 96 + prefix_bits
        insert(prefix_bits, cidr)

    node_count = len(nodes)
    tree = bytearray()
    for left, right in nodes:
        for rec in (left, right):
            if rec is None:
                value = node_count  # no data
            elif isinstance(rec, int):
                value = rec
            else:
                value = node_count + _DATA_SEPARATOR_SIZE + offsets[rec[1]]
            tree += value.to_bytes(4, "big")

    metadata = {
        "node_count": node_count,
        "record_size": record_size,
        "ip_version": ip_version,
        "database_type": "pingoo-tpu-test",
        "binary_format_major_version": 2,
        "binary_format_minor_version": 0,
    }
    return (bytes(tree) + b"\x00" * _DATA_SEPARATOR_SIZE + bytes(data_section)
            + _METADATA_MARKER + _encode_value(metadata))
