"""HTTP/TCP services: reverse proxy, static site, TCP proxy.

Reference parity:
  * HttpProxyService (services/http_proxy_service.rs): route match,
    random upstream, hop-by-hop header stripping (:25-35,114-116),
    Host/X-Forwarded-For/-Host/-Proto + Pingoo-Client-Ip/-Country/-Asn
    (:134-190), upstream error -> 502 (:192-195), response cleanup:
    strip X-Accel-*/Alt-Svc, set `server: pingoo` (:37-43,197-201),
    4s connect timeout (:54-71).
  * StaticSiteService (services/http_static_site_service.rs): GET/HEAD
    only, traversal guard (:91-94), dir -> index.html and extensionless
    -> .html prettify (:100-123), ETag = SHA256(path,size,mtime) with
    If-None-Match -> 304 (:150-182), small-file cache 500 x <=500KB
    (:30-32,185-235), larger files streamed (:238-256), configurable
    not_found page.
  * TcpProxyService (services/tcp_proxy_service.rs): random upstream,
    3 retries / 5 ms, 3 s connect timeout, then bidirectional byte pump.
"""

from __future__ import annotations

import asyncio
import hashlib
import mimetypes
import os
import random
import time
from dataclasses import dataclass
from typing import Optional

from ..config.schema import ServiceConfig, StaticSiteConfig, Upstream
from ..expr import Context, Program, execute_as_bool

HOP_BY_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate", "proxy-authorization",
    "te", "trailer", "transfer-encoding", "upgrade",
}
RESPONSE_STRIP_HEADERS = {
    "x-accel-buffering", "x-accel-charset", "x-accel-expires",
    "x-accel-limit-rate", "x-accel-redirect", "alt-svc", "server",
}
CONNECT_TIMEOUT_S = 4.0
TCP_CONNECT_TIMEOUT_S = 3.0
TCP_RETRIES = 3
TCP_RETRY_DELAY_S = 0.005
STATIC_CACHE_MAX_ENTRIES = 500
STATIC_CACHE_MAX_FILE_SIZE = 500 * 1024


@dataclass
class Response:
    status: int
    headers: list[tuple[str, str]]
    body: bytes = b""
    stream_path: Optional[str] = None  # large static files stream from disk
    # Protocol upgrade (WebSocket): (upstream_reader, upstream_writer,
    # raw response head bytes). The listener relays the head verbatim
    # and then pumps raw bytes both ways until either side closes —
    # the reference serves with upgrades enabled
    # (http_listener.rs:277 serve_connection_with_upgrades).
    tunnel: Optional[tuple] = None


def match_route(route: Optional[Program], ctx: Context) -> bool:
    """Service route matching (services/mod.rs match_request): no route
    means match-all; errors mean no-match (same fail-open as rules)."""
    if route is None:
        return True
    return execute_as_bool(route, ctx)


class HttpProxyService:
    def __init__(self, config: ServiceConfig, registry):
        self.name = config.name
        self.route = config.route
        self.registry = registry
        self._session = None
        self._h2_conns: dict = {}  # (host, port) -> H2UpstreamConnection
        self._h2_lock = None  # created lazily on the serving loop

    async def _get_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0, ttl_dns_cache=10),
                timeout=aiohttp.ClientTimeout(connect=CONNECT_TIMEOUT_S),
                auto_decompress=False,
            )
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None
        for conn in self._h2_conns.values():
            await conn.close()
        self._h2_conns.clear()

    async def _h2_conn(self, host: str, port: int):
        """Pooled h2 prior-knowledge upstream connection (the reference's
        hyper client pools h1/h2 alike, http_proxy_service.rs:54-71).
        Creation is serialized per service (concurrent first requests
        must not each open a connection and leak the losers), and a dead
        connection is closed before its replacement goes in."""
        from .h2 import H2UpstreamConnection

        if self._h2_lock is None:
            self._h2_lock = asyncio.Lock()
        key = (host, port)
        async with self._h2_lock:
            conn = self._h2_conns.get(key)
            if conn is None or not conn.alive:
                if conn is not None:
                    await conn.close()
                conn = H2UpstreamConnection(host, port)
                await asyncio.wait_for(conn.connect(), CONNECT_TIMEOUT_S)
                self._h2_conns[key] = conn
            return conn

    @staticmethod
    def _upgrade_value(req) -> Optional[str]:
        """The Upgrade token when this is an upgrade request (Connection
        lists 'upgrade' and an Upgrade header names the protocol)."""
        conn_v = ""
        up_v = None
        for n, v in req.headers:
            ln = n.lower()
            if ln == "connection":
                conn_v = v.lower()
            elif ln == "upgrade":
                up_v = v
        if up_v and "upgrade" in conn_v:
            return up_v
        return None

    async def _handle_upgrade(self, req, request_ctx, upstream,
                              upgrade: str) -> Response:
        """Tunnel an Upgrade request: send it to the upstream over a raw
        connection preserving the upgrade headers, read the response
        head, and hand the open connection to the listener for
        bidirectional pumping."""
        target_host = upstream.ip or upstream.hostname
        try:
            if upstream.tls:
                import ssl as ssl_mod

                ctx = ssl_mod.create_default_context()
                up_r, up_w = await asyncio.wait_for(
                    asyncio.open_connection(
                        target_host, upstream.port, ssl=ctx,
                        server_hostname=upstream.hostname),
                    CONNECT_TIMEOUT_S)
            else:
                up_r, up_w = await asyncio.wait_for(
                    asyncio.open_connection(target_host, upstream.port),
                    CONNECT_TIMEOUT_S)
        except Exception:
            return Response(502, [("content-type", "text/plain"),
                                  ("server", "pingoo")], b"Bad Gateway")
        head = f"{req.method} {req.target} HTTP/1.1\r\n"
        head += f"host: {upstream.hostname}\r\n"
        for n, v in req.headers:
            ln = n.lower()
            if ln in HOP_BY_HOP_HEADERS or ln == "host":
                continue
            head += f"{n}: {v}\r\n"
        head += f"connection: upgrade\r\nupgrade: {upgrade}\r\n"
        head += f"x-forwarded-for: {request_ctx.client_ip}\r\n"
        head += ("x-forwarded-proto: "
                 f"{'https' if request_ctx.tls else 'http'}\r\n")
        head += f"pingoo-client-ip: {request_ctx.client_ip}\r\n\r\n"
        try:
            up_w.write(head.encode("latin-1"))
            await up_w.drain()
            resp_head = await asyncio.wait_for(
                up_r.readuntil(b"\r\n\r\n"), 30)
        except Exception:
            up_w.close()
            return Response(502, [("content-type", "text/plain"),
                                  ("server", "pingoo")], b"Bad Gateway")
        status_line = resp_head.split(b"\r\n", 1)[0]
        parts = status_line.split()
        status = int(parts[1]) if len(parts) >= 2 and parts[1].isdigit() \
            else 502
        if status != 101:
            # Upstream REFUSED the upgrade: relay it as a normal framed
            # response (entering the raw tunnel here would let follow-up
            # keep-alive requests bypass rule evaluation entirely).
            try:
                return await self._read_refusal(up_r, resp_head, status)
            finally:
                up_w.close()
        # Relay the 101 head verbatim (its Connection/Upgrade/
        # Sec-WebSocket-* headers are the handshake).
        return Response(101, [], tunnel=(up_r, up_w, resp_head))

    @staticmethod
    async def _read_refusal(up_r, resp_head: bytes, status: int) -> Response:
        """Parse a non-101 answer to an upgrade request into a normal
        Response (content-length framing; EOF framing otherwise)."""
        headers = []
        content_length = None
        for line in resp_head.split(b"\r\n")[1:]:
            name, _, value = line.partition(b":")
            if not _:
                continue
            lname = name.decode("latin-1").strip().lower()
            v = value.decode("latin-1").strip()
            if lname == "content-length":
                try:
                    content_length = int(v)
                except ValueError:
                    pass
            if lname in HOP_BY_HOP_HEADERS or lname in RESPONSE_STRIP_HEADERS \
                    or lname == "content-length":
                continue
            headers.append((name.decode("latin-1").strip(), v))
        if content_length is not None:
            body = await asyncio.wait_for(
                up_r.readexactly(content_length), 30) if content_length \
                else b""
        else:
            body = await asyncio.wait_for(up_r.read(), 30)
        headers.append(("server", "pingoo"))
        return Response(status, headers, body)

    async def handle(self, req, request_ctx) -> Response:
        upstreams = self.registry.get_upstreams(self.name)
        if not upstreams:
            return Response(502, [("content-type", "text/plain")],
                            b"Bad Gateway")
        upstream = random.choice(upstreams)
        upgrade = self._upgrade_value(req)
        if upgrade is not None and not getattr(upstream, "h2", False):
            return await self._handle_upgrade(req, request_ctx, upstream,
                                              upgrade)
        scheme = "https" if upstream.tls else "http"
        target_host = upstream.ip or upstream.hostname
        url = f"{scheme}://{target_host}:{upstream.port}{req.target}"

        headers = []
        for name, value in req.headers:
            lname = name.lower()
            if lname in HOP_BY_HOP_HEADERS or lname == "host":
                continue
            headers.append((name, value))
        # Forwarding headers (http_proxy_service.rs:134-190).
        headers.append(("Host", upstream.hostname))
        headers.append(("X-Forwarded-Host", request_ctx.host))
        headers.append(("X-Forwarded-Proto",
                        "https" if request_ctx.tls else "http"))
        prior_xff = next((v for n, v in req.headers
                          if n.lower() == "x-forwarded-for"), None)
        xff = (f"{prior_xff}, {request_ctx.client_ip}" if prior_xff
               else request_ctx.client_ip)
        headers.append(("X-Forwarded-For", xff))
        headers.append(("Pingoo-Client-Ip", request_ctx.client_ip))
        if request_ctx.geoip_enabled:
            headers.append(("Pingoo-Client-Country", request_ctx.country))
            headers.append(("Pingoo-Client-Asn", str(request_ctx.asn)))

        if getattr(upstream, "h2", False):
            try:
                conn = await self._h2_conn(target_host, upstream.port)
                # No total timeout — the h1 path has none either (only
                # the connect timeout); long-poll upstreams must behave
                # identically over both protocols.
                status, resp_headers, body = await conn.request(
                    req.method, upstream.hostname, req.target, headers,
                    req.body or b"")
                out_headers = [
                    (n, v) for n, v in resp_headers
                    if n.lower() not in HOP_BY_HOP_HEADERS
                    and n.lower() not in RESPONSE_STRIP_HEADERS
                    and n.lower() != "content-length"
                ]
                out_headers.append(("server", "pingoo"))
                return Response(status, out_headers, body)
            except Exception:
                return Response(502, [("content-type", "text/plain"),
                                      ("server", "pingoo")], b"Bad Gateway")

        try:
            session = await self._get_session()
            async with session.request(
                req.method, url, headers=headers, data=req.body or None,
                allow_redirects=False,  # upstream TLS certs ARE validated
            ) as resp:
                body = await resp.read()
                out_headers = []
                for name, value in resp.headers.items():
                    lname = name.lower()
                    if (lname in HOP_BY_HOP_HEADERS
                            or lname in RESPONSE_STRIP_HEADERS
                            or lname == "content-length"):
                        continue
                    out_headers.append((name, value))
                out_headers.append(("server", "pingoo"))
                return Response(resp.status, out_headers, body)
        except Exception:
            return Response(502, [("content-type", "text/plain"),
                                  ("server", "pingoo")], b"Bad Gateway")


class StaticSiteService:
    def __init__(self, config: ServiceConfig):
        self.name = config.name
        self.route = config.route
        assert config.static is not None
        self.static: StaticSiteConfig = config.static
        self._cache: dict[str, tuple[float, Response]] = {}

    async def handle(self, req, request_ctx) -> Response:
        if req.method not in ("GET", "HEAD"):
            return Response(405, [("content-type", "text/plain")],
                            b"Method Not Allowed")
        path = req.path
        # Traversal guard (http_static_site_service.rs:91-94).
        if ".." in path or "\\" in path:
            return self._not_found()
        rel = path.lstrip("/")
        root = os.path.abspath(self.static.root)
        full = os.path.abspath(os.path.join(root, rel))
        if not (full == root or full.startswith(root + os.sep)):
            return self._not_found()
        # dir -> index.html; extensionless -> .html prettify (:100-123).
        if os.path.isdir(full):
            full = os.path.join(full, "index.html")
        elif not os.path.exists(full) and "." not in os.path.basename(full):
            candidate = full + ".html"
            if os.path.exists(candidate):
                full = candidate
        if not os.path.isfile(full):
            return self._not_found()

        try:
            st = os.stat(full)
        except OSError:
            return self._not_found()
        etag = '"' + hashlib.sha256(
            f"{full}{st.st_size}{st.st_mtime_ns}".encode()).hexdigest()[:32] + '"'
        if_none_match = next(
            (v for n, v in req.headers if n.lower() == "if-none-match"), None)
        if if_none_match == etag:
            return Response(304, [("etag", etag)])

        ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
        headers = [("content-type", ctype), ("etag", etag),
                   ("server", "pingoo")]
        if st.st_size > STATIC_CACHE_MAX_FILE_SIZE:
            return Response(200, headers, stream_path=full)
        cached = self._cache.get(full)
        if cached and cached[0] == st.st_mtime_ns:
            resp = cached[1]
            return Response(resp.status, headers, resp.body)
        with open(full, "rb") as f:
            body = f.read()
        if len(self._cache) >= STATIC_CACHE_MAX_ENTRIES:
            self._cache.clear()
        self._cache[full] = (st.st_mtime_ns, Response(200, headers, body))
        if req.method == "HEAD":
            return Response(200, headers)
        return Response(200, headers, body)

    def _not_found(self) -> Response:
        nf = self.static.not_found
        if nf.file and os.path.isfile(nf.file):
            with open(nf.file, "rb") as f:
                return Response(nf.status, [("content-type", "text/html")],
                                f.read())
        return Response(nf.status, [("content-type", "text/plain")],
                        b"Not Found")


class TcpProxyService:
    def __init__(self, config: ServiceConfig, registry):
        self.name = config.name
        self.registry = registry

    async def serve_connection(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter) -> None:
        upstream_pair = None
        for attempt in range(TCP_RETRIES):
            upstreams = self.registry.get_upstreams(self.name)
            if upstreams:
                upstream = random.choice(upstreams)
                try:
                    upstream_pair = await asyncio.wait_for(
                        asyncio.open_connection(
                            upstream.ip or upstream.hostname, upstream.port),
                        TCP_CONNECT_TIMEOUT_S)
                    break
                except (OSError, asyncio.TimeoutError):
                    pass
            await asyncio.sleep(TCP_RETRY_DELAY_S)
        if upstream_pair is None:
            writer.close()
            return
        up_reader, up_writer = upstream_pair

        async def pump(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
            try:
                while True:
                    chunk = await src.read(65536)
                    if not chunk:
                        # Half-close: signal EOF downstream but keep the
                        # other direction flowing (copy_bidirectional
                        # semantics, tcp_proxy_service.rs:74-82).
                        if dst.can_write_eof():
                            dst.write_eof()
                        break
                    dst.write(chunk)
                    await dst.drain()
            except (OSError, asyncio.CancelledError):
                try:
                    dst.close()
                except OSError:
                    pass

        await asyncio.gather(pump(reader, up_writer), pump(up_reader, writer))
        for w in (up_writer, writer):
            try:
                w.close()
            except OSError:
                pass


def build_http_services(configs: list[ServiceConfig], registry):
    """Factory (reference services/http_utils.rs:43-51)."""
    out = []
    for cfg in configs:
        if cfg.http_proxy is not None:
            out.append(HttpProxyService(cfg, registry))
        elif cfg.static is not None:
            out.append(StaticSiteService(cfg))
    return out
