"""The captcha challenge frontend — built-app parity.

The reference embeds a compiled Preact/vite app (captcha/src/index.tsx,
served via captcha.rs serve_captcha / serve_asset). This module is the
same app re-derived without a JS toolchain: a hand-compiled vanilla
rendering of the identical UX state machine —

  checkbox -> 'Verifying...' + spinner -> GET /api/init (retried 3x,
  200 ms apart) -> WebCrypto SHA-256 proof of work (nonce starts at 1)
  -> POST /api/verify -> 'Success!' -> location.reload() after 500 ms
  (reload happens on failure too, exactly like index.tsx:72), with the
  reference's error copy when anything throws.

The page shell mirrors index.html + index.css (dark/light color-scheme,
domain headline, bordered checkbox card), and the script ships as a
separate /__pingoo/captcha/assets/index.js asset like the vite build.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
  <head>
    <meta charset="UTF-8" />
    <meta name="viewport" content="width=device-width, initial-scale=1.0" />
    <title>Security Verification</title>
    <style>
:root {
  font-family: system-ui, Avenir, Helvetica, Arial, sans-serif;
  line-height: 1.5; font-weight: 400;
  color-scheme: light dark;
  color: rgba(255, 255, 255, 0.87); background-color: #242424;
  font-synthesis: none; text-rendering: optimizeLegibility;
  -webkit-font-smoothing: antialiased;
}
@media (prefers-color-scheme: light) {
  :root { color: #213547; background-color: #ffffff; }
}
body { margin: 0; display: flex; place-items: center;
       min-width: 320px; min-height: 100vh; }
#pingoo-captcha { width: 100%; }
.wrap { display: flex; justify-content: center; width: 100%; }
.col { display: flex; flex-direction: column; max-width: 36rem;
       padding: 1.25rem; margin-top: -20vh; }
.col > * + * { margin-top: 2rem; }
h1 { font-size: 1.5rem; font-weight: 700; margin: 0; }
h2 { font-size: 1.25rem; font-weight: 500; margin: 0; }
.box { display: flex; flex-direction: column; width: fit-content;
       border: 1px solid #8884; border-radius: 0.375rem;
       padding: 1.25rem; align-items: center; }
.row { display: flex; align-items: center; width: 100%; }
.row p { margin: 0 0 0 1rem; }
input[type=checkbox] { width: 2rem; height: 2rem; cursor: pointer; }
.error { font-weight: 500; color: #ef4444; }
.spinner { height: 2rem; width: 2rem; color: #6b7280; }
.spinner svg { animation: spin 1s linear infinite; }
@keyframes spin { to { transform: rotate(360deg); } }
.hidden { display: none; }
    </style>
  </head>
  <body>
    <div id="pingoo-captcha">
      <div class="wrap"><div class="col">
        <h1 id="domain"></h1>
        <h2>Verify you are human by completing the action below.</h2>
        <div class="box"><div class="row">
          <input id="cb" type="checkbox" />
          <span id="spin" class="spinner hidden">
            <svg xmlns="http://www.w3.org/2000/svg" fill="none"
                 viewBox="0 0 24 24">
              <circle style="opacity:.25" cx="12" cy="12" r="10"
                      stroke="currentColor" stroke-width="4"></circle>
              <path style="opacity:.75" fill="currentColor"
                    d="M4 12a8 8 0 018-8V0C5.373 0 0 5.373 0 12h4zm2
                       5.291A7.962 7.962 0 014 12H0c0 3.042 1.135 5.824 3
                       7.938l3-2.647z"></path>
            </svg>
          </span>
          <p id="message">Click on the checkbox</p>
        </div></div>
        <p id="error" class="error hidden">Oops! Something went wrong.
        Please reload the page and ensure that your cookies are
        enabled.</p>
      </div></div>
    </div>
    <script src="/__pingoo/captcha/assets/index.js"></script>
  </body>
</html>
"""

APP_JS = """'use strict';
(function () {
  var checkboxLoading = false;
  var verified = false;
  var cb = document.getElementById('cb');
  var spin = document.getElementById('spin');
  var message = document.getElementById('message');
  var errorEl = document.getElementById('error');
  document.getElementById('domain').textContent = window.location.hostname;

  function renderMessage() {
    if (verified) { message.textContent = 'Success!'; }
    else if (checkboxLoading) { message.textContent = 'Verifying...'; }
    else { message.textContent = 'Click on the checkbox'; }
    cb.classList.toggle('hidden', checkboxLoading);
    spin.classList.toggle('hidden', !checkboxLoading);
    cb.checked = verified;
  }

  function uint8ArrayToHex(data) {
    var hex = '';
    for (var i = 0; i < data.length; i++) {
      hex += data[i].toString(16).padStart(2, '0');
    }
    return hex;
  }

  async function retry(fn, options) {
    var attempts = (options && options.attempts) || 3;
    var delay = (options && options.delay) || 100;
    for (var i = 0; i < attempts; i++) {
      try { return await fn(); }
      catch (err) {
        if (i < attempts - 1) {
          await new Promise(function (r) { setTimeout(r, delay); });
        } else { throw err; }
      }
    }
  }

  async function proofOfWork(challenge, difficulty) {
    var nonce = 0;
    var hash = '';
    var target = '0'.repeat(difficulty);
    var enc = new TextEncoder();
    do {
      nonce++;
      hash = uint8ArrayToHex(new Uint8Array(await window.crypto.subtle
        .digest('SHA-256', enc.encode(challenge + nonce))));
    } while (hash.substring(0, difficulty) !== target);
    return { nonce: nonce.toString(10), hash: hash };
  }

  async function onCheckboxClicked(event) {
    if (event) event.preventDefault();
    if (checkboxLoading || verified) return;
    errorEl.classList.add('hidden');
    checkboxLoading = true;
    renderMessage();
    try {
      var settings = await retry(async function () {
        var initRes = await fetch('/__pingoo/captcha/api/init');
        if (initRes.status !== 200) { throw new Error(await initRes.text()); }
        return await initRes.json();
      }, { delay: 200 });
      var result = await proofOfWork(settings.challenge, settings.difficulty);
      var verifyRes = await fetch('/__pingoo/captcha/api/verify', {
        method: 'POST',
        headers: { 'Content-Type': 'application/json' },
        body: JSON.stringify(result),
      });
      checkboxLoading = false;
      if (verifyRes.status === 200) { verified = true; }
      renderMessage();
      // reload to allow access (or redo the challenge on failure)
      setTimeout(function () { location.reload(); }, 500);
    } catch (err) {
      console.error(err);
      errorEl.classList.remove('hidden');
      checkboxLoading = false;
      renderMessage();
    }
  }

  cb.addEventListener('click', onCheckboxClicked);
  renderMessage();
})();
"""
