"""HTTP/HTTPS listener: the WAF hot path.

Reference parity (pingoo/listeners/http_listener.rs:120-282, https_
listener.rs:98-110 — the same function drives both, TLS handled by the
wrapping transport):

  per request: host/path extraction (:140-141, 284-296) -> geoip lookup
  with not-found -> default record (:143-157) -> user-agent trim with
  256-byte cap (:159-165) -> captcha client id (:167) -> cookie parse
  (:169-181) -> empty/oversized UA -> 403 (:196-198) ->
  /__pingoo/captcha* routing (:200-204) -> captcha-verified cookie check
  where an INVALID cookie serves the challenge page immediately
  (:222-236) -> rules loop with per-action semantics: Block -> 403,
  Captcha -> challenge page unless verified; NOTE the loop continues
  through subsequent matching rules (:251-264) -> service routing loop,
  first match handles (:266-270) -> 404 (:272).

The one architectural change (the point of this framework): the rules
loop consumes a per-request row of the batched TPU verdict bitmap
(engine/service.py) instead of tree-walking rules inline; action
application order is identical because the engine returns the full
per-rule match row (SURVEY.md §7 "Exact FP/FN parity").

Adds a /__pingoo/metrics endpoint (req/s, verdict latency, batch
occupancy) — the reference has no metrics surface (SURVEY.md §5) but the
north-star metric requires one.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Optional

import h11

from ..engine import bodyscan
from ..engine.batch import RequestTuple
from ..engine.service import VerdictService
from ..expr import Context
from ..obs import REGISTRY, schema as obs_schema
from ..obs.trace import TRACE_HEADER, AccessLogSampler, new_trace_id
from .captcha import (
    CAPTCHA_PATH_PREFIX,
    CAPTCHA_VERIFIED_COOKIE,
    CaptchaManager,
    generate_captcha_client_id,
)
from .geoip import AddressNotFound, GeoipDB, GeoipRecord
from .services import Response, match_route

USER_AGENT_MAX_LENGTH = 256
HOSTNAME_MAX_LENGTH = 256


def _int_env(name: str, default: int, floor: int) -> int:
    raw = os.environ.get(name, "")
    try:
        value = int(raw)
    except ValueError:
        return default
    return value if value >= floor else default


# Request-size caps, shared knob-for-knob with the native plane
# (native/httpd.cc reads the same env vars) so oversized requests get
# the same status on both listeners: 431 for a head beyond
# PINGOO_MAX_HEADER_BYTES, 413 for a body beyond PINGOO_MAX_BODY_BYTES
# (ISSUE 11; parity test in tests/test_fuzz_corpus.py).
MAX_HEADER_BYTES = _int_env("PINGOO_MAX_HEADER_BYTES", 32 * 1024, 256)
MAX_BODY_BYTES = _int_env("PINGOO_MAX_BODY_BYTES", 16 * 1024 * 1024, 1)

# End of an h1 request head, tolerating the bare-LF variants h11
# accepts (the strict gate below then rejects them explicitly rather
# than letting the two listener planes diverge on them).
_HEAD_END_RE = re.compile(rb"\r?\n\r?\n")

_RAW_400 = (b"HTTP/1.1 400 Bad Request\r\nserver: pingoo\r\n"
            b"content-length: 0\r\nconnection: close\r\n\r\n")
_RAW_413 = (b"HTTP/1.1 413 Content Too Large\r\nserver: pingoo\r\n"
            b"content-length: 0\r\nconnection: close\r\n\r\n")
_RAW_431 = (b"HTTP/1.1 431 Request Header Fields Too Large\r\n"
            b"server: pingoo\r\n"
            b"content-length: 0\r\nconnection: close\r\n\r\n")


def strict_head_violation(head: bytes) -> Optional[str]:
    """WAFFLED-class strict gate over the RAW request head, applied
    before h11 parses it (and mirrored in native/httpd.cc parse_head):
    h11 is lenient exactly where parser pairs historically disagree —
    it joins obsolete line folds, collapses value-identical duplicate
    Content-Length headers, accepts bare-LF line endings and
    Transfer-Encoding alongside Content-Length. Each of those is a
    framing ambiguity one hop may read differently from the next
    (request smuggling), so both listener planes refuse them outright.
    Returns a short reason string, or None when the head is clean."""
    if b"\n" in head.replace(b"\r\n", b""):
        return "bare-lf-line-ending"
    lines = head.split(b"\r\n")
    # h11 tolerates versions up to HTTP/2.x on an h1 socket; the native
    # plane serves exactly 1.0/1.1. Pin the gate to the intersection.
    if not (lines[0].endswith(b" HTTP/1.1")
            or lines[0].endswith(b" HTTP/1.0")):
        return "http-version"
    cl_seen = 0
    te_seen = False
    for line in lines[1:]:
        if not line:
            break
        if line[:1] in (b" ", b"\t"):
            return "obs-fold"
        name, sep, value = line.partition(b":")
        if not sep:
            return "colonless-field-line"
        if name != name.rstrip(b" \t"):
            return "whitespace-before-colon"
        lname = name.lower()
        if lname == b"content-length":
            cl_seen += 1
            # Digits only (after OWS): h11 collapses a value-identical
            # list ("3, 3") that the native plane refuses; and signs,
            # blanks, or separators are framing ambiguity either way.
            if not value.strip(b" \t").isdigit():
                return "bad-content-length"
        elif lname == b"transfer-encoding":
            te_seen = True
    if cl_seen > 1:
        return "duplicate-content-length"
    if te_seen and cl_seen:
        return "te-with-cl"
    return None
GRACEFUL_SHUTDOWN_S = 20  # listeners/mod.rs:28


@dataclass
class Request:
    method: str
    target: str  # full request target (url)
    path: str
    headers: list[tuple[str, str]]
    body: bytes = b""


@dataclass
class RequestContext:
    """Reference http_listener.rs RequestContext (:183-194)."""

    client_ip: str
    client_port: int
    asn: int = 0
    country: str = "XX"
    geoip_enabled: bool = False
    tls: bool = False
    host: str = ""


@dataclass
class ListenerStats:
    requests: int = 0
    blocked: int = 0
    captcha_served: int = 0
    fail_open: int = 0  # degraded verdicts served (engine fail-open)
    body_fail_open: int = 0  # body scans degraded to metadata-only
    started_at: float = field(default_factory=time.time)


def blocked_response() -> Response:
    return Response(403, [("content-type", "text/plain"),
                          ("server", "pingoo")], b"Forbidden")


def not_found_response() -> Response:
    return Response(404, [("content-type", "text/plain"),
                          ("server", "pingoo")], b"Not Found")


def parse_cookies(headers: list[tuple[str, str]]) -> dict[str, str]:
    out: dict[str, str] = {}
    for name, value in headers:
        if name.lower() != "cookie":
            continue
        for part in value.split(";"):
            k, _, v = part.strip().partition("=")
            if k:
                out.setdefault(k, v)
    return out


def _strip_port(authority: str) -> str:
    """Drop a trailing :port, IPv6-bracket aware: "[::1]:80" -> "[::1]"."""
    authority = authority.strip()
    if authority.startswith("["):
        end = authority.find("]")
        return authority[: end + 1] if end >= 0 else authority
    return authority.rsplit(":", 1)[0] if ":" in authority else authority


def get_host(req: Request) -> str:
    """Host from the request target or Host header (:284-296). Over-long
    hosts become EMPTY, not truncated (heapless from_str overflow ->
    unwrap_or_default, http_listener.rs:287,292)."""
    if req.target.startswith("http://") or req.target.startswith("https://"):
        rest = req.target.split("://", 1)[1]
        host = _strip_port(rest.split("/", 1)[0])
    else:
        host = ""
        for name, value in req.headers:
            if name.lower() == "host":
                host = _strip_port(value)
                break
    return host if len(host) <= HOSTNAME_MAX_LENGTH else ""


def declared_content_length(head: bytes) -> Optional[int]:
    """The head's Content-Length value, or None when absent/garbled.
    Only meaningful AFTER strict_head_violation passed (at most one CL,
    no folded lines)."""
    for line in head.split(b"\r\n")[1:]:
        if not line:
            break
        name, sep, value = line.partition(b":")
        if sep and name.lower() == b"content-length":
            try:
                return int(value.strip())
            except ValueError:
                return None
    return None


def extract_request_fields(req: Request) -> tuple[str, str]:
    """(host, user_agent) exactly as the serving path computes them.
    The differential fuzzer (tools/analyze/fuzz.py) calls this so its
    oracle can never drift from the listener's own extraction."""
    host = get_host(req)
    user_agent = ""
    for name, value in req.headers:
        if name.lower() == "user-agent":
            user_agent = value.strip()
            break
    if len(user_agent) >= USER_AGENT_MAX_LENGTH:
        user_agent = ""  # heapless from_str overflow -> default empty
    return host, user_agent


def parse_request_bytes(data: bytes):
    """One-shot parse oracle: run DATA through exactly the gates and
    h11 parse the live listener applies, without sockets. Returns
    ("ok", Request), ("reject", "400"|"413"|"431"), or
    ("incomplete", None) when DATA ends before a full message."""
    m = _HEAD_END_RE.search(data)
    if m is None:
        return ("reject", "431") if len(data) > MAX_HEADER_BYTES \
            else ("incomplete", None)
    if m.end() > MAX_HEADER_BYTES:
        return ("reject", "431")
    if strict_head_violation(data[:m.end()]) is not None:
        return ("reject", "400")
    cl = declared_content_length(data[:m.end()])
    if cl is not None and cl > MAX_BODY_BYTES:
        return ("reject", "413")
    conn = h11.Connection(h11.SERVER,
                          max_incomplete_event_size=MAX_HEADER_BYTES)
    try:
        conn.receive_data(data)
        conn.receive_data(b"")  # EOF: flush a read-to-close body
        req_event = None
        body = bytearray()
        while True:
            event = conn.next_event()
            if event is h11.NEED_DATA or event is h11.PAUSED:
                return ("incomplete", None)
            if isinstance(event, h11.Request):
                req_event = event
            elif isinstance(event, h11.Data):
                body += event.data
                if len(body) > MAX_BODY_BYTES:
                    return ("reject", "413")
            elif isinstance(event, h11.EndOfMessage):
                break
            elif isinstance(event, h11.ConnectionClosed) or event is None:
                return ("incomplete", None)
    except h11.RemoteProtocolError:
        return ("reject", "400")
    target = req_event.target.decode("latin-1")
    headers = [(n.decode("latin-1"), v.decode("latin-1"))
               for n, v in req_event.headers]
    return ("ok", Request(method=req_event.method.decode("ascii"),
                          target=target, path=target.split("?", 1)[0],
                          headers=headers, body=bytes(body)))


def request_tuple_to_context(tup: RequestTuple, lists: dict) -> Context:
    """Interpreter context for route matching (engine/batch.py owns the
    shared construction)."""
    from ..engine.batch import tuple_to_context

    return tuple_to_context(tup, lists)


class HttpListener:
    """One HTTP(S) listener bound to an address, serving h11 connections."""

    def __init__(
        self,
        name: str,
        host: str,
        port: int,
        services: list,  # (service, is proxy/static objects with .route)
        verdict: VerdictService,
        lists: dict,
        rules_meta: list,  # plan.rules (kept for metrics/introspection)
        captcha: CaptchaManager,
        geoip: Optional[GeoipDB] = None,
        tls_context=None,
        acme_challenges: Optional[dict] = None,
        trust_xff: bool = False,
        xff_token: Optional[str] = None,
        route_indices: Optional[list] = None,
    ):
        self.name = name
        self.host = host
        self.port = port
        self.services = services
        self.verdict = verdict
        self.lists = lists
        self.rules_meta = rules_meta
        self.captcha = captcha
        self.geoip = geoip
        self.tls_context = tls_context
        self.acme_challenges = acme_challenges
        # When this listener runs as the control plane BEHIND the native
        # data plane (which injects x-forwarded-for), the captcha client
        # id must bind to the REAL client address, not the proxy's.
        # XFF is client-forgeable, so trust is TOKEN-BOUND when
        # xff_token is set: only requests carrying the native plane's
        # per-boot x-pingoo-internal token are trusted — any other
        # local process dialing the loopback port cannot spoof client
        # identity for captcha binding or IP rules. A bare
        # trust_xff=True (no token) trusts unconditionally; only for
        # closed test rigs. When xff_token is set it alone decides
        # (handle_request branches on it before consulting trust_xff).
        self.trust_xff = trust_xff
        self.xff_token = xff_token
        # Per-service columns of the batched verdict carrying the route
        # predicates (plan.route_index); None entries (or no list) fall
        # back to per-request interpretation of service.route.
        self.route_indices = route_indices
        self.stats = ListenerStats()
        self._server: Optional[asyncio.AbstractServer] = None
        # Unified telemetry (obs/): the listener's counters fold into
        # the shared registry at scrape time (one collector per
        # listener, labels disambiguate), the access-log sampler emits
        # trace-id-carrying structured lines.
        self._access_log = AccessLogSampler(name)
        # Streaming body inspection (ISSUE 13, docs/BODY_STREAMING.md):
        # the listener buffers whole bodies, but the scan still runs
        # the SAME windowed chunk-carry engine the native plane's
        # sidecar uses (bodyscan.scan_buffered), so one payload yields
        # one verdict on both planes. Unlike the native plane, this
        # covers h2 streams too (their bodies buffer through the same
        # Request). A broken scanner fails open to metadata-only.
        self._body_scanner = None
        if bodyscan.body_inspect_enabled():
            try:
                self._body_scanner = bodyscan.BodyScanner()
                self._body_scanner.attach_metrics("python")
            except Exception:
                self._body_scanner = None
                self.stats.body_fail_open += 1
        REGISTRY.register_collector(self._export_metrics)

    def _export_metrics(self) -> None:
        """Registry collector: mirror ListenerStats into the shared
        metric names (obs/schema.SHARED_METRICS) so the Prometheus
        exposition carries this listener next to the verdict pipeline
        histograms and (under the native plane) the ring telemetry."""
        lab = {"plane": "python", "listener": self.name}
        for name, value in (
                ("pingoo_requests_total", self.stats.requests),
                ("pingoo_blocked_total", self.stats.blocked),
                ("pingoo_captcha_total", self.stats.captcha_served),
                ("pingoo_fail_open_total", self.stats.fail_open)):
            REGISTRY.counter(name, obs_schema.SHARED_METRICS[name],
                             labels=lab).set_total(value)
        REGISTRY.counter(
            "pingoo_body_degrade_total",
            obs_schema.BODY_METRICS["pingoo_body_degrade_total"],
            labels={**lab, "reason": "ladder"},
        ).set_total(self.stats.body_fail_open)
        uptime = time.time() - self.stats.started_at
        REGISTRY.gauge("pingoo_uptime_seconds", "listener uptime",
                       labels=lab).set(round(uptime, 1))

    async def bind(self) -> None:
        # reuse_port: N processes can share the port for zero-downtime
        # upgrades (reference listeners/mod.rs:57-61 SO_REUSEPORT).
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port,
            ssl=self.tls_context, reuse_address=True, reuse_port=True,
            backlog=2048)

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def close(self) -> None:
        if self._body_scanner is not None:
            self._body_scanner.detach_metrics()
        REGISTRY.unregister_collector(self._export_metrics)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection loop -----------------------------------------------------

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername") or ("0.0.0.0", 0)
        # HTTP/2 detection (reference hyper auto builder,
        # http_listener.rs:276-278): ALPN "h2" on TLS connections, the
        # 24-byte client preface on cleartext (prior knowledge).
        initial = b""
        ssl_obj = writer.get_extra_info("ssl_object")
        if ssl_obj is not None:
            if ssl_obj.selected_alpn_protocol() == "h2":
                await self._serve_h2(reader, writer, peer)
                return
        else:
            from .h2 import H2_PREFACE, available as h2_available

            if h2_available():
                while (len(initial) < len(H2_PREFACE)
                       and H2_PREFACE.startswith(initial)):
                    chunk = await reader.read(len(H2_PREFACE) - len(initial))
                    if not chunk:
                        break
                    initial += chunk
                if initial == H2_PREFACE:
                    await self._serve_h2(reader, writer, peer,
                                         initial=initial)
                    return
        conn = h11.Connection(h11.SERVER,
                              max_incomplete_event_size=MAX_HEADER_BYTES)
        if initial:
            conn.receive_data(initial)
        try:
            while True:
                raw = await self._gate_head(conn, reader)
                if raw is not None:
                    writer.write(raw)
                    await writer.drain()
                    break
                event = await self._next_event(conn, reader)
                if event is h11.PAUSED or isinstance(
                        event, (h11.ConnectionClosed, type(None))):
                    break
                if isinstance(event, h11.Request):
                    request = await self._read_request(conn, reader, event)
                    response = await self.handle_request(request, peer)
                    if response.tunnel is not None:
                        await self._pump_tunnel(conn, reader, writer,
                                                response.tunnel)
                        break  # raw bytes flowed: the h1 cycle is over
                    await self._send_response(conn, writer, request, response)
                    if conn.our_state is h11.MUST_CLOSE:
                        break
                    conn.start_next_cycle()
        except h11.RemoteProtocolError as exc:
            # Answer before closing (the native plane does too): 413
            # for the body cap, 400 for everything h11 refused — unless
            # a response already started, where injecting one would
            # corrupt the client's framing.
            try:
                if conn.our_state is h11.IDLE:
                    writer.write(_RAW_413 if "body too large" in str(exc)
                                 else _RAW_400)
                    await writer.drain()
            except (OSError, asyncio.IncompleteReadError):
                pass
        except (OSError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except OSError:
                pass

    async def _pump_tunnel(self, conn, reader, writer, tunnel) -> None:
        """Protocol upgrade (WebSocket): relay the upstream's response
        head verbatim, then splice raw bytes both directions until
        either side closes (reference http_listener.rs:277
        serve_connection_with_upgrades)."""
        up_reader, up_writer, head = tunnel
        try:
            writer.write(head)
            # Bytes the client sent after its upgrade request are
            # already buffered inside h11 — forward them first.
            trailing, _ = conn.trailing_data
            if trailing:
                up_writer.write(trailing)
            await writer.drain()
            await up_writer.drain()

            async def pump(src, dst):
                try:
                    while True:
                        data = await src.read(65536)
                        if not data:
                            break
                        dst.write(data)
                        await dst.drain()
                except (OSError, asyncio.IncompleteReadError):
                    pass
                finally:
                    try:
                        dst.write_eof()
                    except OSError:
                        pass

            await asyncio.gather(pump(reader, up_writer),
                                 pump(up_reader, writer))
        finally:
            try:
                up_writer.close()
            except OSError:
                pass

    async def _gate_head(self, conn, reader) -> Optional[bytes]:
        """Buffer the next request head RAW (h11 sees every byte too —
        this only mirrors, never consumes) and apply the strict gate
        plus the PINGOO_MAX_HEADER_BYTES cap before h11 parses it.
        Returns a raw response to send-and-close (431/400), or None
        when the head passed / the peer closed. h11's trailing_data
        seeds the scan so pipelined requests gate correctly."""
        scan = bytearray(conn.trailing_data[0])
        while _HEAD_END_RE.search(scan) is None:
            if len(scan) > MAX_HEADER_BYTES:
                return _RAW_431
            data = await reader.read(65536)
            if not data:
                return None  # EOF: the event loop settles the state
            conn.receive_data(data)
            scan += data
        end = _HEAD_END_RE.search(scan).end()
        if end > MAX_HEADER_BYTES:
            return _RAW_431
        head = bytes(scan[:end])
        if strict_head_violation(head) is not None:
            return _RAW_400
        cl = declared_content_length(head)
        if cl is not None and cl > MAX_BODY_BYTES:
            return _RAW_413  # eager, like the native plane: never buffer
        return None

    async def _next_event(self, conn, reader):
        while True:
            event = conn.next_event()
            if event is h11.NEED_DATA:
                data = await reader.read(65536)
                conn.receive_data(data)
                if data == b"" and conn.their_state is h11.IDLE:
                    return None
                continue
            return event

    async def _read_request(self, conn, reader, event: h11.Request) -> Request:
        body = bytearray()
        while True:
            ev = await self._next_event(conn, reader)
            if isinstance(ev, h11.Data):
                body += ev.data
                if len(body) > MAX_BODY_BYTES:
                    raise h11.RemoteProtocolError("body too large")
            elif isinstance(ev, h11.EndOfMessage) or ev is None:
                break
        target = event.target.decode("latin-1")
        path = target.split("?", 1)[0]
        headers = [(n.decode("latin-1"), v.decode("latin-1"))
                   for n, v in event.headers]
        return Request(method=event.method.decode("ascii"), target=target,
                       path=path, headers=headers, body=bytes(body))

    async def _send_response(self, conn, writer, request: Request,
                             response: Response) -> None:
        headers = [(k, v) for k, v in response.headers]
        if response.stream_path is not None and request.method != "HEAD":
            # Large static files stream in chunks — never slurped
            # (http_static_site_service.rs:238-256 ReaderStream parity).
            size = os.path.getsize(response.stream_path)
            headers.append(("content-length", str(size)))
            writer.write(conn.send(h11.Response(
                status_code=response.status,
                headers=[(k.encode(), v.encode()) for k, v in headers])))
            with open(response.stream_path, "rb") as f:
                while True:
                    chunk = f.read(65536)
                    if not chunk:
                        break
                    writer.write(conn.send(h11.Data(data=chunk)))
                    await writer.drain()
            writer.write(conn.send(h11.EndOfMessage()))
            await writer.drain()
            return
        if response.stream_path is not None:  # HEAD on a streamed file
            body = b""
            headers.append(
                ("content-length", str(os.path.getsize(response.stream_path))))
        else:
            body = b"" if request.method == "HEAD" else response.body
            headers.append(("content-length", str(len(response.body))))
        writer.write(conn.send(h11.Response(
            status_code=response.status,
            headers=[(k.encode(), v.encode()) for k, v in headers])))
        if body:
            writer.write(conn.send(h11.Data(data=body)))
        writer.write(conn.send(h11.EndOfMessage()))
        await writer.drain()

    # -- HTTP/2 connection loop ---------------------------------------------

    async def _serve_h2(self, reader, writer, peer, initial=b"") -> None:
        """Serve one h2 connection: every stream's request runs through
        the SAME handle_request hot path as h1 (the reference's hyper
        auto builder likewise multiplexes into one service_fn). Streams
        are handled CONCURRENTLY — one slow upstream must not stall the
        other multiplexed streams or frame processing — with writes
        serialized through a lock."""
        from .h2 import H2ServerSession

        write_lock = asyncio.Lock()
        tasks: set = set()

        async def flush():
            out = session.pull()
            if out:
                async with write_lock:
                    writer.write(out)
                    await writer.drain()

        async def handle_stream(sid, hdrs, body):
            req = self._h2_to_request(hdrs, body)
            if req is None:
                session.submit_response(sid, 400,
                                        [("content-type", "text/plain")],
                                        b"Bad Request")
                await flush()
                return
            response = await self.handle_request(req, peer)
            body_out = response.body
            content_length = None
            if response.stream_path is not None:
                if req.method == "HEAD":
                    # Advertise the real entity size without reading it.
                    body_out = b""
                    content_length = os.path.getsize(response.stream_path)
                else:
                    # h2 responses are submitted whole; large static
                    # files load here (streamed DATA frames are a
                    # future refinement).
                    with open(response.stream_path, "rb") as f:
                        body_out = f.read()
            elif req.method == "HEAD":
                content_length = len(response.body)
                body_out = b""
            session.submit_response(sid, response.status, response.headers,
                                    body_out, content_length=content_length)
            await flush()

        def on_request(sid, hdrs, body):
            task = asyncio.ensure_future(handle_stream(sid, hdrs, body))
            tasks.add(task)
            task.add_done_callback(tasks.discard)

        session = H2ServerSession(on_request)
        try:
            if initial and not session.feed(initial):
                return
            while True:
                await flush()
                data = await reader.read(65536)
                if not data or not session.feed(data):
                    break
        except (OSError, asyncio.IncompleteReadError):
            pass
        finally:
            for task in list(tasks):
                task.cancel()
            try:
                await flush()
            except OSError:
                pass
            session.close()
            try:
                writer.close()
            except OSError:
                pass

    @staticmethod
    def _h2_to_request(hdrs: list, body: bytes) -> Optional[Request]:
        """h2 pseudo-headers -> the Request shape the h1 path uses; the
        :authority travels as a host header (get_host reads it like
        hyper's uri.host for h2, http_listener.rs:284-289)."""
        pseudo = {k: v for k, v in hdrs if k.startswith(b":")}
        method = pseudo.get(b":method")
        path = pseudo.get(b":path")
        if not method or not path:
            return None
        headers = [(k.decode("latin-1"), v.decode("latin-1"))
                   for k, v in hdrs if not k.startswith(b":")]
        authority = pseudo.get(b":authority")
        if authority:
            headers.insert(0, ("host", authority.decode("latin-1")))
        target = path.decode("latin-1")
        return Request(method=method.decode("latin-1"), target=target,
                       path=target.split("?", 1)[0], headers=headers,
                       body=body)

    # -- the hot path --------------------------------------------------------

    async def handle_request(self, req: Request, peer) -> Response:
        """Trace-instrumented entry: every request gets a trace id that
        propagates into the verdict batch (RequestTuple.trace_id),
        returns in the x-pingoo-trace-id response header, and lands in
        the sampled structured access log."""
        t0 = time.monotonic()
        trace_id = new_trace_id()
        response = await self._handle_request(req, peer, trace_id)
        response.headers = list(response.headers) + [
            (TRACE_HEADER, trace_id)]
        self._access_log.maybe_log(
            trace_id=trace_id, method=req.method, path=req.path,
            status=response.status, client_ip=str(peer[0]),
            duration_ms=(time.monotonic() - t0) * 1e3)
        return response

    async def _handle_request(self, req: Request, peer,
                              trace_id: str = "") -> Response:
        self.stats.requests += 1
        client_ip, client_port = str(peer[0]), int(peer[1])
        trusted = self.trust_xff
        token = None
        for name, value in req.headers:
            if name.lower() == "x-pingoo-internal":
                token = value
                break
        if self.xff_token is not None:
            import hmac as _hmac

            # bytes compare: compare_digest raises TypeError on
            # non-ASCII str input, and the header is attacker-supplied.
            trusted = token is not None and _hmac.compare_digest(
                token.encode("latin-1", "replace"),
                self.xff_token.encode("latin-1", "replace"))
        if token is not None:
            # The token header never travels further (rules context,
            # upstream hops): strip it regardless of validity. Skipped
            # entirely on the common no-token request.
            req.headers = [(n, v) for n, v in req.headers
                           if n.lower() != "x-pingoo-internal"]
        if trusted:
            for name, value in req.headers:
                if name.lower() == "x-forwarded-for":
                    first = value.split(",")[0].strip()
                    if first:
                        client_ip = first
                    break
        host, user_agent = extract_request_fields(req)

        geoip_record = GeoipRecord()
        if self.geoip is not None:
            try:
                geoip_record = self.geoip.lookup(client_ip)
            except (AddressNotFound, ValueError):
                pass

        client_id = generate_captcha_client_id(client_ip, user_agent, host)
        cookies = parse_cookies(req.headers)

        request_ctx = RequestContext(
            client_ip=client_ip, client_port=client_port,
            asn=geoip_record.asn, country=geoip_record.country,
            geoip_enabled=self.geoip is not None,
            tls=self.tls_context is not None, host=host)

        # Empty/oversized UA -> 403 (:196-198).
        if not user_agent:
            self.stats.blocked += 1
            return blocked_response()

        # ACME http-01 (host/acme.py; the reference answers challenges at
        # TLS-accept time instead, listeners/mod.rs:130-141).
        if self.acme_challenges is not None and req.path.startswith(
                "/.well-known/acme-challenge/"):
            token = req.path.rsplit("/", 1)[-1]
            keyauth = self.acme_challenges.get(token)
            if keyauth:
                return Response(200, [("content-type", "text/plain")],
                                keyauth.encode())
            return not_found_response()

        if req.path.startswith(CAPTCHA_PATH_PREFIX):
            status, headers, body = self.captcha.serve(
                req.method, req.path, req.body, cookies, client_id)
            return Response(status, headers, body)

        if req.path == "/__pingoo/metrics":
            return self._metrics_response(req)

        if req.path == "/__pingoo/profile":
            return await self._profile_response(req)

        if req.path == "/__pingoo/flightrecorder":
            return self._flightrecorder_response()

        if req.path == "/__pingoo/compileledger":
            return self._compileledger_response()

        if req.path == "/__pingoo/timeline":
            return self._timeline_response()

        if req.path == "/__pingoo/explain":
            return await self._explain_response(req, request_ctx)

        # Captcha-verified cookie: invalid -> challenge page (:222-236).
        captcha_verified = False
        verified_cookie = cookies.get(CAPTCHA_VERIFIED_COOKIE)
        if verified_cookie is not None:
            if self.captcha.is_verified(verified_cookie, client_id):
                captcha_verified = True
            else:
                return self._serve_captcha()

        tup = RequestTuple(
            host=host, url=req.target, path=req.path, method=req.method,
            user_agent=user_agent, ip=client_ip, remote_port=client_port,
            asn=geoip_record.asn, country=geoip_record.country,
            trace_id=trace_id)

        # RULES LOOP (:251-264): the engine's action lanes reproduce the
        # reference loop for both captcha states (engine/verdict.py
        # action_lanes — verified clients skip Captcha actions but still
        # block on any matched Block).
        verdict = await self.verdict.evaluate(tup)
        if verdict.degraded:
            self.stats.fail_open += 1
        action = verdict.action_for(captcha_verified)
        # Body-verdict merge (ISSUE 13): skipped when metadata alone
        # already decides — the native plane aborts inspection on the
        # same condition, so both planes scan the same set of requests.
        if (action == 0 and req.body and not verdict.degraded
                and self._body_scanner is not None):
            bv = self._scan_body(req.body)
            if bv is not None and not bv.degraded:
                meta_byte = ((verdict.action & 0x3)
                             | (0x4 if verdict.verified_block else 0))
                merged = bodyscan.merge_actions(
                    meta_byte, bv.unverified, bv.verified_block)
                verdict.action = merged & 0x3
                verdict.verified_block = bool(merged & 0x4)
                action = verdict.action_for(captcha_verified)
        if action == 1:
            self.stats.blocked += 1
            return blocked_response()
        if action == 2:
            return self._serve_captcha()

        # ROUTING LOOP (:266-270): route predicates ride the SAME
        # batched verdict as the rules (plan route pseudo-columns) —
        # no per-request tree-walk on the hot path. Services without a
        # compiled column interpret their route inline (same semantics).
        route_ctx = None
        for j, service in enumerate(self.services):
            idx = (self.route_indices[j]
                   if self.route_indices and j < len(self.route_indices)
                   else None)
            if idx is not None and not verdict.degraded:
                routed = bool(verdict.matched[idx])
            else:
                # No compiled column, or the engine failed and matched
                # is a fail-open placeholder: interpret the route so a
                # broken engine degrades to slow routing, not to 404s.
                if route_ctx is None:
                    route_ctx = request_tuple_to_context(tup, self.lists)
                routed = match_route(service.route, route_ctx)
            if routed:
                return await service.handle(req, request_ctx)
        return not_found_response()

    def _scan_body(self, payload: bytes):
        """Run the buffered body through the windowed chunk-carry scan;
        None (metadata-only, counted) on any scanner fault — inspection
        fails open, never closed."""
        try:
            return self._body_scanner.scan_buffered(payload)
        except Exception:
            self.stats.body_fail_open += 1
            self._body_scanner.flows.clear()  # no half-scanned carry
            return None

    def _serve_captcha(self) -> Response:
        from .captcha import CAPTCHA_PAGE

        self.stats.captcha_served += 1
        return Response(403, [("content-type", "text/html; charset=utf-8"),
                              ("server", "pingoo")], CAPTCHA_PAGE.encode())

    @staticmethod
    def _accepts_json(req: Request) -> bool:
        for name, value in req.headers:
            if name.lower() == "accept":
                return "application/json" in value.lower()
        return False

    def _metrics_response(self, req: Request) -> Response:
        """Content-negotiated exposition: Prometheus text by default
        (what a scraper or plain curl sees), the back-compatible JSON
        schema under Accept: application/json."""
        if not self._accepts_json(req):
            return Response(
                200,
                [("content-type",
                  "text/plain; version=0.0.4; charset=utf-8")],
                REGISTRY.prometheus_text().encode())
        uptime = time.time() - self.stats.started_at
        payload = {
            "listener": self.name,
            "uptime_s": round(uptime, 1),
            "requests": self.stats.requests,
            "blocked": self.stats.blocked,
            "captcha_served": self.stats.captcha_served,
            "fail_open": self.stats.fail_open,
            "req_per_s": round(self.stats.requests / uptime, 2) if uptime else 0,
            "verdict": self.verdict.stats.snapshot(),
            "pipeline": self.verdict.pipeline_snapshot(),
            "ladder": self.verdict.ladder.snapshot(),
        }
        return Response(200, [("content-type", "application/json")],
                        json.dumps(payload).encode())

    def _flightrecorder_response(self) -> Response:
        """Dump every flight recorder registered in this process (the
        listener plane's, plus the sidecar plane's when co-resident) —
        the /__pingoo/flightrecorder endpoint (docs/OBSERVABILITY.md)."""
        from ..obs.flightrecorder import dump_all

        return Response(200, [("content-type", "application/json")],
                        json.dumps(dump_all()).encode())

    def _compileledger_response(self) -> Response:
        """Dump the process-wide compile ledger (every jit trace/compile
        this process paid, with fn kind / shape context / wall ms) —
        the /__pingoo/compileledger endpoint (ISSUE 17)."""
        from ..obs.perf import get_compile_ledger

        return Response(200, [("content-type", "application/json")],
                        json.dumps(get_compile_ledger().snapshot()).encode())

    def _timeline_response(self) -> Response:
        """Chrome-trace (catapult) JSON of the bounded cross-plane span
        store — loads directly in Perfetto; empty traceEvents (bar the
        metadata rows) when PINGOO_TIMELINE_SAMPLE is off."""
        from ..obs.timeline import get_timeline

        return Response(200, [("content-type", "application/json")],
                        get_timeline().chrome_trace_json().encode())

    async def _explain_response(self, req: Request,
                                request_ctx: RequestContext) -> Response:
        """GET /__pingoo/explain?path=/x[&method=&host=&url=&ua=&ip=
        &asn=&country=&port=]: re-run one synthetic request through the
        REAL batched verdict path and the interpreter oracle, returning
        per-rule / per-stage provenance JSON (VerdictService.explain).
        Unspecified client fields default to the CALLING request's
        (ip/asn/country), so `curl .../__pingoo/explain?path=/probe`
        explains that path for the caller's own network identity."""
        from urllib.parse import parse_qs, unquote

        query = parse_qs(req.target.partition("?")[2],
                         keep_blank_values=True)

        def q(name, default=""):
            vals = query.get(name)
            return unquote(vals[0]) if vals else default

        path = q("path", "/")
        try:
            asn = int(q("asn", str(request_ctx.asn)) or 0)
            port = int(q("port", str(request_ctx.client_port)) or 0)
        except ValueError:
            return Response(400, [("content-type", "application/json")],
                            b'{"error": "asn/port must be integers"}')
        tup = RequestTuple(
            host=q("host", request_ctx.host),
            url=q("url", path),
            path=path,
            method=q("method", "GET") or "GET",
            user_agent=q("ua", q("user_agent", "pingoo-explain")),
            ip=q("ip", request_ctx.client_ip),
            remote_port=port,
            asn=asn,
            country=q("country", request_ctx.country),
            trace_id=new_trace_id())
        payload = await self.verdict.explain(tup)
        return Response(200, [("content-type", "application/json")],
                        json.dumps(payload).encode())

    async def _profile_response(self, req: Request) -> Response:
        """On-demand bounded jax.profiler window:
        GET /__pingoo/profile?seconds=N (default 3, cap 30). 409 when a
        capture (or the boot-time PINGOO_PROFILE_DIR trace) is live."""
        seconds = 3.0
        query = req.target.partition("?")[2]
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if k == "seconds":
                try:
                    seconds = float(v)
                except ValueError:
                    pass
        result = await self.verdict.capture_profile(seconds)
        if "error" in result:
            status = 409 if "already active" in result["error"] else 503
        else:
            status = 200
        return Response(status, [("content-type", "application/json")],
                        json.dumps(result).encode())
