"""HTTP/2 via a ctypes binding to the system libnghttp2.

The reference serves h1+h2 through hyper's auto builder
(pingoo/listeners/http_listener.rs:276-278) and proxies upstream over
h1/h2 (services/http_proxy_service.rs:54-71). This environment ships no
Python h2/hpack packages, but libnghttp2.so.14 (the reference C HTTP/2
implementation) is present — this module declares the small ABI surface
needed and wraps it in two sans-io session objects:

  H2ServerSession — feed()/pull() byte pump + completed-request events;
    submit_response() answers a stream (HPACK, flow control, framing all
    handled by nghttp2).
  H2ClientSession — submit_request() -> stream id; completed-response
    events. Used for h2 prior-knowledge upstream proxying.

Sessions are sans-io on purpose: the asyncio listener (host/httpd.py)
and proxy service own the sockets and drive feed/pull, exactly like the
h1 path drives h11.
"""

from __future__ import annotations

import ctypes
from ctypes import (
    CFUNCTYPE,
    POINTER,
    Structure,
    c_char_p,
    c_int,
    c_int32,
    c_size_t,
    c_ssize_t,
    c_uint8,
    c_uint32,
    c_void_p,
    cast,
)
from typing import Callable, Optional

H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

NGHTTP2_NV_FLAG_NONE = 0
NGHTTP2_FLAG_END_STREAM = 0x1
NGHTTP2_FRAME_DATA = 0
NGHTTP2_FRAME_HEADERS = 1
NGHTTP2_DATA_FLAG_EOF = 0x1
NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS = 3
# One connection may not park unbounded streams (each buffers up to the body
# cap): advertise the same 128-stream ceiling the native plane enforces.
MAX_CONCURRENT_STREAMS = 128


class SettingsEntry(Structure):
    # nghttp2_settings_entry
    _fields_ = [("settings_id", c_int32), ("value", c_uint32)]


class NV(Structure):
    _fields_ = [("name", c_char_p), ("value", c_char_p),
                ("namelen", c_size_t), ("valuelen", c_size_t),
                ("flags", c_uint8)]


class FrameHd(Structure):
    # nghttp2_frame_hd: every nghttp2_frame union member starts with it.
    _fields_ = [("length", c_size_t), ("stream_id", c_int32),
                ("type", c_uint8), ("flags", c_uint8),
                ("reserved", c_uint8)]


class DataSource(ctypes.Union):
    _fields_ = [("fd", c_int), ("ptr", c_void_p)]


READ_CB = CFUNCTYPE(c_ssize_t, c_void_p, c_int32, POINTER(c_uint8), c_size_t,
                    POINTER(c_uint32), c_void_p, c_void_p)


class DataProvider(Structure):
    _fields_ = [("source", DataSource), ("read_callback", READ_CB)]


ON_HEADER_CB = CFUNCTYPE(c_int, c_void_p, c_void_p, POINTER(c_uint8),
                         c_size_t, POINTER(c_uint8), c_size_t, c_uint8,
                         c_void_p)
ON_FRAME_RECV_CB = CFUNCTYPE(c_int, c_void_p, c_void_p, c_void_p)
ON_DATA_CHUNK_CB = CFUNCTYPE(c_int, c_void_p, c_uint8, c_int32,
                             POINTER(c_uint8), c_size_t, c_void_p)
ON_STREAM_CLOSE_CB = CFUNCTYPE(c_int, c_void_p, c_int32, c_uint32, c_void_p)
ON_BEGIN_HEADERS_CB = CFUNCTYPE(c_int, c_void_p, c_void_p, c_void_p)

_lib = None


def load_lib():
    """-> the nghttp2 CDLL, or None when unavailable (h2 then disabled)."""
    global _lib
    if _lib is not None:
        return _lib
    for name in ("libnghttp2.so.14", "libnghttp2.so"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        return None
    lib.nghttp2_session_callbacks_new.argtypes = [POINTER(c_void_p)]
    lib.nghttp2_session_callbacks_new.restype = c_int
    lib.nghttp2_session_callbacks_del.argtypes = [c_void_p]
    for fn, cbt in (
        ("nghttp2_session_callbacks_set_on_header_callback", ON_HEADER_CB),
        ("nghttp2_session_callbacks_set_on_frame_recv_callback",
         ON_FRAME_RECV_CB),
        ("nghttp2_session_callbacks_set_on_data_chunk_recv_callback",
         ON_DATA_CHUNK_CB),
        ("nghttp2_session_callbacks_set_on_stream_close_callback",
         ON_STREAM_CLOSE_CB),
        ("nghttp2_session_callbacks_set_on_begin_headers_callback",
         ON_BEGIN_HEADERS_CB),
    ):
        getattr(lib, fn).argtypes = [c_void_p, cbt]
    lib.nghttp2_session_server_new.argtypes = [POINTER(c_void_p), c_void_p,
                                               c_void_p]
    lib.nghttp2_session_server_new.restype = c_int
    lib.nghttp2_session_client_new.argtypes = [POINTER(c_void_p), c_void_p,
                                               c_void_p]
    lib.nghttp2_session_client_new.restype = c_int
    lib.nghttp2_session_del.argtypes = [c_void_p]
    lib.nghttp2_session_mem_recv.argtypes = [c_void_p, c_char_p, c_size_t]
    lib.nghttp2_session_mem_recv.restype = c_ssize_t
    lib.nghttp2_session_mem_send.argtypes = [c_void_p, POINTER(c_void_p)]
    lib.nghttp2_session_mem_send.restype = c_ssize_t
    lib.nghttp2_submit_settings.argtypes = [c_void_p, c_uint8, c_void_p,
                                            c_size_t]
    lib.nghttp2_submit_settings.restype = c_int
    lib.nghttp2_submit_response.argtypes = [c_void_p, c_int32, POINTER(NV),
                                            c_size_t, POINTER(DataProvider)]
    lib.nghttp2_submit_response.restype = c_int
    lib.nghttp2_submit_request.argtypes = [c_void_p, c_void_p, POINTER(NV),
                                           c_size_t, POINTER(DataProvider),
                                           c_void_p]
    lib.nghttp2_submit_request.restype = c_int32
    lib.nghttp2_session_want_read.argtypes = [c_void_p]
    lib.nghttp2_session_want_read.restype = c_int
    lib.nghttp2_session_want_write.argtypes = [c_void_p]
    lib.nghttp2_session_want_write.restype = c_int
    _lib = lib
    return lib


def available() -> bool:
    return load_lib() is not None


def _nv_array(headers: list[tuple[bytes, bytes]]):
    arr = (NV * len(headers))()
    # Keep the encoded byte strings alive alongside the array.
    keep = []
    for i, (name, value) in enumerate(headers):
        keep.append((name, value))
        arr[i].name = name
        arr[i].value = value
        arr[i].namelen = len(name)
        arr[i].valuelen = len(value)
        arr[i].flags = NGHTTP2_NV_FLAG_NONE
    return arr, keep


class _Stream:
    __slots__ = ("headers", "body", "headers_done", "closed", "send_body",
                 "send_off")

    def __init__(self):
        self.headers: list[tuple[bytes, bytes]] = []
        self.body = bytearray()
        self.headers_done = False
        self.closed = False
        self.send_body = b""
        self.send_off = 0


class _Session:
    """Shared sans-io plumbing for server/client sessions."""

    def __init__(self, server: bool):
        lib = load_lib()
        if lib is None:
            raise RuntimeError("libnghttp2 unavailable")
        self._lib = lib
        self._streams: dict[int, _Stream] = {}
        self.dead = False

        # Per-instance callback closures (kept referenced for GC safety).
        self._cbs = [
            ON_HEADER_CB(self._on_header),
            ON_FRAME_RECV_CB(self._on_frame_recv),
            ON_DATA_CHUNK_CB(self._on_data_chunk),
            ON_STREAM_CLOSE_CB(self._on_stream_close),
        ]
        self._read_cb = READ_CB(self._data_read)

        callbacks = c_void_p()
        lib.nghttp2_session_callbacks_new(ctypes.byref(callbacks))
        lib.nghttp2_session_callbacks_set_on_header_callback(
            callbacks, self._cbs[0])
        lib.nghttp2_session_callbacks_set_on_frame_recv_callback(
            callbacks, self._cbs[1])
        lib.nghttp2_session_callbacks_set_on_data_chunk_recv_callback(
            callbacks, self._cbs[2])
        lib.nghttp2_session_callbacks_set_on_stream_close_callback(
            callbacks, self._cbs[3])
        self._session = c_void_p()
        new = (lib.nghttp2_session_server_new if server
               else lib.nghttp2_session_client_new)
        rv = new(ctypes.byref(self._session), callbacks, None)
        lib.nghttp2_session_callbacks_del(callbacks)
        if rv != 0:
            raise RuntimeError(f"nghttp2 session init: {rv}")
        if server:
            entry = SettingsEntry(NGHTTP2_SETTINGS_MAX_CONCURRENT_STREAMS,
                                  MAX_CONCURRENT_STREAMS)
            lib.nghttp2_submit_settings(
                self._session, 0, ctypes.byref(entry), 1)
        else:
            lib.nghttp2_submit_settings(self._session, 0, None, 0)

    def close(self) -> None:
        if self._session:
            self._lib.nghttp2_session_del(self._session)
            self._session = c_void_p()

    # -- byte pump -----------------------------------------------------------

    def feed(self, data: bytes) -> bool:
        """Process inbound bytes; False = protocol error, hang up."""
        n = self._lib.nghttp2_session_mem_recv(self._session, data, len(data))
        if n < 0 or n != len(data):
            self.dead = True
            return False
        return True

    def pull(self) -> bytes:
        """Outbound bytes nghttp2 wants on the wire (may be b"")."""
        out = bytearray()
        while True:
            ptr = c_void_p()
            n = self._lib.nghttp2_session_mem_send(self._session,
                                                   ctypes.byref(ptr))
            if n <= 0:
                break
            out += ctypes.string_at(ptr, n)
        return bytes(out)

    def wants_more(self) -> bool:
        return bool(self._lib.nghttp2_session_want_read(self._session) or
                    self._lib.nghttp2_session_want_write(self._session))

    # -- nghttp2 callbacks ---------------------------------------------------

    def _stream(self, stream_id: int) -> _Stream:
        st = self._streams.get(stream_id)
        if st is None:
            st = _Stream()
            self._streams[stream_id] = st
        return st

    def _on_header(self, session, frame, name, namelen, value, valuelen,
                   flags, user_data):
        hd = cast(frame, POINTER(FrameHd)).contents
        st = self._stream(hd.stream_id)
        st.headers.append((ctypes.string_at(name, namelen),
                           ctypes.string_at(value, valuelen)))
        return 0

    def _on_frame_recv(self, session, frame, user_data):
        hd = cast(frame, POINTER(FrameHd)).contents
        if hd.type == NGHTTP2_FRAME_HEADERS:
            st = self._stream(hd.stream_id)
            st.headers_done = True
            if hd.flags & NGHTTP2_FLAG_END_STREAM:
                self._on_message(hd.stream_id, st)
        elif hd.type == NGHTTP2_FRAME_DATA and \
                hd.flags & NGHTTP2_FLAG_END_STREAM:
            st = self._stream(hd.stream_id)
            self._on_message(hd.stream_id, st)
        return 0

    def _on_data_chunk(self, session, flags, stream_id, data, length,
                       user_data):
        st = self._stream(stream_id)
        if len(st.body) + length > 16 * 1024 * 1024:
            return 0x01  # NGHTTP2_ERR_CALLBACK_FAILURE -> connection error
        st.body += ctypes.string_at(data, length)
        return 0

    def _on_stream_close(self, session, stream_id, error_code, user_data):
        st = self._streams.pop(stream_id, None)
        if st is not None and not st.closed:
            st.closed = True
            self._on_closed(stream_id, st, error_code)
        return 0

    def _data_read(self, session, stream_id, buf, length, data_flags, source,
                   user_data):
        st = self._streams.get(stream_id)
        body = st.send_body if st else b""
        off = st.send_off if st else 0
        n = min(len(body) - off, length)
        if n > 0:
            ctypes.memmove(buf, body[off: off + n], n)
            if st:
                st.send_off = off + n
        if st is None or st.send_off >= len(body):
            data_flags[0] = NGHTTP2_DATA_FLAG_EOF
        return n

    # -- overridden by subclasses -------------------------------------------

    def _on_message(self, stream_id: int, st: _Stream) -> None:
        raise NotImplementedError

    def _on_closed(self, stream_id: int, st: _Stream, error: int) -> None:
        pass


class H2ServerSession(_Session):
    """Server half: completed requests surface via `on_request(stream_id,
    headers, body)`; answer with submit_response()."""

    def __init__(self, on_request: Callable[[int, list, bytes], None]):
        super().__init__(server=True)
        self._on_request = on_request

    def _on_message(self, stream_id: int, st: _Stream) -> None:
        self._on_request(stream_id, list(st.headers), bytes(st.body))

    def submit_response(self, stream_id: int, status: int,
                        headers: list[tuple[str, str]], body: bytes,
                        content_length: Optional[int] = None) -> None:
        """Answer a stream. `content_length` overrides the advertised
        length (HEAD responses carry the real entity size with an empty
        body). A stream the peer already reset is dropped silently —
        re-creating its state would pin the body forever."""
        st = self._streams.get(stream_id)
        if st is None or st.closed:
            return  # peer reset the stream while the handler ran
        nv_list = [(b":status", str(status).encode())]
        for k, v in headers:
            lk = k.lower()
            if lk in ("connection", "keep-alive", "transfer-encoding",
                      "content-length", "upgrade"):
                continue  # connection-specific headers are illegal in h2
            nv_list.append((lk.encode("latin-1"), v.encode("latin-1")))
        length = len(body) if content_length is None else content_length
        nv_list.append((b"content-length", str(length).encode()))
        arr, keep = _nv_array(nv_list)
        st.send_body = body
        st.send_off = 0
        provider = DataProvider()
        provider.read_callback = self._read_cb
        rv = self._lib.nghttp2_submit_response(
            self._session, stream_id, arr, len(nv_list),
            ctypes.byref(provider))
        if rv != 0:
            self._streams.pop(stream_id, None)
        del keep


class H2ClientSession(_Session):
    """Client half (h2 prior-knowledge upstream): submit_request() ->
    stream id; completed responses surface via `on_response(stream_id,
    headers, body, error)` (error != 0 => stream reset)."""

    def __init__(self,
                 on_response: Callable[[int, list, bytes, int], None]):
        super().__init__(server=False)
        self._on_response = on_response
        self._done: set[int] = set()

    def _on_message(self, stream_id: int, st: _Stream) -> None:
        self._done.add(stream_id)
        self._on_response(stream_id, list(st.headers), bytes(st.body), 0)

    def _on_closed(self, stream_id: int, st: _Stream, error: int) -> None:
        if stream_id not in self._done:
            self._on_response(stream_id, list(st.headers), bytes(st.body),
                              error or 1)
        self._done.discard(stream_id)

    def submit_request(self, method: str, scheme: str, authority: str,
                       path: str, headers: list[tuple[str, str]],
                       body: bytes = b"") -> int:
        nv_list = [(b":method", method.encode()),
                   (b":scheme", scheme.encode()),
                   (b":authority", authority.encode("latin-1")),
                   (b":path", path.encode("latin-1"))]
        for k, v in headers:
            lk = k.lower()
            if lk in ("connection", "keep-alive", "transfer-encoding",
                      "host", "content-length", "upgrade", "te"):
                continue
            nv_list.append((lk.encode("latin-1"), v.encode("latin-1")))
        if body:
            nv_list.append((b"content-length", str(len(body)).encode()))
        arr, keep = _nv_array(nv_list)
        provider = DataProvider()
        provider.read_callback = self._read_cb
        stream_id = self._lib.nghttp2_submit_request(
            self._session, None, arr, len(nv_list),
            ctypes.byref(provider) if body else None, None)
        del keep  # nv bytes were copied by nghttp2 during the call
        if stream_id > 0:
            # ALWAYS materialize the stream entry — a server can
            # RST_STREAM before any response headers arrive, and
            # _on_stream_close only surfaces the failure for tracked
            # streams. (The provider struct is copied at submit time;
            # body bytes are served later through _data_read.)
            st = self._stream(stream_id)
            st.send_body = body
            st.send_off = 0
        return stream_id


class H2UpstreamConnection:
    """One h2 prior-knowledge upstream connection multiplexing requests
    (asyncio; the proxy-service side of http_proxy_service.rs:54-71).

    request() submits a stream and awaits its response; a connection
    error fails every in-flight future (callers map that to 502)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._session: Optional[H2ClientSession] = None
        self._reader = None
        self._writer = None
        self._futures: dict[int, "object"] = {}
        self._read_task = None
        self._lock = None

    @property
    def alive(self) -> bool:
        return (self._session is not None and not self._session.dead
                and self._writer is not None)

    async def connect(self, ssl=None, server_hostname=None) -> None:
        import asyncio

        self._lock = self._lock or asyncio.Lock()
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=ssl, server_hostname=server_hostname)
        self._session = H2ClientSession(self._on_response)
        await self._flush()
        self._read_task = asyncio.get_running_loop().create_task(
            self._read_loop())

    def _on_response(self, stream_id, headers, body, error):
        fut = self._futures.pop(stream_id, None)
        if fut is not None and not fut.done():
            if error:
                fut.set_exception(ConnectionError(f"h2 stream reset {error}"))
            else:
                fut.set_result((headers, body))

    async def _flush(self) -> None:
        out = self._session.pull()
        if out:
            self._writer.write(out)
            await self._writer.drain()

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data or not self._session.feed(data):
                    break
                await self._flush()
        except Exception:
            pass
        finally:
            self._fail_all(ConnectionError("h2 upstream connection lost"))

    def _fail_all(self, exc: Exception) -> None:
        if self._session is not None:
            self._session.dead = True
        for fut in list(self._futures.values()):
            if not fut.done():
                fut.set_exception(exc)
        self._futures.clear()

    async def request(self, method: str, authority: str, path: str,
                      headers: list[tuple[str, str]], body: bytes = b""
                      ) -> tuple[int, list[tuple[str, str]], bytes]:
        import asyncio

        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            stream_id = self._session.submit_request(
                method, "http", authority, path, headers, body)
            if stream_id <= 0:
                raise ConnectionError(f"h2 submit failed: {stream_id}")
            self._futures[stream_id] = fut
            await self._flush()
        raw_headers, raw_body = await fut
        status = 502
        out: list[tuple[str, str]] = []
        for name, value in raw_headers:
            if name == b":status":
                status = int(value)
            elif not name.startswith(b":"):
                out.append((name.decode("latin-1"), value.decode("latin-1")))
        return status, out, raw_body

    async def close(self) -> None:
        if self._read_task is not None:
            self._read_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        if self._session is not None:
            self._session.close()
            self._session = None
