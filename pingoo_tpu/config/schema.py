"""Validated configuration model.

Mirrors the reference's two-stage config pipeline (pingoo/config/
config_file.rs -> config.rs): raw YAML is parsed into a file-shaped dict,
then converted into these validated dataclasses. Expressions (rules and
service routes) are compiled at load time so config errors fail fast at
boot (reference config.rs:255-269, config_file.rs:257-265).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from ..expr import Program


class ListenerProtocol(enum.Enum):
    TCP = "tcp"
    TCP_AND_TLS = "tcp+tls"
    HTTP = "http"
    HTTPS = "https"

    @staticmethod
    def parse(text: str) -> "ListenerProtocol":
        for proto in ListenerProtocol:
            if proto.value == text:
                return proto
        raise ConfigError(f"{text} is not a valid protocol")

    @property
    def is_tls(self) -> bool:
        return self in (ListenerProtocol.HTTPS, ListenerProtocol.TCP_AND_TLS)

    @property
    def is_http(self) -> bool:
        return self in (ListenerProtocol.HTTP, ListenerProtocol.HTTPS)


class ConfigError(Exception):
    """Invalid configuration (reference error.rs Error::Config)."""


class Action(enum.Enum):
    """Rule actions (reference rules/rules.rs:30-35)."""

    BLOCK = "block"
    CAPTCHA = "captcha"

    @staticmethod
    def parse(text: str) -> "Action":
        for action in Action:
            if action.value == text:
                return action
        raise ConfigError(f"unknown action: {text}")


class ListType(enum.Enum):
    """List item types (reference pingoo/lists.rs ListType)."""

    STRING = "String"
    INT = "Int"
    IP = "Ip"

    @staticmethod
    def parse(text: str) -> "ListType":
        for lt in ListType:
            if lt.value == text:
                return lt
        raise ConfigError(f"{text} is not a valid ListType")


@dataclass(frozen=True)
class ListenerConfig:
    name: str
    host: str  # ip address text
    port: int
    protocol: ListenerProtocol
    services: tuple[str, ...]


@dataclass(frozen=True)
class Upstream:
    """A resolved upstream address (reference service_registry.rs Upstream
    / config_file.rs parse_upstream)."""

    hostname: str
    port: int
    tls: bool
    ip: Optional[str] = None  # None -> hostname needs DNS discovery
    # h2:// scheme — proxy upstream over HTTP/2 prior knowledge (the
    # reference's hyper client speaks h1/h2, http_proxy_service.rs:54-71).
    h2: bool = False


@dataclass(frozen=True)
class StaticSiteNotFound:
    file: Optional[str] = None
    status: int = 404


@dataclass(frozen=True)
class StaticSiteConfig:
    root: str
    not_found: StaticSiteNotFound = field(default_factory=StaticSiteNotFound)


@dataclass(frozen=True)
class ServiceConfig:
    """Exactly one of http_proxy / tcp_proxy / static is set
    (reference config_file.rs parse_service)."""

    name: str
    route: Optional[Program] = None
    http_proxy: Optional[tuple[Upstream, ...]] = None
    tcp_proxy: Optional[tuple[Upstream, ...]] = None
    static: Optional[StaticSiteConfig] = None


@dataclass(frozen=True)
class RuleConfig:
    """A compiled rule (reference pingoo/rules.rs Rule). A rule without an
    expression always matches (pingoo/rules.rs:48-50)."""

    name: str
    expression: Optional[Program]
    actions: tuple[Action, ...]


@dataclass(frozen=True)
class ListConfig:
    name: str
    type: ListType
    file: str


@dataclass(frozen=True)
class AcmeConfig:
    directory_url: str
    domains: tuple[str, ...]


@dataclass(frozen=True)
class TlsConfig:
    acme: Optional[AcmeConfig] = None


@dataclass(frozen=True)
class ServiceDiscoveryConfig:
    docker_socket: str = "/var/run/docker.sock"


@dataclass(frozen=True)
class ChildProcess:
    command: tuple[str, ...]


@dataclass(frozen=True)
class Config:
    listeners: tuple[ListenerConfig, ...]
    services: tuple[ServiceConfig, ...]
    rules: tuple[RuleConfig, ...]
    lists: tuple[ListConfig, ...]
    tls: TlsConfig = field(default_factory=TlsConfig)
    service_discovery: ServiceDiscoveryConfig = field(
        default_factory=ServiceDiscoveryConfig
    )
    child_process: Optional[ChildProcess] = None
