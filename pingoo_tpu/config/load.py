"""YAML config loading + validation.

Reference parity (pingoo/config/config.rs load_and_validate,
config_file.rs parsers):

  * listeners: name -> {address: "proto://ip[:port]", services: [..]};
    protocols http/https/tcp/tcp+tls; default ports 80/443 for http/https,
    required otherwise; host must be a literal ip (config_file.rs:145-188).
  * services: name -> exactly one of http_proxy/tcp_proxy/static, plus an
    optional `route` expression compiled at load time; tcp_proxy can't
    have a route (config_file.rs:190-274).
  * upstream URLs: scheme tcp/http/https, ascii host required, default
    port from scheme, https => tls, localhost -> 127.0.0.1
    (config_file.rs:280-333).
  * rules from the main file plus every *.yml in the rules folder,
    duplicate names rejected (config.rs:378-422, 206-213).
  * listener validation: duplicate ports, no services, >1 service on tcp,
    unknown/duplicate service names (config.rs:325-376).
  * acme: trimmed directory url, duplicate/wildcard/non-ascii-lowercase
    domains rejected (config.rs:269-303).

Unlike the reference's fixed /etc/pingoo paths (config.rs:24-38), every
path is parameterizable so the framework is testable; the defaults match
the reference.
"""

from __future__ import annotations

import ipaddress
import os
from typing import Any, Mapping
from urllib.parse import urlsplit

import yaml

from ..expr import CompileError, Program, compile_expression
from .schema import (
    AcmeConfig,
    Action,
    ChildProcess,
    Config,
    ConfigError,
    ListConfig,
    ListenerConfig,
    ListenerProtocol,
    ListType,
    RuleConfig,
    ServiceConfig,
    ServiceDiscoveryConfig,
    StaticSiteConfig,
    StaticSiteNotFound,
    TlsConfig,
    Upstream,
)

DEFAULT_CONFIG_DIR = "/etc/pingoo"
DEFAULT_CONFIG_FILE = os.path.join(DEFAULT_CONFIG_DIR, "pingoo.yml")
LETSENCRYPT_PRODUCTION_URL = "https://acme-v02.api.letsencrypt.org/directory"


def load_and_validate(
    config_file: str = DEFAULT_CONFIG_FILE,
    rules_dir: str | None = None,
) -> Config:
    """Load the YAML config file, merge the rules folder, validate."""
    try:
        with open(config_file, "rb") as f:
            raw = yaml.safe_load(f) or {}
    except OSError as exc:
        raise ConfigError(f"error reading config file ({config_file}): {exc}")
    except yaml.YAMLError as exc:
        raise ConfigError(f"error parsing config file ({config_file}): {exc}")
    if rules_dir is None:
        rules_dir = os.path.join(os.path.dirname(config_file) or ".", "rules")
    return parse_config(raw, rules_dir=rules_dir)


def parse_config(raw: Mapping[str, Any], rules_dir: str | None = None) -> Config:
    if not isinstance(raw, Mapping):
        raise ConfigError("config root must be a mapping")
    _check_keys(
        raw,
        {"listeners", "services", "rules", "tls", "service_discovery", "lists",
         "child_process"},
        "config",
    )

    services = _parse_services(_want_map(raw, "services"))
    listeners = _parse_listeners(_want_map(raw, "listeners"), services)

    rule_entries = dict(_want_map(raw, "rules", required=False))
    if rules_dir:
        for name, entry in _load_rules_folder(rules_dir).items():
            if name in rule_entries:
                raise ConfigError(f"duplicate rule name: {name}")
            rule_entries[name] = entry
    rules = tuple(_parse_rule(name, entry) for name, entry in rule_entries.items())

    lists = _parse_lists(_want_map(raw, "lists", required=False))
    tls = _parse_tls(raw.get("tls"), listeners)
    discovery = _parse_discovery(raw.get("service_discovery"))
    child = _parse_child_process(raw.get("child_process"))

    return Config(
        listeners=listeners,
        services=tuple(services.values()),
        rules=rules,
        lists=lists,
        tls=tls,
        service_discovery=discovery,
        child_process=child,
    )


def _load_rules_folder(rules_dir: str) -> dict[str, Any]:
    """Load rules from every .yml file in `rules_dir`
    (reference config.rs:378-422; a missing folder is fine)."""
    out: dict[str, Any] = {}
    try:
        entries = sorted(os.listdir(rules_dir))
    except FileNotFoundError:
        return out
    except OSError as exc:
        raise ConfigError(f"error reading rules folder {rules_dir!r}: {exc}")
    for fname in entries:
        if not fname.endswith(".yml"):
            continue
        path = os.path.join(rules_dir, fname)
        try:
            with open(path, "rb") as f:
                rules = yaml.safe_load(f) or {}
        except (OSError, yaml.YAMLError) as exc:
            raise ConfigError(f"error parsing rules file {path!r}: {exc}")
        if not isinstance(rules, Mapping):
            raise ConfigError(f"error parsing rules file {path!r}: not a mapping")
        for name, entry in rules.items():
            if name in out:
                raise ConfigError(f"duplicate rule name: {name}")
            out[name] = entry
    return out


# -- listeners ---------------------------------------------------------------


def parse_listener_address(text: str) -> tuple[str, int, ListenerProtocol]:
    """Parse "proto://ip[:port]" (reference config_file.rs:145-188)."""
    if "://" in text:
        scheme, _, rest = text.partition("://")
    else:
        scheme, rest = "http", text
    protocol = ListenerProtocol.parse(scheme)
    parts = urlsplit(f"//{rest}")
    if parts.path:
        raise ConfigError(f"listener address {text} is not valid: path must be empty")
    if not parts.hostname:
        raise ConfigError(f"listener address {text} is not valid: authority is missing")
    try:
        port = parts.port
    except ValueError:
        raise ConfigError(f"listener address {text} is not valid: bad port")
    if port is None:
        if protocol == ListenerProtocol.HTTP:
            port = 80
        elif protocol == ListenerProtocol.HTTPS:
            port = 443
        else:
            raise ConfigError(f"listener address {text} is not valid: port is missing")
    host = parts.hostname
    try:
        ipaddress.ip_address(host)
    except ValueError:
        raise ConfigError(f"listener address {text} is not valid: host must be an ip")
    return host, port, protocol


def _parse_listeners(
    raw: Mapping[str, Any], services: Mapping[str, ServiceConfig]
) -> tuple[ListenerConfig, ...]:
    if not raw:
        raise ConfigError("config: at least one listener is required")
    http_services = tuple(
        n for n, s in services.items() if s.http_proxy is not None or s.static is not None
    )
    tcp_services = tuple(n for n, s in services.items() if s.tcp_proxy is not None)

    listeners = []
    for name, entry in raw.items():
        if not isinstance(entry, Mapping):
            raise ConfigError(f"config: listeners.{name} must be a mapping")
        _check_keys(entry, {"address", "services"}, f"listeners.{name}")
        address = entry.get("address")
        if not isinstance(address, str):
            raise ConfigError(f"config: listeners.{name}: address is required")
        host, port, protocol = parse_listener_address(address)
        svc = entry.get("services")
        if svc is None:
            svc = list(http_services if protocol.is_http else tcp_services)
        if not isinstance(svc, list) or not all(isinstance(s, str) for s in svc):
            raise ConfigError(f"config: listeners.{name}: services must be a list of names")
        listeners.append(
            ListenerConfig(
                name=name, host=host, port=port, protocol=protocol,
                services=tuple(svc),
            )
        )

    # Validation per reference config.rs:325-376.
    for i, listener in enumerate(listeners):
        for j, other in enumerate(listeners):
            if i != j and listener.port == other.port:
                raise ConfigError(
                    f"config: listeners: {listener.name} and {other.name} "
                    "can't listen on the same port"
                )
        if not listener.services:
            raise ConfigError(
                f"config: listeners: {listener.name}: no service found for this listener"
            )
        if not listener.protocol.is_http and len(listener.services) > 1:
            raise ConfigError(
                f"config: listeners: {listener.name}: TCP listeners can only "
                "have 1 associated service"
            )
        seen: set[str] = set()
        for service_name in listener.services:
            if service_name not in services:
                raise ConfigError(
                    f"config: listeners: {listener.name}: service "
                    f"{service_name} doesn't exist"
                )
            if service_name in seen:
                raise ConfigError(
                    f"config: listeners: {listener.name}: duplicate services "
                    f"are not allowed ({service_name})"
                )
            seen.add(service_name)
    return tuple(listeners)


# -- services ----------------------------------------------------------------


def parse_upstream(text: str) -> Upstream:
    """Parse an upstream URL (reference config_file.rs:280-333)."""
    parts = urlsplit(text)
    scheme = parts.scheme
    # h2 = cleartext HTTP/2 prior knowledge (the reference's hyper
    # client negotiates h1/h2 instead; explicit scheme here).
    if scheme not in ("tcp", "http", "https", "h2"):
        raise ConfigError(f"{text} is not a valid URL: {scheme or '(none)'} is not a valid protocol")
    hostname = parts.hostname or ""
    if not hostname:
        raise ConfigError(f"{text} is not a valid URL: host is missing")
    if not hostname.isascii():
        raise ConfigError(
            f"{text} is not a valid URL: only ascii hostnames are currently supported"
        )
    try:
        port = parts.port
    except ValueError:
        raise ConfigError(f"{text} is not a valid URL: bad port")
    if port is None:
        port = {"http": 80, "https": 443, "h2": 80}.get(scheme)
        if port is None:
            raise ConfigError(f"{text} is not a valid URL: port is missing")
    tls = scheme == "https"
    h2 = scheme == "h2"
    if hostname == "localhost":
        return Upstream(hostname=hostname, port=port, tls=tls,
                        ip="127.0.0.1", h2=h2)
    try:
        ipaddress.ip_address(hostname)
    except ValueError:
        return Upstream(hostname=hostname, port=port, tls=tls, ip=None, h2=h2)
    return Upstream(hostname=hostname, port=port, tls=tls, ip=hostname, h2=h2)


def _parse_services(raw: Mapping[str, Any]) -> dict[str, ServiceConfig]:
    if not raw:
        raise ConfigError("config: at least one service is required")
    services: dict[str, ServiceConfig] = {}
    for name, entry in raw.items():
        if not isinstance(entry, Mapping):
            raise ConfigError(f"config: services.{name} must be a mapping")
        _check_keys(
            entry, {"route", "http_proxy", "tcp_proxy", "static"}, f"services.{name}"
        )
        kinds = [k for k in ("http_proxy", "tcp_proxy", "static") if entry.get(k) is not None]
        if len(kinds) != 1:
            raise ConfigError(
                f"invalid service definition for {name}: services must have "
                "exactly 1 http_proxy, tcp_proxy or static field"
            )
        route_src = entry.get("route")
        route: Program | None = None
        if route_src is not None:
            if entry.get("tcp_proxy") is not None:
                raise ConfigError(
                    f"Invalid service definition for {name}: TCP proxy can't have a route"
                )
            try:
                route = compile_expression(str(route_src))
            except CompileError as exc:
                raise ConfigError(f"error parsing route for service {name}: {exc}")

        http_proxy = tcp_proxy = None
        static = None
        if "http_proxy" in kinds:
            http_proxy = tuple(parse_upstream(str(u)) for u in _want_list(entry, "http_proxy", name))
        elif "tcp_proxy" in kinds:
            tcp_proxy = tuple(parse_upstream(str(u)) for u in _want_list(entry, "tcp_proxy", name))
        else:
            st = entry["static"]
            if not isinstance(st, Mapping):
                raise ConfigError(f"config: services.{name}.static must be a mapping")
            _check_keys(st, {"root", "not_found"}, f"services.{name}.static")
            nf_raw = st.get("not_found") or {}
            if not isinstance(nf_raw, Mapping):
                raise ConfigError(f"config: services.{name}.static.not_found must be a mapping")
            status = nf_raw.get("status", 404)
            if not isinstance(status, int) or not (100 <= status <= 999):
                raise ConfigError(
                    f"services.[{name}].static.not_found.status: Not a valid HTTP status code"
                )
            nf_file = nf_raw.get("file")
            static = StaticSiteConfig(
                root=str(st.get("root", "")),
                not_found=StaticSiteNotFound(
                    file=os.path.join(str(st.get("root", "")), nf_file) if nf_file else None,
                    status=status,
                ),
            )
        services[name] = ServiceConfig(
            name=name, route=route, http_proxy=http_proxy, tcp_proxy=tcp_proxy,
            static=static,
        )
    return services


# -- rules / lists / tls / misc ---------------------------------------------


def _parse_rule(name: str, entry: Any) -> RuleConfig:
    if not isinstance(entry, Mapping):
        raise ConfigError(f"error parsing rules: rule {name} must be a mapping")
    _check_keys(entry, {"expression", "actions"}, f"rules.{name}")
    expression_src = entry.get("expression")
    expression: Program | None = None
    if expression_src is not None:
        try:
            expression = compile_expression(str(expression_src))
        except CompileError as exc:
            raise ConfigError(f"error parsing rules: {name}: {exc}")
    actions_raw = entry.get("actions")
    if not isinstance(actions_raw, list):
        raise ConfigError(f"error parsing rules: {name}: actions must be a list")
    actions = []
    for a in actions_raw:
        if isinstance(a, Mapping) and "action" in a:
            actions.append(Action.parse(str(a["action"])))
        elif isinstance(a, str):
            actions.append(Action.parse(a))
        else:
            raise ConfigError(f"error parsing rules: {name}: invalid action entry {a!r}")
    return RuleConfig(name=name, expression=expression, actions=tuple(actions))


def _parse_lists(raw: Mapping[str, Any]) -> tuple[ListConfig, ...]:
    out = []
    for name, entry in raw.items():
        if not isinstance(entry, Mapping) or "type" not in entry or "file" not in entry:
            raise ConfigError(f"config: lists.{name} must have `type` and `file`")
        out.append(
            ListConfig(name=name, type=ListType.parse(str(entry["type"])), file=str(entry["file"]))
        )
    return tuple(out)


def _parse_tls(raw: Any, listeners: tuple[ListenerConfig, ...]) -> TlsConfig:
    if raw is None:
        return TlsConfig()
    if not isinstance(raw, Mapping):
        raise ConfigError("config: tls must be a mapping")
    _check_keys(raw, {"acme"}, "tls")
    acme_raw = raw.get("acme")
    if acme_raw is None:
        return TlsConfig()
    if not isinstance(acme_raw, Mapping):
        raise ConfigError("config: tls.acme must be a mapping")
    _check_keys(acme_raw, {"directory_url", "domains"}, "tls.acme")
    directory_url = str(
        acme_raw.get("directory_url", LETSENCRYPT_PRODUCTION_URL)
    ).strip().rstrip("/")
    domains_raw = acme_raw.get("domains", [])
    if not isinstance(domains_raw, list):
        raise ConfigError("acme: domains must be a list")
    domains = tuple(str(d) for d in domains_raw)
    seen: set[str] = set()
    for domain in domains:
        if domain in seen:
            raise ConfigError(f"acme: duplicate domain: {domain}")
        seen.add(domain)
        if "*" in domain:
            raise ConfigError(
                "acme: Pingoo currently doesn't support wildcard domains for "
                f"automatic TLS ({domain})"
            )
        if not domain.isascii() or domain.lower() != domain:
            raise ConfigError(f"acme: invalid domain: {domain}")
    return TlsConfig(acme=AcmeConfig(directory_url=directory_url, domains=domains))


def _parse_discovery(raw: Any) -> ServiceDiscoveryConfig:
    if raw is None:
        return ServiceDiscoveryConfig()
    if not isinstance(raw, Mapping):
        raise ConfigError("config: service_discovery must be a mapping")
    docker = raw.get("docker") or {}
    if not isinstance(docker, Mapping):
        raise ConfigError("config: service_discovery.docker must be a mapping")
    return ServiceDiscoveryConfig(
        docker_socket=str(docker.get("socket", "/var/run/docker.sock"))
    )


def _parse_child_process(raw: Any) -> ChildProcess | None:
    if raw is None:
        return None
    if not isinstance(raw, Mapping) or not isinstance(raw.get("command"), list):
        raise ConfigError("config: child_process.command must be a list")
    return ChildProcess(command=tuple(str(c) for c in raw["command"]))


# -- helpers -----------------------------------------------------------------


def _want_map(raw: Mapping[str, Any], key: str, required: bool = True) -> Mapping[str, Any]:
    value = raw.get(key)
    if value is None:
        if required:
            raise ConfigError(f"config: {key} is required")
        return {}
    if not isinstance(value, Mapping):
        raise ConfigError(f"config: {key} must be a mapping")
    return value


def _want_list(entry: Mapping[str, Any], key: str, service: str) -> list:
    value = entry.get(key)
    if not isinstance(value, list) or not value:
        raise ConfigError(f"config: services.{service}.{key} must be a non-empty list")
    return value


def _check_keys(raw: Mapping[str, Any], allowed: set[str], where: str) -> None:
    unknown = set(raw.keys()) - allowed
    if unknown:
        raise ConfigError(f"config: {where}: unknown keys: {sorted(unknown)}")
