"""Python binding for the native shared-memory verdict ring.

The C++ side (pingoo_tpu/native/pingoo_ring.{h,cc}) owns the queue
algebra; this module maps the ring file, exposes enqueue/dequeue via
ctypes, and — the part that matters for throughput — decodes a whole
dequeued batch into engine arrays with one numpy structured view (the
slot layout mirrors engine/batch.py field specs by construction).

`RingSidecar` is the TPU-side drain loop: dequeue a batch, run the
jitted verdict, post (ticket, action, bot_score) back. Together with
native/loadgen.cc this is the host<->device transport of SURVEY.md §7
item 4 running end-to-end.
"""

from __future__ import annotations

import contextlib
import ctypes
import mmap
import os
import subprocess
import time
from typing import Optional

import numpy as np

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
LIB_PATH = os.path.join(NATIVE_DIR, "libpingoo_ring.so")

FIELD_CAPS = {"method": 16, "host": 256, "path": 2048, "url": 2048,
              "user_agent": 256}

RING_MAGIC = 0x50474F52  # PINGOO_RING_MAGIC ("PGOR")
SLOT_FLAG_TRUNCATED = 0x1  # PINGOO_SLOT_FLAG_TRUNCATED
SPILL_SLOTS = 64  # PINGOO_SPILL_SLOTS
SPILL_DATA_CAP = 65536  # PINGOO_SPILL_DATA_CAP
SPILL_NONE = 0xFF  # PINGOO_SPILL_NONE

# -- ABI mirror of pingoo_ring.h -----------------------------------------
# These constants and structured dtypes are the Python half of the
# cross-plane ABI contract. They are NOT free-hand: `make analyze-abi`
# (tools/analyze/abi.py) diffs every size/offset below against a C++
# emitter compiled from pingoo_ring.h and against the committed golden
# table (tools/analyze/abi_golden.json). Change the header, the dtypes,
# and the golden together or the check fails.

RING_FORMAT_VERSION = 6  # PINGOO_RING_VERSION
REQUEST_SLOT_SIZE = 4688  # sizeof(PingooRequestSlot)
VERDICT_SLOT_SIZE = 24  # sizeof(PingooVerdictSlot)
RING_HEADER_SIZE = 640  # sizeof(PingooRingHeader)
TELEMETRY_BLOCK_SIZE = 128  # sizeof(PingooRingTelemetry)
SPILL_SLOT_SIZE = 65552  # sizeof(PingooSpillSlot)
WAIT_BUCKETS = 8  # PINGOO_WAIT_BUCKETS
BODY_SLOTS = 256  # PINGOO_BODY_SLOTS (v6 body-window ring)
BODY_WINDOW_CAP = 4096  # PINGOO_BODY_WINDOW_CAP
BODY_SLOT_SIZE = 4136  # sizeof(PingooBodySlot)
BODY_FLAG_FINAL = 0x1  # PINGOO_BODY_FLAG_FINAL
BODY_FLAG_ABORT = 0x2  # PINGOO_BODY_FLAG_ABORT
# Body verdicts ride the shared verdict ring with this bit set in the
# ticket (PINGOO_BODY_VERDICT_BIT) so the data plane demuxes them.
BODY_VERDICT_BIT = 1 << 63

# numpy mirror of PingooRequestSlot. The explicit itemsize carries the
# C struct's 8-byte tail padding (4684 -> 4688) so a whole dequeued
# batch decodes with one structured view.
REQUEST_SLOT_DTYPE = np.dtype({
    "names": [
        "seq", "ticket", "enq_ms",
        "method_len", "host_len", "path_len", "url_len", "ua_len",
        "remote_port", "ip", "asn", "country", "flags", "spill_idx",
        "method", "host", "path", "url", "user_agent",
    ],
    "formats": [
        "<u8", "<u8", "<u8",
        "<u2", "<u2", "<u2", "<u2", "<u2",
        "<u2", ("u1", 16), "<u4", "S2", "u1", "u1",
        ("u1", 16), ("u1", 256), ("u1", 2048), ("u1", 2048), ("u1", 256),
    ],
    "offsets": [
        0, 8, 16,
        24, 26, 28, 30, 32,
        34, 36, 52, 56, 58, 59,
        60, 76, 332, 2380, 4428,
    ],
    "itemsize": REQUEST_SLOT_SIZE,
})

# numpy mirror of PingooVerdictSlot.
VERDICT_SLOT_DTYPE = np.dtype({
    "names": ["seq", "ticket", "action", "_pad", "bot_score"],
    "formats": ["<u8", "<u8", "u1", ("u1", 3), "<f4"],
    "offsets": [0, 8, 16, 17, 20],
    "itemsize": VERDICT_SLOT_SIZE,
})

# numpy mirror of PingooRingTelemetry (the v4 atomic header block;
# alignas(64) pads the struct to 128 bytes).
TELEMETRY_DTYPE = np.dtype({
    "names": ["enqueued", "enqueue_full", "dequeued", "depth_hwm",
              "verdicts_posted", "verdict_post_full", "wait_sum_ms",
              "wait_hist"],
    "formats": ["<u8", "<u8", "<u8", "<u8", "<u8", "<u8", "<u8",
                ("<u8", WAIT_BUCKETS)],
    "offsets": [0, 8, 16, 24, 32, 40, 48, 56],
    "itemsize": TELEMETRY_BLOCK_SIZE,
})

# numpy mirror of PingooRingHeader (cache-line-aligned counters; the
# v5 liveness block — sidecar_epoch / sidecar_heartbeat_ms /
# posted_floor — rides its own cache line after the telemetry block;
# the v6 body-window ring adds body_slot_size/body_capacity up front
# and a body_head/body_tail cache-line pair at the end).
RING_HEADER_DTYPE = np.dtype({
    "names": ["magic", "version", "capacity", "request_slot_size",
              "verdict_slot_size", "body_slot_size", "body_capacity",
              "req_head", "req_tail", "ver_head", "ver_tail",
              "telemetry", "sidecar_epoch", "sidecar_heartbeat_ms",
              "posted_floor", "body_head", "body_tail"],
    "formats": ["<u4", "<u4", "<u4", "<u4", "<u4", "<u4", "<u4", "<u8",
                "<u8", "<u8", "<u8", TELEMETRY_DTYPE, "<u8", "<u8",
                "<u8", "<u8", "<u8"],
    "offsets": [0, 4, 8, 12, 16, 20, 24, 64, 128, 192, 256, 320, 448,
                456, 464, 512, 576],
    "itemsize": RING_HEADER_SIZE,
})

# numpy mirror of PingooSpillSlot (overflow url/path strings).
SPILL_SLOT_DTYPE = np.dtype({
    "names": ["state", "url_len", "path_len", "data"],
    "formats": ["<u8", "<u4", "<u4", ("u1", 65536)],
    "offsets": [0, 8, 12, 16],
    "itemsize": SPILL_SLOT_SIZE,
})

# numpy mirror of PingooBodySlot (v6 body-window ring): a whole
# dequeued window batch decodes with one structured view, same as the
# request slots.
BODY_SLOT_DTYPE = np.dtype({
    "names": ["seq", "flow", "win_seq", "win_len", "total_len", "flags",
              "_pad", "data"],
    "formats": ["<u8", "<u8", "<u4", "<u4", "<u8", "u1", ("u1", 7),
                ("u1", BODY_WINDOW_CAP)],
    "offsets": [0, 8, 16, 20, 24, 32, 33, 40],
    "itemsize": BODY_SLOT_SIZE,
})

for _dt, _size in ((REQUEST_SLOT_DTYPE, REQUEST_SLOT_SIZE),
                   (VERDICT_SLOT_DTYPE, VERDICT_SLOT_SIZE),
                   (TELEMETRY_DTYPE, TELEMETRY_BLOCK_SIZE),
                   (RING_HEADER_DTYPE, RING_HEADER_SIZE),
                   (SPILL_SLOT_DTYPE, SPILL_SLOT_SIZE),
                   (BODY_SLOT_DTYPE, BODY_SLOT_SIZE)):
    assert _dt.itemsize == _size, (_dt, _dt.itemsize, _size)
del _dt, _size

# Flat order of pingoo_ring_telemetry_snapshot (pingoo_ring.h
# PINGOO_TELEMETRY_WORDS); the 8 wait_hist buckets follow.
TELEMETRY_FIELDS = ("enqueued", "enqueue_full", "dequeued", "depth",
                    "depth_hwm", "verdicts_posted", "verdict_post_full",
                    "wait_sum_ms")
TELEMETRY_WORDS = len(TELEMETRY_FIELDS) + 8
WAIT_BUCKET_BOUNDS_MS = (1, 2, 5, 10, 50, 100, 1000)  # last bucket +inf


def ensure_built() -> bool:
    """Build the native library if missing; False if no toolchain."""
    if os.path.exists(LIB_PATH):
        return True
    try:
        subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                       capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _load_lib():
    lib = ctypes.CDLL(LIB_PATH)
    lib.pingoo_ring_bytes.restype = ctypes.c_size_t
    lib.pingoo_ring_bytes.argtypes = [ctypes.c_uint32]
    lib.pingoo_ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.pingoo_ring_attach.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_uint32)]
    lib.pingoo_ring_attach.restype = ctypes.c_int
    lib.pingoo_ring_enqueue_request.restype = ctypes.c_uint64
    lib.pingoo_ring_enqueue_request.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_uint32,  # method
        ctypes.c_char_p, ctypes.c_uint32,  # host
        ctypes.c_char_p, ctypes.c_uint32,  # path
        ctypes.c_char_p, ctypes.c_uint32,  # url
        ctypes.c_char_p, ctypes.c_uint32,  # ua
        ctypes.c_char_p,                   # ip[16]
        ctypes.c_uint16, ctypes.c_uint32, ctypes.c_char_p,
    ]
    lib.pingoo_ring_dequeue_requests.restype = ctypes.c_uint32
    lib.pingoo_ring_dequeue_requests.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
    lib.pingoo_ring_post_verdict.restype = ctypes.c_int
    lib.pingoo_ring_post_verdict.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint8, ctypes.c_float]
    lib.pingoo_ring_post_verdicts.restype = ctypes.c_uint32
    lib.pingoo_ring_post_verdicts.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
    lib.pingoo_ring_poll_verdict.restype = ctypes.c_int
    lib.pingoo_ring_poll_verdict.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float)]
    # Body-window ring (v6, ISSUE 13).
    lib.pingoo_ring_enqueue_body.restype = ctypes.c_int
    lib.pingoo_ring_enqueue_body.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_uint8]
    lib.pingoo_ring_dequeue_bodies.restype = ctypes.c_uint32
    lib.pingoo_ring_dequeue_bodies.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
    lib.pingoo_ring_spill_read.restype = ctypes.c_int
    lib.pingoo_ring_spill_read.argtypes = [
        ctypes.c_void_p, ctypes.c_uint8,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint32)]
    lib.pingoo_ring_spill_release.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint8]
    lib.pingoo_ring_telemetry_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.pingoo_ring_record_waits.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
    lib.pingoo_ring_now_ms.restype = ctypes.c_uint64
    lib.pingoo_ring_now_ms.argtypes = []
    # Liveness / supervision protocol (v5, ISSUE 10).
    lib.pingoo_ring_sidecar_attach.restype = ctypes.c_uint64
    lib.pingoo_ring_sidecar_attach.argtypes = [ctypes.c_void_p]
    lib.pingoo_ring_heartbeat.argtypes = [ctypes.c_void_p]
    lib.pingoo_ring_liveness.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.pingoo_ring_set_posted_floor.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]
    lib.pingoo_ring_reclaim_request.restype = ctypes.c_int
    lib.pingoo_ring_reclaim_request.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p]
    return lib


class Ring:
    """A mapped ring file."""

    def __init__(self, path: str, capacity: int = 4096, create: bool = False):
        if not ensure_built():
            raise RuntimeError("native ring library unavailable (no g++?)")
        if capacity & (capacity - 1) or capacity <= 0:
            # The C ring masks with `pos & (cap - 1)`; a non-pow2
            # capacity would silently alias slots and corrupt the queue.
            raise ValueError(f"ring capacity must be a power of two, got {capacity}")
        self.lib = _load_lib()
        self.capacity = capacity
        nbytes = self.lib.pingoo_ring_bytes(capacity)
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self.fd = os.open(path, flags, 0o600)
        if create:
            os.ftruncate(self.fd, nbytes)
        self.map = mmap.mmap(self.fd, nbytes)
        self.addr = ctypes.addressof(
            (ctypes.c_char * nbytes).from_buffer(self.map))
        if create:
            self.lib.pingoo_ring_init(self.addr, capacity)
        cap_out = ctypes.c_uint32()
        if self.lib.pingoo_ring_attach(self.addr, ctypes.byref(cap_out)) != 0:
            raise RuntimeError("ring attach failed (layout mismatch?)")
        self.capacity = int(cap_out.value)
        self._scratch = np.zeros(self.capacity, dtype=REQUEST_SLOT_DTYPE)
        self._body_scratch = None  # allocated on first dequeue_bodies

    def close(self) -> None:
        self._scratch = None
        self._body_scratch = None
        self.map.close()
        os.close(self.fd)

    # -- producer side (tests / python data plane) ---------------------------

    def enqueue(self, method=b"GET", host=b"", path=b"/", url=b"/",
                user_agent=b"", ip: bytes = b"\x00" * 16, port: int = 0,
                asn: int = 0, country: bytes = b"XX") -> Optional[int]:
        ticket = self.lib.pingoo_ring_enqueue_request(
            self.addr, method, len(method), host, len(host), path, len(path),
            url, len(url), user_agent, len(user_agent), ip, port, asn,
            country)
        return None if ticket == 2**64 - 1 else int(ticket)

    # -- consumer side (sidecar) ---------------------------------------------

    def dequeue_batch(self, max_batch: int = 1024) -> np.ndarray:
        """-> structured array view of up to max_batch request slots."""
        n = self.lib.pingoo_ring_dequeue_requests(
            self.addr, self._scratch.ctypes.data_as(ctypes.c_void_p),
            min(max_batch, self.capacity))
        return self._scratch[:n].copy()

    def dequeue_batch_into(self, out: np.ndarray) -> int:
        """Zero-copy bulk dequeue (ISSUE 9, docs/EXECUTOR.md): the FFI
        slot copy lands directly in the caller's REQUEST_SLOT_DTYPE
        buffer — typically a row offset into the sidecar's pooled
        accumulation buffer, so multi-ring parts merge WITHOUT the
        scratch round trip, the per-part `.copy()`, or the launch-time
        `np.concatenate`. Returns the slot count written; the caller
        owns `out` for the batch's whole lifetime."""
        assert out.dtype == REQUEST_SLOT_DTYPE and out.flags.c_contiguous
        if not len(out):
            return 0
        n = self.lib.pingoo_ring_dequeue_requests(
            self.addr, out.ctypes.data_as(ctypes.c_void_p),
            min(len(out), self.capacity))
        return int(n)

    def post_verdict(self, ticket: int, action: int, score: float = 0.0) -> bool:
        return self.lib.pingoo_ring_post_verdict(
            self.addr, ticket, action, score) == 0

    def post_verdicts(self, tickets: np.ndarray, actions: np.ndarray) -> int:
        """Batched post (one FFI hop); returns count posted — fewer than
        len(tickets) only when the verdict ring is full."""
        tickets = np.ascontiguousarray(tickets, dtype=np.uint64)
        actions = np.ascontiguousarray(actions, dtype=np.uint8)
        return int(self.lib.pingoo_ring_post_verdicts(
            self.addr, tickets.ctypes.data_as(ctypes.c_void_p),
            actions.ctypes.data_as(ctypes.c_void_p), len(tickets)))

    def spill_read(self, idx: int) -> Optional[tuple[bytes, bytes]]:
        """Full (url, path) bytes of a claimed spill slot, or None."""
        url_p = ctypes.c_char_p()
        path_p = ctypes.c_char_p()
        url_n = ctypes.c_uint32()
        path_n = ctypes.c_uint32()
        if self.lib.pingoo_ring_spill_read(
                self.addr, idx, ctypes.byref(url_p), ctypes.byref(url_n),
                ctypes.byref(path_p), ctypes.byref(path_n)) != 0:
            return None
        url = ctypes.string_at(url_p, url_n.value)
        path = ctypes.string_at(path_p, path_n.value)
        return url, path

    def spill_release(self, idx: int) -> None:
        self.lib.pingoo_ring_spill_release(self.addr, idx)

    def telemetry(self) -> dict:
        """Snapshot of the shm header's atomic telemetry block (ring
        v4): queue counters, depth + high-water mark, full-ring stalls,
        and the enqueue->verdict-post wait histogram (bucket upper
        bounds WAIT_BUCKET_BOUNDS_MS, last bucket +inf)."""
        buf = (ctypes.c_uint64 * TELEMETRY_WORDS)()
        if not self.map.closed:  # post-close scrape reads zeros, not UB
            self.lib.pingoo_ring_telemetry_snapshot(self.addr, buf)
        out = {name: int(buf[i]) for i, name in enumerate(TELEMETRY_FIELDS)}
        out["wait_hist"] = [int(buf[len(TELEMETRY_FIELDS) + b])
                            for b in range(8)]
        return out

    def record_waits(self, enq_ms: np.ndarray) -> None:
        """Feed dequeued slots' enq_ms back at verdict-post time (one
        FFI hop per batch) so the telemetry wait histogram measures
        enqueue -> verdict-post per request."""
        if self.map.closed:
            return
        enq = np.ascontiguousarray(enq_ms, dtype=np.uint64)
        self.lib.pingoo_ring_record_waits(
            self.addr, enq.ctypes.data_as(ctypes.c_void_p), len(enq))

    def poll_verdict(self) -> Optional[tuple[int, int, float]]:
        ticket = ctypes.c_uint64()
        action = ctypes.c_uint8()
        score = ctypes.c_float()
        if self.lib.pingoo_ring_poll_verdict(
                self.addr, ctypes.byref(ticket), ctypes.byref(action),
                ctypes.byref(score)) != 0:
            return None
        return int(ticket.value), int(action.value), float(score.value)

    # -- body-window ring (v6, docs/BODY_STREAMING.md) ------------------------

    def enqueue_body(self, flow: int, win_seq: int, data: bytes,
                     total_len: int, flags: int = 0) -> bool:
        """Enqueue one de-framed body window for `flow` (the request
        ticket). False when the body ring is full — the producer then
        fails the flow open to metadata-only rather than stalling."""
        rc = self.lib.pingoo_ring_enqueue_body(
            self.addr, flow, win_seq, total_len, data, len(data), flags)
        if rc == -2:
            raise ValueError(
                f"body window of {len(data)} bytes exceeds the "
                f"{BODY_WINDOW_CAP}-byte slot cap")
        return rc == 0

    def dequeue_bodies(self, max_batch: int = BODY_SLOTS) -> np.ndarray:
        """-> structured BODY_SLOT_DTYPE array of dequeued windows."""
        if self._body_scratch is None:
            self._body_scratch = np.zeros(BODY_SLOTS,
                                          dtype=BODY_SLOT_DTYPE)
        n = self.lib.pingoo_ring_dequeue_bodies(
            self.addr,
            self._body_scratch.ctypes.data_as(ctypes.c_void_p),
            min(max_batch, BODY_SLOTS))
        return self._body_scratch[:n].copy()

    # -- liveness / supervision protocol (ring v5, docs/RESILIENCE.md) -------

    def sidecar_attach(self) -> int:
        """Bump the sidecar epoch (one consumer generation = one epoch),
        stamp the first heartbeat, and return the NEW epoch."""
        return int(self.lib.pingoo_ring_sidecar_attach(self.addr))

    def heartbeat(self) -> None:
        """Stamp the liveness heartbeat (called every poll cycle)."""
        if not self.map.closed:
            self.lib.pingoo_ring_heartbeat(self.addr)

    def liveness(self) -> dict:
        """One-call liveness snapshot: epoch, heartbeat_ms (0 = no
        sidecar has ever attached), posted_floor, req_tail, now_ms —
        all on the ring's own CLOCK_MONOTONIC ms time base."""
        buf = (ctypes.c_uint64 * 5)()
        if not self.map.closed:
            self.lib.pingoo_ring_liveness(self.addr, buf)
        return {"epoch": int(buf[0]), "heartbeat_ms": int(buf[1]),
                "posted_floor": int(buf[2]), "req_tail": int(buf[3]),
                "now_ms": int(buf[4])}

    def set_posted_floor(self, ticket: int) -> None:
        """Advance the posted floor (monotonic max): every ticket below
        it has a verdict posted, so a reattaching sidecar only scans
        [posted_floor, req_tail) for orphans."""
        self.lib.pingoo_ring_set_posted_floor(self.addr, ticket)

    def reclaim(self, ticket: int) -> Optional[np.ndarray]:
        """Reclaim one orphaned ticket during crash-reattach
        reconciliation: a 1-element REQUEST_SLOT_DTYPE array when the
        request bytes are still intact (re-evaluate them), or None when
        the slot was reused (fail-open the ticket). Also unwedges a
        slot whose consumer died between its tail-CAS and seq-release."""
        out = np.zeros(1, dtype=REQUEST_SLOT_DTYPE)
        if self.lib.pingoo_ring_reclaim_request(
                self.addr, ticket,
                out.ctypes.data_as(ctypes.c_void_p)) != 0:
            return None
        return out


def slots_to_arrays(slots: np.ndarray) -> dict:
    """Structured slots -> engine batch arrays (zero-parse bulk decode)."""
    arrays: dict = {}
    for field, cap in FIELD_CAPS.items():
        arrays[f"{field}_bytes"] = np.ascontiguousarray(slots[field])
        arrays[f"{field}_len"] = slots[f"{field}_len" if field != "user_agent"
                                       else "ua_len"].astype(np.int32)
    country = np.frombuffer(
        slots["country"].tobytes(), dtype=np.uint8).reshape(-1, 2)
    arrays["country_bytes"] = np.ascontiguousarray(country)
    arrays["country_len"] = np.full(len(slots), 2, dtype=np.int32)
    ip = slots["ip"].reshape(-1, 16)
    arrays["ip"] = np.ascontiguousarray(
        ip.view(">u4").reshape(-1, 4).astype(np.uint32))
    arrays["asn"] = slots["asn"].astype(np.int64)
    arrays["remote_port"] = slots["remote_port"].astype(np.int64)
    return arrays


class _TableMarker(str):
    """Identity-carrying marker for services-table upstream entries.
    A marker is recognized ONLY by `isinstance` + identity — a config-
    derived hostname that happens to equal a marker's text can never be
    mistaken for one (it raises in _append_tls with guidance to use the
    explicit (ip, port, "tls", name) form instead)."""

    __slots__ = ()


# Marks a services-table upstream as the loopback control plane: the
# C++ connector sends its per-boot internal token on hops to it, which
# is what lets the Python listener trust the injected x-forwarded-for.
INTERNAL = _TableMarker("internal")
# Marks a cleartext prior-knowledge HTTP/2 upstream (config scheme
# h2://): the C++ connector frames requests over an nghttp2 client
# session instead of h1 (reference hyper client speaks h2 upstream,
# http_proxy_service.rs:54-71).
H2 = _TableMarker("h2-prior-knowledge")


def _append_tls(lines: list, ip, port, sni, explicit: bool = False) -> None:
    if (not sni or len(sni) > 255 or any(ch.isspace() for ch in sni)):
        # 255 = the C++ reader's %255s scan width; a longer name would
        # be silently truncated into a hop that can never pass
        # hostname verification.
        raise ValueError(f"bad tls server name {sni!r}")
    if not explicit and sni in (INTERNAL, H2):
        # Reserved table keywords in the legacy 3-tuple form are
        # ambiguous: a server name that collides with a marker must use
        # the unambiguous (ip, port, "tls", name) form (explicit=True)
        # — silently re-tagging the hop would either leak the internal
        # token or downgrade TLS to cleartext h2.
        raise ValueError(
            f"tls server name {sni!r} collides with a table marker; "
            f"use the (ip, port, 'tls', name) entry form")
    lines.append(f"upstream {ip} {port} tls {sni}")


def write_services_file(path: str, services: list) -> None:
    """Publish the native plane's routing table: `services` is the
    listener's ordered [(name, [upstream, ...])] — typically registry
    snapshots (host/discovery.ServiceRegistry.get_upstreams) — or
    `(name, upstreams, static_root)` for a static-site service (the
    C++ plane serves its <=500KB files directly; bigger ones proxy to
    the upstream list). Each upstream is `(ip, port)` for plaintext,
    `(ip, port, server_name)` for a verified TLS hop (the C++
    connector dials it with SNI + hostname checks against server_name,
    reference http_proxy_service.rs:54-71), `(ip, port, H2)` for
    cleartext prior-knowledge h2, or `(ip, port, INTERNAL)` for the
    loopback control plane (token-authenticated identity headers).
    Written atomically (tmp + rename) so the C++ reader (httpd.cc
    ServiceTable) never observes a partial table; it hot-reloads on
    mtime change."""
    if len(services) > 31:
        raise ValueError(
            f"native routing supports at most 31 services (5-bit route "
            f"field, 31 = no match), got {len(services)}")
    lines = ["pingoo-services v1"]
    for order, entry in enumerate(services):
        name, ups = entry[0], entry[1]
        static_root = entry[2] if len(entry) > 2 else None
        lines.append(f"service {order} {name}")
        if static_root is not None:
            if (not static_root or len(static_root) > 383
                    or any(ch.isspace() for ch in static_root)):
                # %383s scan width; whitespace would split the token.
                raise ValueError(f"bad static root {static_root!r}")
            lines.append(f"static {static_root}")
        for up in ups:
            if len(up) == 2:
                lines.append(f"upstream {up[0]} {up[1]}")
            elif len(up) == 4 and up[2] == "tls":
                # unambiguous TLS form: (ip, port, "tls", server_name)
                _append_tls(lines, up[0], up[1], up[3], explicit=True)
            elif isinstance(up[2], _TableMarker) and up[2] is INTERNAL:
                lines.append(f"upstream {up[0]} {up[1]} internal")
            elif isinstance(up[2], _TableMarker) and up[2] is H2:
                lines.append(f"upstream {up[0]} {up[1]} h2")
            else:
                _append_tls(lines, up[0], up[1], up[2])
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.replace(tmp, path)


class _MegaSlice:
    """One staged megastep slice's resolve metadata (ISSUE 12): the
    per-batch state `_dispatch` would have threaded through its
    in-flight tuple, parked until the window's single device sync."""

    __slots__ = ("parts", "slots", "raw", "n", "skip_masks", "slot_buf",
                 "pipe_slot", "epoch", "oldest_enq_ms")


class _MegaWindow:
    """One in-flight K-slice megastep window (ISSUE 12): the deque
    entry `_complete_inflight` routes to `_complete_megastep`."""

    __slots__ = ("slices", "k", "k_ship", "dev_out", "t_launch",
                 "window_id")


class RingSidecar:
    """Drain loop: ring batches -> jitted verdict -> verdict ring.

    `ring` may be a single Ring or a list of Rings — the data plane
    scales across cores as N SO_REUSEPORT worker processes with one
    ring each (verdicts must return on the worker's own ring; the
    verdict queue is MPMC, so co-consumers would steal each other's
    tickets). The sidecar drains all rings into ONE merged device batch
    per cycle and scatters the verdicts back per ring.
    """

    def __init__(self, ring, plan, lists, max_batch: int = 1024,
                 idle_sleep_s: float = 0.0002, pipeline_depth: int = 3,
                 services: Optional[list] = None, geoip=None,
                 ring_services: Optional[list] = None):
        self.rings: list[Ring] = list(ring) if isinstance(
            ring, (list, tuple)) else [ring]
        self.ring = self.rings[0]  # single-ring callers' view
        self.plan = plan
        self.lists = lists
        self.max_batch = max_batch
        self.idle_sleep_s = idle_sleep_s
        # Batches dispatched-but-not-collected. Depth > 1 only pays off
        # when producers keep more than one batch of requests in flight;
        # it hides the device round-trip latency (large when the chip is
        # behind a network tunnel) behind the next batch's host work.
        self.pipeline_depth = max(1, pipeline_depth)
        # Overlapped zero-copy executor (ISSUE 9, docs/EXECUTOR.md):
        # PINGOO_PIPELINE=on (default) dequeues straight into pooled
        # slot buffers (Ring.dequeue_batch_into) and encodes through
        # the reused StagingEncoder views — no per-batch concatenate /
        # slots_to_arrays / bucket / pad allocations; =off keeps the
        # legacy chain (the bench A/B arm and the parity oracle path).
        # PINGOO_PIPELINE_DEPTH overrides the in-flight bound for both.
        mode = os.environ.get("PINGOO_PIPELINE", "on").strip().lower()
        self.pipeline_mode = "off" if mode in ("off", "0", "false") \
            else "on"
        try:
            self.pipeline_depth = max(1, int(os.environ.get(
                "PINGOO_PIPELINE_DEPTH", str(self.pipeline_depth))))
        except ValueError:
            pass
        self._zero_copy = self.pipeline_mode == "on"
        # Continuous-batching admission scheduler (ISSUE 6, docs/
        # SCHEDULER.md): replaces the fixed drain window (dispatch
        # whatever one dequeue pass returned) with the deadline-slack
        # launch policy shared with the Python plane. Timestamps come
        # from the ring's enq_ms clock (pingoo_ring_now_ms), converted
        # to seconds for the scheduler.
        from .sched import MeshUnavailable, Scheduler, SchedulerConfig

        self.sched = Scheduler(SchedulerConfig.from_env(max_batch),
                               plane="sidecar")
        # Perf ledger + cross-plane timeline + durable cost ledger
        # (ISSUE 17, docs/OBSERVABILITY.md): compile events from every
        # jitted program below become counted/persisted ledger entries
        # (no-op passthrough while PINGOO_PERF_LEDGER is off), sampled
        # batches emit cross-plane spans joined on the ring clock, and
        # the CostModel reloads the prior run's measured EWMAs keyed to
        # this backend + ruleset fingerprint.
        from .obs.perf import get_compile_ledger, plan_fingerprint
        from .obs.timeline import get_timeline
        from .sched.scheduler import load_cost_ledger

        self._plan_fp = plan_fingerprint(plan)
        self._perf = get_compile_ledger()
        self._perf.ensure_instruments("sidecar")
        self._timeline = get_timeline()
        self._timeline.ensure_instruments("sidecar")
        self._backend_label = "host"
        try:
            import jax

            self._backend_label = str(jax.default_backend())
        except Exception:
            pass
        self.cost_ledger_result = load_cost_ledger(
            self.sched.cost, backend=self._backend_label,
            fingerprint=self._plan_fp, plane="sidecar")
        # The sidecar uses the transfer-thin lane reduction — the
        # first-match action decision computes ON DEVICE and only four
        # int32 lanes come back, not the [B, R] match matrix (which
        # dominated per-batch time through a network tunnel).
        # `services` (the native listener's service names, in order)
        # adds the ROUTE lane so the C++ plane can dispatch each request
        # to the right service's upstream set (verdict byte bits 3-7).
        self.services = list(services) if services else None
        # `ring_services` (aligned with `rings`; entries may be None)
        # gives each worker ring its OWN service order — the reference
        # binds a service list per listener (config.rs:241-253), and the
        # native plane runs one ring per (listener, worker). The lane fn
        # computes one route lane per DISTINCT order; each row reads the
        # lane of the ring it arrived on.
        if ring_services is not None:
            if services is not None:
                raise ValueError("pass services or ring_services, not both")
            if len(ring_services) != len(self.rings):
                raise ValueError(
                    f"ring_services has {len(ring_services)} entries for "
                    f"{len(self.rings)} rings")
            per_ring = [list(s) if s else None for s in ring_services]
        else:
            per_ring = [self.services] * len(self.rings)
        self._groups: list[list] = []
        self._ring_group: list[Optional[int]] = []
        for svc in per_ring:
            if svc is None:
                self._ring_group.append(None)
                continue
            for gi, g in enumerate(self._groups):
                if g == svc:
                    break
            else:
                gi = len(self._groups)
                self._groups.append(svc)
            self._ring_group.append(gi)
        for g in self._groups:
            if len(g) > 31:
                # The verdict byte's route field is 5 bits: orders 0-30
                # plus the no-match sentinel 31. More services would
                # alias the sentinel onto a real service and invert
                # no-match into proxy-to-last-service.
                raise ValueError(
                    f"native routing supports at most 31 services, "
                    f"got {len(g)}")
        self._ring_group_of = {id(r): gi for r, gi in
                               zip(self.rings, self._ring_group)}
        # Verdict provenance (ISSUE 5): the per-rule attribution fold
        # rides the lane dispatch as an aux output (with_rule_hits) —
        # the match matrix itself still never leaves the device.
        from .obs.provenance import provenance_enabled

        self._provenance_on = provenance_enabled()
        # Degradation ladder (ISSUE 10, docs/RESILIENCE.md): the
        # scattered fallbacks below route through one explicit state
        # machine — demotions are counted per rung and probed back
        # with exponential backoff (engine/ladder.py).
        from .engine.ladder import DegradationLadder

        self.ladder = DegradationLadder("sidecar")
        # Streaming body inspection (ISSUE 13, docs/BODY_STREAMING.md):
        # when PINGOO_BODY_INSPECT=on the sidecar drains the v6
        # body-window ring each cycle, threads NFA/DFA carry state
        # across windows (engine/bodyscan.py), and posts body verdicts
        # on the SAME verdict ring tagged BODY_VERDICT_BIT. Off (the
        # default) the drain is skipped entirely — bit-exact status
        # quo. A scanner fault demotes the ladder's "body" rung:
        # windows fail open to metadata-only until a probe recovers.
        from .engine import bodyscan as _bodyscan

        self._bodyscan_mod = _bodyscan
        self._body_scan = None
        self.body_verdicts = 0
        if _bodyscan.body_inspect_enabled():
            try:
                self._body_scan = _bodyscan.BodyScanner()
                self._body_scan.attach_metrics("sidecar")
            except Exception as exc:
                self.ladder.note_failure("body", exc)
        # The C++ plane has no mmdb decoder: it enqueues slots with
        # asn=0 / country="XX" (its unknown markers). The reference
        # resolves geoip per request in the listener
        # (http_listener.rs:143-157); here the sidecar enriches those
        # rows from the host GeoipDB (host/geoip.py, cached) before
        # encoding, so geo/asn rules see real values for natively
        # fronted traffic too. None disables (geo rules then evaluate
        # on XX/0, the reference's missing-database behavior).
        self.geoip = geoip
        self.processed = 0
        self.truncated_rows = 0
        self.spilled_rows = 0  # overflow rows re-evaluated untruncated
        # Depth-capped rows re-evaluated over the full slot view
        # (ISSUE 15: PINGOO_STAGING=compact with a PINGOO_STAGING_DEPTH
        # clamp below a field's required depth).
        self.depth_overflow_rows = 0
        self.batches = 0
        self.device_wait_s = 0.0  # blocking time on device lane results
        self._ring_rr = -1  # rotating drain start (multi-ring fairness)
        self._thread = None  # set by run(); joined by stop()
        self._stop = False
        # Unified telemetry (obs/): per-stage drain-loop histograms plus
        # a collector that folds the rings' shm telemetry blocks into
        # the shared registry, so the Python control-plane scrape
        # carries native-plane queue state in the same exposition.
        from .obs import REGISTRY

        self._registry = REGISTRY
        # Pipeline executor substrate (ISSUE 9): the staging encoder's
        # rotating buffer sets must outlive every in-flight batch that
        # still reads its views (depth in flight + the one being
        # filled), and the slot-buffer pool holds one accumulation
        # buffer per in-flight batch plus the one being filled — a
        # drained pool allocates a fresh buffer (cold path only).
        from collections import deque as _deque

        from .engine.batch import StagingEncoder
        from .obs.pipeline import PipelineStats

        self._pipe = PipelineStats("sidecar", self.pipeline_depth)
        self._staging = None
        self._slot_pool: _deque = _deque()
        caps = dict(FIELD_CAPS)
        caps["country"] = 2
        # Device-resident megastep (ISSUE 12, docs/EXECUTOR.md
        # "Device-resident loop"): PINGOO_MEGASTEP=off|auto|force. In a
        # megastep window the drain loop STAGES admitted batches into
        # the DeviceInputQueue's double-buffered [K, B, ...] host
        # stacks instead of dispatching each one, then runs ONE jitted
        # lax.scan over all K slices — one dispatch wall amortized over
        # K batches. `off` keeps the per-batch path (the bit-exact
        # parity oracle), `auto` engages only with backlog queued
        # behind the window, `force` megasteps every window (the bench
        # arm). Short/stale slices are masked on device by their
        # n_valid/epoch words, never re-shaped.
        from .engine.batch import DeviceInputQueue
        from .engine.verdict import (_resolve_megastep_mode,
                                     megastep_k_cap, megastep_k_ladder)

        self._mega_mode = _resolve_megastep_mode()
        self._mega_k = megastep_k_cap()
        self._mega_rungs = megastep_k_ladder(self._mega_k)
        self._mega_queue = None
        self._mega_staged: list = []
        self._mega_buf_id = 0
        self._mega_target = 1
        self._mega_fn = None
        self.mega_windows = 0
        self.mega_echo_mismatch = 0  # device epoch echo != staged epoch
        if self._mega_mode != "off":
            self._mega_queue = DeviceInputQueue(
                self._mega_k, max_batch, field_specs=caps, nbuf=2)
        # Slot-buffer pool: one per in-flight batch plus the one being
        # filled; a staged megastep window parks up to K slot buffers
        # until its single resolve, so the pool covers whichever bound
        # is larger.
        pool_n = max(self.pipeline_depth,
                     self._mega_k if self._mega_mode != "off" else 1) + 1
        if self._zero_copy:
            self._staging = self._make_staging(plan, caps)
            for _ in range(pool_n):
                self._slot_pool.append(
                    np.zeros(max_batch, dtype=REQUEST_SLOT_DTYPE))
        self._stage = {
            stage: REGISTRY.histogram(
                "pingoo_verdict_stage_ms",
                "verdict pipeline stage latency (ms)",
                labels={"plane": "sidecar", "stage": stage})
            for stage in ("sched", "encode", "prefilter",
                          "device_dispatch", "device_compute", "resolve",
                          "provenance")}
        # Compact staging (ISSUE 15): bytes staged to the device per
        # verdict batch, by PINGOO_STAGING arm — same series the Python
        # listener plane exports.
        from .obs.schema import STAGING_METRICS

        self._staged_bytes_counter = {
            mode: REGISTRY.counter(
                "pingoo_staged_bytes_total",
                STAGING_METRICS["pingoo_staged_bytes_total"],
                labels={"plane": "sidecar", "mode": mode})
            for mode in ("full", "compact")}
        # Stage-A literal prefilter (docs/PREFILTER.md): the sidecar is
        # the native plane's verdict engine, so it exports the same
        # candidate-rate/skip metrics the Python listener plane does.
        from .obs.schema import PREFILTER_METRICS

        self._pf_rate_gauge = REGISTRY.gauge(
            "pingoo_prefilter_candidate_rate",
            PREFILTER_METRICS["pingoo_prefilter_candidate_rate"],
            labels={"plane": "sidecar"})
        self._pf_skip_counter = REGISTRY.counter(
            "pingoo_scan_banks_skipped_total",
            PREFILTER_METRICS["pingoo_scan_banks_skipped_total"],
            labels={"plane": "sidecar"})
        # Bitsplit-DFA dispatch accounting (docs/DFA.md): same series
        # the Python listener plane exports, host-static per plan+env
        # (engine/verdict.dfa_dispatch_counts), folded once per batch.
        from .obs.schema import DFA_METRICS

        self._dfa_banks_counter = {
            mode: REGISTRY.counter(
                "pingoo_dfa_banks_total",
                DFA_METRICS["pingoo_dfa_banks_total"],
                labels={"plane": "sidecar", "mode": mode})
            for mode in ("auto", "force")}
        self._dfa_recheck_counter = REGISTRY.counter(
            "pingoo_dfa_recheck_total",
            DFA_METRICS["pingoo_dfa_recheck_total"],
            labels={"plane": "sidecar"})
        # Attribution lanes + flight recorder + shadow-parity auditor
        # for the native plane's verdict engine (this drain loop).
        self._attribution = None
        self.flight_recorder = None
        self.parity = None
        # Ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md): every
        # plan-derived piece of engine state (jitted lane fn, host
        # routes, mesh+tables, prefilter, attribution, dev cols) is
        # built by _build_plan_state and installed by _adopt_plan_state
        # — at init here, and again at a drain-loop batch boundary when
        # request_swap hands over a plan compiled ahead of time.
        import threading as _threading

        self._swap_lock = _threading.Lock()
        self._swap_queue: list = []
        self.ruleset_epoch = 0
        self.tenant = "default"
        # drain+flip pause per applied swap (ms) — chaos_smoke folds
        # the p99 into the bench summary (swap_pause_p99_ms).
        self.swap_pauses_ms: list = []
        self._adopt_plan_state(plan, None, self._build_plan_state(plan))
        from .engine.hotswap import set_epoch_gauge

        set_epoch_gauge("sidecar", 0)
        self._collector_live = True
        REGISTRY.register_collector(self._export_ring_telemetry)
        # -- sidecar supervision (ISSUE 10, docs/RESILIENCE.md) ---------------
        from .obs.chaos import ChaosInjector
        from .obs.schema import RESILIENCE_METRICS

        self.chaos = ChaosInjector.from_env()
        # Liveness protocol (ring v5): bump each ring's epoch so the
        # data plane can tell a restarted sidecar from a frozen one,
        # then reconcile tickets the dead epoch dequeued but never
        # answered — BEFORE the drain loop starts, so reconciliation
        # verdicts can never race this epoch's own posts.
        self._reattach_counters = {
            action: REGISTRY.counter(
                "pingoo_reattach_reconciled_total",
                RESILIENCE_METRICS["pingoo_reattach_reconciled_total"],
                labels={"plane": "sidecar", "action": action})
            for action in ("reeval", "failopen")}
        self.reconciled = {"reeval": 0, "failopen": 0}
        self.epochs = [r.sidecar_attach() for r in self.rings]
        self.epoch = max(self.epochs)
        REGISTRY.gauge(
            "pingoo_sidecar_epoch",
            RESILIENCE_METRICS["pingoo_sidecar_epoch"],
            labels={"plane": "sidecar"}).set(self.epoch)
        # Busy-window heartbeat watchdog (docs/RESILIENCE.md): the
        # drain loop legitimately blocks for seconds inside XLA
        # compiles (first call per pow2 bucket), the device-result
        # sync, interpreter fallbacks, and reattach reconciliation —
        # without this, every such window flips the data plane
        # degraded and fails live requests open. The watchdog stamps
        # ONLY while the loop is inside one of those declared windows
        # (`_hb_busy`), bounded by the grace cap: a SIGKILL silences
        # it with the process, a loop wedged anywhere else stops
        # stamping immediately, and a device call hung past the grace
        # goes dark too (per-ticket verdict timeouts bound the harm
        # meanwhile).
        import threading as _threading

        self._busy_since: Optional[float] = None
        self._hb_watchdog = _threading.Thread(
            target=self._heartbeat_watchdog, name="pingoo-hb-watchdog",
            daemon=True)
        self._hb_watchdog.start()
        with self._hb_busy():
            self._reconcile_orphans()

    # A device call (compile/execute) blocked longer than this is
    # treated as wedged: the watchdog stops covering for it and the
    # data plane's liveness detector takes over. Far above any real
    # XLA compile, far below "hung forever".
    _HB_BUSY_GRACE_S = 120.0

    @contextlib.contextmanager
    def _hb_busy(self):
        """Declare a known-blocking drain-loop window (XLA compile,
        device sync, interpreter fallback, reattach reconciliation):
        the heartbeat watchdog stamps only inside these."""
        self._busy_since = time.monotonic()
        try:
            yield
        finally:
            self._busy_since = None

    def _heartbeat_watchdog(self) -> None:
        while not self._stop:
            busy = self._busy_since
            if busy is not None \
                    and time.monotonic() - busy < self._HB_BUSY_GRACE_S \
                    and not self.chaos.heartbeat_frozen():
                for r in self.rings:
                    r.heartbeat()
            time.sleep(0.1)

    # -- ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md) ----------------------

    def _make_staging(self, plan, caps: dict):
        """The zero-copy staging encoder for a plan: plain rotating
        buffers under PINGOO_STAGING=full, packed one-copy layout under
        =compact (ISSUE 15) — slot-direct capped-prefix copies into one
        flat buffer, one device_put per batch."""
        from .engine.batch import (StagingEncoder, resolve_stage_caps,
                                   stage_overflow_thresholds)

        scaps = resolve_stage_caps(plan)
        if scaps is None:
            return StagingEncoder(self.max_batch, field_specs=caps,
                                  nbuf=self.pipeline_depth + 1)
        return StagingEncoder(
            self.max_batch, field_specs=caps,
            nbuf=self.pipeline_depth + 1, stage_caps=scaps,
            overflow_thresholds=stage_overflow_thresholds(plan, scaps))

    def _build_plan_state(self, plan) -> dict:
        """Every plan-derived piece of the sidecar's engine state, built
        OFF the drain loop (init, or a request_swap caller's thread —
        compile-ahead through compiler/cache): the drain loop's flip is
        then pointer assignment at a batch boundary, never compilation."""
        from .engine.batch import (resolve_stage_caps,
                                   stage_overflow_thresholds)
        from .engine.verdict import (donate_batch_buffers, make_lane_fn,
                                     make_packed_lane_fn,
                                     make_packed_prefilter_fn,
                                     make_prefilter_fn)
        from .obs.perf import (instrument_jit, plan_fingerprint,
                               staging_widths)
        from .sched import MeshExecutor, MeshUnavailable

        state: dict = {"plan": plan}
        # Compile-ledger wrapping (ISSUE 17): composes AFTER jax.jit
        # (donation/static_argnums untouched); passthrough while
        # PINGOO_PERF_LEDGER is off.
        fp = plan_fingerprint(plan)
        widths = staging_widths(plan)

        def _wrap(fn, name):
            return instrument_jit(fn, name, plane="sidecar",
                                  fingerprint=fp, widths=widths)

        state["lane_fn"] = _wrap(make_lane_fn(
            plan, service_groups=self._groups or None,
            with_rule_hits=self._provenance_on,
            donate=donate_batch_buffers()), "lanes")
        # Compact staging (ISSUE 15): the packed twins decode the
        # one-copy buffer on device; built only under
        # PINGOO_STAGING=compact (the default full arm traces nothing
        # new). Caps/thresholds flip with the plan at the same batch
        # boundary the fns do.
        state["stage_caps"] = resolve_stage_caps(plan)
        state["stage_thresholds"] = None
        state["packed_lane_fn"] = None
        state["packed_pf_fn"] = None
        if state["stage_caps"] is not None:
            state["stage_thresholds"] = stage_overflow_thresholds(
                plan, state["stage_caps"])
            state["packed_lane_fn"] = _wrap(make_packed_lane_fn(
                plan, service_groups=self._groups or None,
                with_rule_hits=self._provenance_on,
                donate=donate_batch_buffers()), "lanes")
            ppf = make_packed_prefilter_fn(plan)
            state["packed_pf_fn"] = \
                _wrap(ppf.fn, "prefilter") if ppf is not None else None
        # Services whose route predicate fell back to host interpretation
        # are merged into the device route lane per batch (per group).
        host_routes: list = []
        by_index = {r.index: r for r in plan.rules}
        for g in self._groups:
            hr = []
            for order, name in enumerate(g):
                ridx = plan.route_index.get(name)
                if ridx is not None and by_index[ridx].host:
                    hr.append((order, by_index[ridx].program))
            host_routes.append(hr)
        state["host_routes"] = host_routes
        # Serving mesh (ISSUE 6): tp padding must land in plan.np_tables
        # before device_tables() materializes; failures degrade to the
        # single-device path (never crash the drain) and stay visible
        # via pingoo_mesh_devices == 1.
        try:
            mesh = MeshExecutor(plan, plane="sidecar",
                                metrics=self.sched.metrics)
        except (MeshUnavailable, ValueError) as exc:
            self.ladder.note_failure("mesh", exc)
            mesh = MeshExecutor(plan, spec=(1, 1, 1), plane="sidecar",
                                metrics=self.sched.metrics)
        state["mesh"] = mesh
        tables = plan.device_tables()
        state["tables"] = (mesh.place_tables(tables)
                           if mesh.active else tables)
        state["pf_fn"] = None
        state["pf_gated_banks"] = 0
        state["pf_attr"] = None
        pf = make_prefilter_fn(plan)
        if pf is not None:
            state["pf_fn"] = _wrap(pf.fn, "prefilter")
            state["pf_gated_banks"] = len(pf.gated)
            if self._provenance_on:
                from .obs.provenance import PrefilterAttribution

                state["pf_attr"] = PrefilterAttribution(
                    pf.masked, plane="sidecar")
        state["dev_cols"] = np.asarray(plan.device_rule_indices,
                                       dtype=np.int64)
        # Megastep program (ISSUE 12): same unjitted prefilter/lane
        # bodies as the per-batch programs above, scanned over K
        # slices — bit-identical by construction. Built only when the
        # mode can engage (the jit trace is per plan, like lane_fn).
        state["mega_fn"] = None
        if self._mega_mode != "off":
            from .engine.verdict import make_megastep_fn
            from .obs.perf import instrument_megastep

            state["mega_fn"] = instrument_megastep(
                make_megastep_fn(
                    plan, kind="lanes",
                    service_groups=self._groups or None,
                    with_rule_hits=self._provenance_on),
                plane="sidecar", fingerprint=fp, widths=widths)
        return state

    def _adopt_plan_state(self, plan, lists, state: dict) -> None:
        """Flip the drain loop onto a prebuilt plan state. Only safe at
        a batch boundary (init, or _apply_swaps after a full drain):
        _dispatch/_complete read these references per batch."""
        self.plan = plan
        if lists is not None:
            self.lists = lists
        self._lane_fn = state["lane_fn"]
        self._host_routes = state["host_routes"]
        self.mesh = state["mesh"]
        self._tables = state["tables"]
        self._pf_fn = state["pf_fn"]
        self._pf_gated_banks = state["pf_gated_banks"]
        self._pf_attr = state["pf_attr"]
        self._dev_cols = state["dev_cols"]
        self._mega_fn = state.get("mega_fn")
        self._dfa_mode0 = getattr(plan, "dfa_default_mode", "auto")
        self._dfa_probe = False
        # Compact staging (ISSUE 15): re-cap the staging encoder's
        # packed layout for the new plan at the same flip — every batch
        # is encoded AND decoded under one cap set, so a swap that
        # widens a cap changes layout only at this batch boundary.
        self._stage_caps = state.get("stage_caps")
        self._packed_lane_fn = state.get("packed_lane_fn")
        self._packed_pf_fn = state.get("packed_pf_fn")
        if self._staging is not None and self._stage_caps is not None:
            try:
                self._staging.set_stage_caps(
                    self._stage_caps, state.get("stage_thresholds"))
            except ValueError:
                # Encoder built without packed buffers (mode flipped
                # between boot and swap): keep the per-field path.
                self._packed_lane_fn = self._packed_pf_fn = None
        if self._stage_caps:
            from .obs import REGISTRY
            from .obs.schema import STAGING_METRICS

            for field, cap in self._stage_caps.items():
                REGISTRY.gauge(
                    "pingoo_staging_field_cap",
                    STAGING_METRICS["pingoo_staging_field_cap"],
                    labels={"field": field}).set(int(cap))
        self._plan_state = state
        if self._provenance_on:
            from .obs.flightrecorder import (FlightRecorder,
                                             register_recorder)
            from .obs.provenance import ParityAuditor, RuleAttribution

            if self._attribution is not None:
                self._attribution.close()
            if self.parity is not None:
                self.parity.stop()
            self.flight_recorder = register_recorder(FlightRecorder(
                "sidecar", rule_names=plan.rule_names))
            self._attribution = RuleAttribution(plan.rule_names,
                                                plane="sidecar")
            self.parity = ParityAuditor(plan, self.lists,
                                        plane="sidecar",
                                        recorder=self.flight_recorder)

    def request_swap(self, plan, lists=None, tenant: str = "default",
                     state: Optional[dict] = None):
        """Thread-safe ruleset hot-swap request.

        Builds the new plan's engine state HERE — the caller's thread,
        off the drain loop (pair with compiler/cache's
        compile_ruleset_cached or engine/hotswap.TenantPlanStore for
        compile-ahead) — then queues a SwapHandle the drain loop flips
        to at its next batch boundary: in-flight batches finish on the
        old plan, admissions after the flip use the new one, and every
        verdict belongs to exactly one epoch. `handle.wait()` blocks
        until the flip; the loop must be running (a request made after
        shutdown resolves "rejected" at the final flush)."""
        from .engine.hotswap import SwapHandle, note_swap

        if state is None:
            try:
                state = self._build_plan_state(plan)
            except Exception as exc:
                note_swap("sidecar", tenant, "rejected")
                raise RuntimeError(
                    f"hot-swap build failed for tenant {tenant!r}: "
                    f"{exc}") from exc
        handle = SwapHandle(plan=plan, tenant=tenant, lists=lists,
                            state=state)
        with self._swap_lock:
            self._swap_queue.append(handle)
        return handle

    def _apply_swaps(self, inflight, pend_parts, pend_n,
                     oldest_enq_ms, pend_buf):
        """Apply every queued hot-swap at this batch boundary: launch
        and complete everything ADMITTED on the old plan first (each
        ticket posts exactly once, on the plan of its admission epoch —
        zero dropped, zero double-posted), then flip to the prebuilt
        state. The pause clock covers drain+flip only; the requester
        compiled ahead on its own thread (engine/hotswap.py)."""
        from .engine.hotswap import note_swap, set_epoch_gauge

        t0 = time.monotonic()
        with self._hb_busy():
            if pend_parts:
                # Megastep boundary (ISSUE 12): pending slots join the
                # OPEN window when one exists — launching them per-batch
                # past staged (older) slices would post their tickets
                # first and break the posted-floor prefix invariant.
                if self._mega_staged \
                        and len(self._mega_staged) < self._mega_k:
                    self._stage_mega_slice(pend_parts, pend_n,
                                           oldest_enq_ms,
                                           slot_buf=pend_buf)
                else:
                    if self._mega_staged:
                        inflight.append(self._launch_megastep())
                    inflight.append(self._dispatch(pend_parts, pend_n,
                                                   oldest_enq_ms,
                                                   slot_buf=pend_buf))
                pend_parts, pend_n, oldest_enq_ms = [], 0, None
                pend_buf = self._take_slot_buf() if self._zero_copy \
                    else None
            if self._mega_staged:
                # The flip happens only at a megastep boundary: every
                # slice staged under the old epoch computes and posts
                # on the old plan before the new one is adopted.
                inflight.append(self._launch_megastep())
            while inflight:
                self._complete_inflight(inflight.popleft())
            while True:
                with self._swap_lock:
                    if not self._swap_queue:
                        break
                    handle = self._swap_queue.pop(0)
                try:
                    self._adopt_plan_state(handle.plan, handle.lists,
                                           handle.state)
                except Exception as exc:  # never kill the drain loop
                    note_swap("sidecar", handle.tenant, "rejected")
                    handle.resolve(self.ruleset_epoch, 0.0,
                                   result="rejected", error=exc)
                    continue
                self.ruleset_epoch += 1
                self.tenant = handle.tenant
                pause_ms = (time.monotonic() - t0) * 1e3
                set_epoch_gauge("sidecar", self.ruleset_epoch)
                note_swap("sidecar", handle.tenant, "ok")
                self._stage["sched"].observe(pause_ms)
                self.swap_pauses_ms.append(pause_ms)
                handle.resolve(self.ruleset_epoch, pause_ms)
        return pend_parts, pend_n, oldest_enq_ms, pend_buf

    def run(self, max_requests: Optional[int] = None) -> int:
        """Blocking drain loop; returns requests processed.

        Two-deep pipeline: batch N+1 is DISPATCHED (jax is async) and its
        host-interpreted rules evaluated while batch N's device verdict
        is still in flight — so per-batch wall time is the max of host
        work and device occupancy, not their sum plus the transport
        round trip (which matters doubly when the chip sits behind a
        network tunnel).

        Admission (ISSUE 6): dequeued slots ACCUMULATE across drain
        cycles under the continuous-batching scheduler — a batch
        launches when it is full, or when the oldest request's
        remaining deadline slack (enq_ms clock) no longer covers the
        EWMA dispatch estimate. PINGOO_SCHED_MODE=fixed restores the
        legacy dispatch-every-pass window.
        """
        from collections import deque

        import threading as _threading

        # stop() joins this thread before callers unmap the rings — a
        # dequeue racing Ring.close() would be a use-after-munmap
        # segfault in the ctypes call.
        self._thread = _threading.current_thread()
        inflight: deque = deque()
        sched = self.sched
        continuous = sched.config.mode == "continuous"
        pend_parts: list[tuple[Ring, np.ndarray]] = []
        pend_n = 0
        oldest_enq_ms: Optional[int] = None
        # Zero-copy accumulation buffer (PINGOO_PIPELINE=on): every
        # ring's dequeue FFI lands its slots contiguously at this
        # buffer's next free row, so the merged launch batch is one
        # view — the buffer travels with the batch and returns to the
        # pool when `_complete` finishes it.
        pend_buf = self._take_slot_buf() if self._zero_copy else None
        while not self._stop:
            # Liveness heartbeat (ring v5): one relaxed shm store per
            # ring per poll cycle. Deliberately stamped from THIS loop
            # (not a free-running helper thread): a wedged drain loop
            # must look dead to the data plane's
            # PINGOO_SIDECAR_TIMEOUT_MS detector. The one exception is
            # declared known-blocking windows (XLA compile, device
            # sync, interpreter fallback — `_hb_busy`), which the
            # bounded watchdog covers so a cold compile under live
            # traffic does not flip the plane degraded —
            # docs/RESILIENCE.md.
            if not self.chaos.heartbeat_frozen():
                for r in self.rings:
                    r.heartbeat()
            # Body-window drain (ISSUE 13): before the request drain so
            # a flow's body verdict never waits a full cycle behind the
            # metadata batch that admitted it.
            if self._body_scan is not None:
                self._drain_bodies()
            # Ruleset hot-swap boundary (ISSUE 11). The swap-storm
            # chaos rung re-requests the CURRENT plan so any verdict
            # drift it produces is a swap-protocol bug by construction
            # (state reused: the storm isolates drain/flip mechanics).
            if self.chaos.swap_due(self.batches):
                self.request_swap(self.plan, tenant=self.tenant,
                                  state=self._plan_state)
            if self._swap_queue:
                pend_parts, pend_n, oldest_enq_ms, pend_buf = \
                    self._apply_swaps(inflight, pend_parts, pend_n,
                                      oldest_enq_ms, pend_buf)
            # One merged dequeue pass across all worker rings. The
            # start index rotates so a saturated ring cannot monopolize
            # the budget and starve its siblings into the data plane's
            # verdict timeout (which fails open).
            budget = self.max_batch - pend_n
            nrings = len(self.rings)
            self._ring_rr = (self._ring_rr + 1) % nrings
            got = 0
            for i in range(nrings):
                if budget <= 0:
                    break
                r = self.rings[(self._ring_rr + i) % nrings]
                if pend_buf is not None:
                    fill = pend_n + got
                    k = r.dequeue_batch_into(
                        pend_buf[fill:fill + budget])
                    s = pend_buf[fill:fill + k]
                else:
                    s = r.dequeue_batch(budget)
                if len(s):
                    if self.geoip is not None:
                        # Enrich IN the per-ring slot arrays (the
                        # sidecar owns them: dequeue_batch copies out
                        # of the ring scratch, dequeue_batch_into
                        # lands in the batch's pooled buffer) BEFORE
                        # merging: both the device batch and the
                        # overflow-spill re-interpretation
                        # (_interpret_overflow_row reads the per-ring
                        # part) must see the same geo values.
                        self._enrich_slots(s)
                    pend_parts.append((r, s))
                    budget -= len(s)
                    got += len(s)
                    first = int(s["enq_ms"].min())
                    if oldest_enq_ms is None or first < oldest_enq_ms:
                        oldest_enq_ms = first
            pend_n += got
            launch = False
            if pend_n:
                if not continuous or pend_n >= self.max_batch:
                    launch = True
                else:
                    now_ms = int(self.ring.lib.pingoo_ring_now_ms())
                    launch = sched.should_launch(
                        pend_n, oldest_enq_ms / 1e3, now_ms / 1e3)
            if launch:
                # Megastep drive (ISSUE 12): while a window is open
                # every admitted batch STAGES into it (per-batch
                # launches past staged slices would post younger
                # tickets first and break the posted-floor prefix);
                # _mega_begin decides whether a launch signal with no
                # open window starts one.
                if self._mega_staged or self._mega_begin(oldest_enq_ms):
                    self._stage_mega_slice(pend_parts, pend_n,
                                           oldest_enq_ms,
                                           slot_buf=pend_buf)
                else:
                    inflight.append(self._dispatch(pend_parts, pend_n,
                                                   oldest_enq_ms,
                                                   slot_buf=pend_buf))
                pend_parts, pend_n, oldest_enq_ms = [], 0, None
                if pend_buf is not None:
                    pend_buf = self._take_slot_buf()
            if self._mega_staged and (got == 0 or self._mega_due()):
                # Window full (K target reached), the oldest staged
                # slice's deadline slack no longer covers the window
                # estimate, or the rings went quiet: ship it. A partial
                # window launches with k_used < K — masked, not
                # re-shaped.
                inflight.append(self._launch_megastep())
            if inflight and (len(inflight) >= self.pipeline_depth
                             or not launch):
                self._complete_inflight(inflight.popleft())
            if got == 0 and not launch and not inflight \
                    and not self._mega_staged:
                if not pend_parts and max_requests is not None \
                        and self.processed >= max_requests:
                    break
                time.sleep(self.idle_sleep_s)
            if max_requests is not None and self.processed >= max_requests \
                    and not inflight and not pend_parts \
                    and not self._mega_staged:
                break
        # Flush: accumulated-but-unlaunched slots still get verdicts
        # (the data plane would otherwise eat a fail-open timeout).
        if pend_parts:
            if self._mega_staged and len(self._mega_staged) < self._mega_k:
                self._stage_mega_slice(pend_parts, pend_n,
                                       oldest_enq_ms, slot_buf=pend_buf)
            else:
                if self._mega_staged:
                    inflight.append(self._launch_megastep())
                inflight.append(self._dispatch(pend_parts, pend_n,
                                               oldest_enq_ms,
                                               slot_buf=pend_buf))
        elif pend_buf is not None:
            self._slot_pool.append(pend_buf)
        if self._mega_staged:
            inflight.append(self._launch_megastep())
        while inflight:
            self._complete_inflight(inflight.popleft())
        # Final body drain: FINAL windows already in the ring still get
        # verdicts (else their held requests eat the fail-open timeout).
        if self._body_scan is not None:
            self._drain_bodies()
        # A swap that never reached a batch boundary before shutdown is
        # rejected, not leaked: wake its requester.
        with self._swap_lock:
            leftovers, self._swap_queue = self._swap_queue, []
        if leftovers:
            from .engine.hotswap import note_swap

            for handle in leftovers:
                note_swap("sidecar", handle.tenant, "rejected")
                handle.resolve(self.ruleset_epoch, 0.0,
                               result="rejected",
                               error=RuntimeError("sidecar stopped"))
        return self.processed

    def _drain_bodies(self) -> None:
        """Drain each ring's body-window ring through the streaming
        scanner and post per-flow body verdicts back on that ring's
        verdict ring, ticket-tagged with BODY_VERDICT_BIT. On the
        ladder's demoted "body" rung (or a scanner fault) every FINAL
        window fails open (action 0, metadata-only) so the data plane's
        held requests never stall on a broken scanner."""
        bs = self._bodyscan_mod
        for r in self.rings:
            slots = r.dequeue_bodies()
            if not len(slots):
                continue
            windows = [bs.BodyWindow(
                flow_id=int(s["flow"]), win_seq=int(s["win_seq"]),
                data=s["data"][:int(s["win_len"])].tobytes(),
                final=bool(s["flags"] & BODY_FLAG_FINAL),
                abort=bool(s["flags"] & BODY_FLAG_ABORT))
                for s in slots]
            verdicts = None
            if self.ladder.try_rung("body"):
                try:
                    # Busy window: the first scan per pow2 row bucket
                    # compiles the chunk kernels.
                    with self._hb_busy():
                        verdicts = self._body_scan.scan_windows(windows)
                    self.ladder.note_success("body")
                except Exception as exc:
                    self.ladder.note_failure("body", exc)
                    # Carry state is suspect after a mid-scan fault:
                    # drop every live flow (their FINAL windows fail
                    # open below or at the data plane's body sweep).
                    self._body_scan.flows.clear()
                    verdicts = None
            if verdicts is None:
                verdicts = [bs.BodyVerdict(w.flow_id, degraded=True)
                            for w in windows if w.final]
            for v in verdicts:
                ticket = v.flow_id | BODY_VERDICT_BIT
                action = 0 if v.degraded else v.action_byte()
                while not r.post_verdict(ticket, action):
                    if self._stop:
                        return
                    time.sleep(self.idle_sleep_s)
                self.body_verdicts += 1
        self._body_scan.evict_stale()

    def _take_slot_buf(self) -> np.ndarray:
        """One pooled REQUEST_SLOT_DTYPE accumulation buffer (pipeline
        hot path: pop; cold path when every pooled buffer is riding an
        in-flight batch: allocate — the pool absorbs it back later)."""
        try:
            return self._slot_pool.popleft()
        except IndexError:
            return np.zeros(self.max_batch, dtype=REQUEST_SLOT_DTYPE)

    def _queued_depth(self) -> int:
        """Requests still waiting across this sidecar's rings (the
        pingoo_sched_queue_depth gauge; one telemetry snapshot per ring
        per LAUNCH, not per request)."""
        total = 0
        for r in self.rings:
            try:
                total += int(r.telemetry()["depth"])
            except Exception:
                pass
        return total

    def _dispatch(self, parts, n: int, oldest_enq_ms: Optional[int],
                  slot_buf=None):
        """Encode + launch one merged batch (jax dispatch is async);
        returns the in-flight tuple `_complete` consumes."""
        from .engine.batch import RequestBatch, bucket_arrays, pad_batch

        pipe_slot = self._pipe.enter(self.pipeline_mode)
        self.chaos.stage("encode")
        t0 = time.monotonic()
        batch = raw = None
        if slot_buf is not None:
            # Zero-copy plane (PINGOO_PIPELINE=on): the dequeue FFI
            # already landed every part contiguously in `slot_buf`, so
            # the merged batch is one view — no concatenate — and the
            # staging encoder fills its reused bucketed+padded
            # matrices straight from the slot fields (no
            # slots_to_arrays intermediates, no bucket/pad copies).
            # `raw` is the unpadded row view of the same staging
            # arrays: bucketed columns are a superset of every row's
            # length, and every consumer (host_rule_lanes,
            # batch_to_contexts) reads data[:len].
            slots = slot_buf[:n]
            if self.ladder.try_rung("pipeline"):
                try:
                    batch = self._staging.encode_slots(
                        slots, pad_to=self.max_batch)
                    raw = RequestBatch(
                        size=n,
                        arrays={k: v[:n]
                                for k, v in batch.arrays.items()},
                        overflow=(batch.overflow[:n]
                                  if batch.overflow is not None
                                  else None))
                    self.ladder.note_success("pipeline")
                except Exception as exc:
                    # Ladder pipeline rung: a broken staging encoder
                    # demotes THIS plane to the legacy encode chain
                    # below (bit-identical, tests/test_pipeline.py)
                    # until a backoff probe re-promotes it.
                    self.ladder.note_failure("pipeline", exc)
                    batch = raw = None
        else:
            slots = parts[0][1] if len(parts) == 1 else np.concatenate(
                [s for _, s in parts])
        if batch is None:
            # Legacy encode chain (PINGOO_PIPELINE=off, or the ladder's
            # pipeline rung demoted): pad the batch axis to one fixed
            # shape (a partial batch would otherwise be a new XLA
            # program — compile stall on the serving path) and bucket
            # field lengths to powers of two so the NFA scan walks the
            # batch's longest value, not the 2048-byte slot capacity
            # (at most log2(cap) shapes per field).
            raw = RequestBatch(size=n, arrays=slots_to_arrays(slots))
            batch = pad_batch(
                RequestBatch(size=n, arrays=bucket_arrays(raw.arrays)),
                self.max_batch)
        # Mesh placement (ISSUE 6): the device programs read the
        # dp-sharded view; `raw` stays host-resident for host-rule
        # interpretation and spill re-evaluation.
        arrays = batch.arrays
        if self.mesh.active:
            arrays = self.mesh.shard_batch(arrays)
        t1 = time.monotonic()
        self.chaos.stage("dispatch")
        pf_hits = pf_aux = None
        rule_hits = None
        dev = None
        tpf = t1
        self._dfa_rung_tick()
        # Ladder device rung: while demoted, skip the dispatch entirely
        # (the interpreter serves in `_complete`) except for backoff
        # probes; a dispatch-time exception demotes — it no longer
        # kills the drain thread.
        if self.ladder.try_rung("device"):
            try:
                self.chaos.maybe_xla_error(self.batches)
                # True padded lane batch for the compile ledger's
                # surface check (packed blobs hide the batch axis).
                from .obs.perf import batch_leading_dim, \
                    set_dispatch_context
                set_dispatch_context(batch=batch_leading_dim(arrays))
                # Busy window: the jitted calls return async once
                # compiled, but the FIRST call per pow2 bucket blocks
                # in XLA for seconds — the watchdog heartbeats through
                # it so the data plane doesn't flip degraded.
                with self._hb_busy():
                    # Compact staging (ISSUE 15): ONE device_put of the
                    # packed buffer replaces the per-field transfers;
                    # the packed twins slice the fields back out on
                    # device. Mesh stays on the per-field path (the
                    # shard plan addresses named arrays).
                    use_packed = (
                        batch.packed is not None
                        and self._packed_lane_fn is not None
                        and not self.mesh.active)
                    if use_packed:
                        import jax

                        dev_packed = jax.device_put(batch.packed)
                        if self._packed_pf_fn is not None:
                            pf_hits, pf_aux = self._packed_pf_fn(
                                self._tables, dev_packed,
                                batch.layout)  # async
                        tpf = time.monotonic()
                        if self._provenance_on:
                            dev, rule_hits = self._packed_lane_fn(
                                self._tables, dev_packed, batch.layout,
                                pf_hits, np.int32(n))  # async
                        else:
                            dev = self._packed_lane_fn(
                                self._tables, dev_packed, batch.layout,
                                pf_hits)  # async
                    else:
                        if self._pf_fn is not None:
                            pf_hits, pf_aux = self._pf_fn(
                                self._tables, arrays)  # async
                        tpf = time.monotonic()
                        if self._provenance_on:
                            # Attribution aux lane rides the SAME
                            # dispatch; the traced n masks
                            # batch-padding rows on device.
                            dev, rule_hits = self._lane_fn(
                                self._tables, arrays, pf_hits,
                                np.int32(n))  # async
                        else:
                            dev = self._lane_fn(self._tables, arrays,
                                                pf_hits)  # async
            except Exception as exc:
                self._note_device_failure(exc)
                pf_hits = pf_aux = rule_hits = dev = None
                tpf = time.monotonic()
        t2 = time.monotonic()
        self._stage["encode"].observe((t1 - t0) * 1e3)
        self._stage["prefilter"].observe((tpf - t1) * 1e3)
        self._stage["device_dispatch"].observe((t2 - tpf) * 1e3)
        # Pipeline telemetry + per-stage cost feed (ISSUE 9): the
        # executor stages are encode (staging fill + mesh placement)
        # and dispatch (prefilter + lane-fn issue); feeding them to the
        # stage-aware cost model keeps should_launch's slack estimate
        # honest once stages of different batches overlap (the single
        # launch->result wall would double-count overlapped host work).
        self._pipe.note_stage(pipe_slot, "encode", t0, t1)
        self._pipe.note_stage(pipe_slot, "dispatch", t1, t2)
        self.sched.observe_stage_cost("encode", self.max_batch,
                                      (t1 - t0) * 1e3)
        self.sched.observe_stage_cost("dispatch", self.max_batch,
                                      (t2 - t1) * 1e3)
        # Staged-bytes accounting (ISSUE 15): the transfer volume
        # behind this dispatch window, on the metrics surface AND into
        # the scheduler's bytes-keyed dispatch EWMA.
        if batch.staged_bytes:
            self._staged_bytes_counter[
                "compact" if batch.packed is not None
                else "full"].inc(batch.staged_bytes)
            self.sched.observe_dispatch_bytes(batch.staged_bytes,
                                              (t2 - t1) * 1e3)
        # Scheduler accounting at launch: occupancy + queue depth, the
        # sidecar's `sched` stage (oldest enqueue -> launch hold on the
        # ring clock), and the fail-open mask for rows whose deadline
        # is unmeetable even by this immediate launch.
        now_ms = int(self.ring.lib.pingoo_ring_now_ms())
        self.sched.note_launch(n, self._queued_depth())
        if oldest_enq_ms is not None:
            self._stage["sched"].observe(
                max(0.0, float(now_ms - oldest_enq_ms)))
        skip_masks = None
        if self.sched.config.failopen == "allow":
            # Per-stage budget slice (ISSUE 9): encode+dispatch are
            # already spent at this point, so the unmeetable test
            # charges each row only the REMAINING work — the compute
            # stage's estimate — instead of the whole-batch wall (which
            # would fail open rows that could still make the deadline).
            skip_masks = self._failopen_late_rows(
                parts, now_ms,
                est_ms=self.sched.cost.estimate_stage(
                    "compute", self.max_batch))
        # `meta` rides the in-flight tuple into _complete (ISSUE 17):
        # the dispatch-side time points feed the cross-plane timeline's
        # stage spans, and the staging mode lands in every flight row.
        meta = {"t0": t0, "t1": t1, "tpf": tpf, "t2": t2,
                "staging_mode": ("compact" if batch.packed is not None
                                 else "full")}
        return (parts, slots, raw, dev, rule_hits, pf_aux, n, skip_masks,
                time.monotonic(), slot_buf, pipe_slot, meta)

    def _failopen_late_rows(self, parts, now_ms: int,
                            est_ms: Optional[float] = None) -> list:
        """PINGOO_SCHED_FAILOPEN=allow: rows whose deadline cannot be
        met even by the launch happening right now get an immediate
        allow verdict (the reference's fail-open posture — attacks pass
        rather than stall the data plane); their device verdicts are
        computed but never posted. Returns one keep-mask per part.
        `est_ms` is the cost still ahead of the rows — the caller's
        stage-budget slice; defaults to the full-batch estimate."""
        if est_ms is None:
            est_ms = self.sched.cost.estimate(self.max_batch)
        deadline_ms = self.sched.config.deadline_ms
        masks = []
        for ring, part in parts:
            enq = part["enq_ms"].astype(np.int64)
            late = (now_ms + est_ms) > (enq + deadline_ms)
            if late.any():
                tickets = np.ascontiguousarray(part["ticket"][late],
                                               dtype=np.uint64)
                acts0 = np.zeros(len(tickets), dtype=np.uint8)
                done = 0
                while done < len(tickets):
                    done += ring.post_verdicts(tickets[done:],
                                               acts0[done:])
                    if done < len(tickets):
                        if self._stop:
                            break
                        time.sleep(self.idle_sleep_s)
                ring.record_waits(part["enq_ms"][late])
                self.sched.note_failopen(int(late.sum()))
            masks.append(~late)
        return masks

    # -- device-resident megastep (ISSUE 12, docs/EXECUTOR.md) ----------------

    def _mega_begin(self, oldest_enq_ms: Optional[int] = None) -> bool:
        """Open a new megastep window? Called at a launch signal with
        no window staged. `force` always megasteps (a K=1 window is
        legal — masked, not re-shaped); `auto` engages only when more
        traffic is already queued behind this batch (a lone batch would
        pay window-fill latency for zero amortization); a demoted
        megastep rung opens only backoff-probe windows (per-batch
        dispatch serves meanwhile). The K target is sized down the pow2
        ladder against the oldest row's remaining deadline slack
        (sched.size_megastep_k) so a window never out-waits its own
        budget. The serving mesh shards per-batch programs only —
        mesh-active planes keep the per-batch path."""
        if self._mega_fn is None or self.mesh.active:
            return False
        if self._mega_mode == "auto" and self._queued_depth() <= 0:
            return False
        if not self.ladder.try_rung("megastep"):
            return False
        self._mega_target = self._mega_k
        if self._mega_mode != "force":
            # Deadline-sized K (auto only — force is the operator
            # pinning the cap for an oracle/bench arm).
            now_ms = int(self.ring.lib.pingoo_ring_now_ms())
            oldest = now_ms if oldest_enq_ms is None else oldest_enq_ms
            self._mega_target = min(
                self._mega_k, self.sched.size_megastep_k(
                    self._mega_rungs, self.max_batch,
                    oldest / 1e3, now_ms / 1e3))
        self._mega_buf_id = self._mega_queue.checkout()
        return True

    def _mega_due(self) -> bool:
        """Ship the open window now? Full to its K target, or the
        oldest staged slice's remaining deadline slack no longer covers
        the window's own cost estimate (waiting for more slices would
        trade amortization for misses)."""
        staged = self._mega_staged
        if len(staged) >= self._mega_target:
            return True
        if self._mega_mode == "force":
            # force pins the cap: only window-full (above) or an idle
            # drain pass in the run loop ships a short window.
            return False
        oldest = min((s.oldest_enq_ms for s in staged
                      if s.oldest_enq_ms is not None), default=None)
        if oldest is None:
            return True
        now_ms = int(self.ring.lib.pingoo_ring_now_ms())
        slack_ms = self.sched.config.deadline_ms - (now_ms - oldest)
        return slack_ms <= self.sched.cost.estimate_megastep(
            len(staged), self.max_batch)

    def _stage_mega_slice(self, parts, n: int,
                          oldest_enq_ms: Optional[int],
                          slot_buf=None) -> None:
        """Encode one admitted batch into the open window's next
        DeviceInputQueue slice row. Mirrors `_dispatch`'s encode stage
        exactly — same staging encoder, same ladder rung, same legacy
        fallback — then copies into the queue's own stacks, so the
        staging views are free to rotate immediately; the slice's
        resolve-path raw views read the queue's copy (stable until this
        buffer set is checked out again, nbuf-1 windows later)."""
        from .engine.batch import RequestBatch, bucket_arrays, pad_batch

        pipe_slot = self._pipe.enter(self.pipeline_mode)
        self.chaos.stage("encode")
        t0 = time.monotonic()
        batch = None
        if slot_buf is not None:
            slots = slot_buf[:n]
            if self.ladder.try_rung("pipeline"):
                try:
                    batch = self._staging.encode_slots(
                        slots, pad_to=self.max_batch)
                    self.ladder.note_success("pipeline")
                except Exception as exc:
                    self.ladder.note_failure("pipeline", exc)
                    batch = None
        else:
            slots = parts[0][1] if len(parts) == 1 else np.concatenate(
                [s for _, s in parts])
        if batch is None:
            batch = pad_batch(RequestBatch(
                size=n, arrays=bucket_arrays(slots_to_arrays(slots))),
                self.max_batch)
        j = len(self._mega_staged)
        self._mega_queue.fill_slice(self._mega_buf_id, j, batch.arrays,
                                    n, self.ruleset_epoch)
        # Compact staging (ISSUE 15): the capped views ride the
        # existing fill_slice width logic; carry the encoder's depth-
        # overflow flags so `_complete` re-serves those rows from the
        # full slot view, same as the per-batch path.
        raw = RequestBatch(size=n, arrays=self._mega_queue.slice_view(
            self._mega_buf_id, j, n),
            overflow=(batch.overflow[:n]
                      if batch.overflow is not None else None))
        t1 = time.monotonic()
        self._stage["encode"].observe((t1 - t0) * 1e3)
        self._pipe.note_stage(pipe_slot, "encode", t0, t1)
        self.sched.observe_stage_cost("encode", self.max_batch,
                                      (t1 - t0) * 1e3)
        # Staging IS this batch's admission: scheduler launch
        # accounting and the fail-open sweep happen here, charging late
        # rows the REMAINING cost — the whole window's estimate, since
        # their verdicts land at its single sync.
        now_ms = int(self.ring.lib.pingoo_ring_now_ms())
        self.sched.note_launch(n, self._queued_depth())
        if oldest_enq_ms is not None:
            self._stage["sched"].observe(
                max(0.0, float(now_ms - oldest_enq_ms)))
        rec = _MegaSlice()
        rec.parts = parts
        rec.slots = slots
        rec.raw = raw
        rec.n = n
        rec.skip_masks = None
        if self.sched.config.failopen == "allow":
            rec.skip_masks = self._failopen_late_rows(
                parts, now_ms,
                est_ms=self.sched.cost.estimate_megastep(
                    self._mega_target, self.max_batch))
        rec.slot_buf = slot_buf
        rec.pipe_slot = pipe_slot
        rec.epoch = self.ruleset_epoch
        rec.oldest_enq_ms = oldest_enq_ms
        self._mega_staged.append(rec)

    def _launch_megastep(self) -> _MegaWindow:
        """Ship the staged window's host stacks (one async device_put)
        and dispatch ONE jitted megastep over its K slices (async);
        returns the in-flight window record. A launch failure demotes
        the megastep rung only — `_complete_megastep` serves the
        window's slices from the interpreter, and per-batch dispatch
        (which probes device health itself) takes over."""
        staged, self._mega_staged = self._mega_staged, []
        k = len(staged)
        # Quantize the shipped leading dim to the NEXT pow2 rung >= k:
        # each distinct K is its own XLA compile of the scan, so
        # arbitrary short idle-drain windows would pay a fresh
        # multi-second compile each. Padded slices ride along masked by
        # their zeroed n_valid words — but padding still costs their
        # scan iterations, so a short window ships at its own rung
        # rather than the full cap (in force mode too: the pinned K
        # caps the rung set, it does not inflate quiet windows).
        k_ship = next((r for r in self._mega_rungs if r >= k),
                      self._mega_k)
        k_ship = max(k, min(k_ship, self._mega_k))
        self.chaos.stage("dispatch")
        self._dfa_rung_tick()
        t0 = time.monotonic()
        dev_out = None
        try:
            self.chaos.maybe_xla_error(self.batches)
            # Busy window: the first call per (K, widths) signature
            # blocks in XLA for seconds; the watchdog heartbeats
            # through it.
            with self._hb_busy():
                stacked, nv, ep = self._mega_queue.device_stack(
                    self._mega_buf_id, k, pad_to=k_ship)
                from .obs.perf import set_dispatch_context
                set_dispatch_context(
                    batch=next((int(a.shape[1]) for a in
                                stacked.values()
                                if getattr(a, "ndim", 0) == 3), None),
                    k=k_ship)
                dev_out = self._mega_fn.fn(self._tables, stacked,
                                           nv, ep)  # async
        except Exception as exc:
            self.ladder.note_failure("megastep", exc)
            dev_out = None
        t1 = time.monotonic()
        self._stage["device_dispatch"].observe((t1 - t0) * 1e3)
        self._pipe.note_stage(staged[0].pipe_slot, "dispatch", t0, t1)
        self.sched.observe_stage_cost("dispatch", self.max_batch,
                                      (t1 - t0) * 1e3)
        self._pipe.note_megastep(k, self._mega_mode)
        self.mega_windows += 1
        win = _MegaWindow()
        win.slices = staged
        win.k = k
        win.k_ship = k_ship
        win.dev_out = dev_out
        win.t_launch = t1
        # Window id (ISSUE 17 satellite): stamps every flight row this
        # window serves, so stranded-slice reconciliation after a
        # mid-window SIGKILL is traceable per window.
        win.window_id = self.mega_windows
        return win

    def _complete_inflight(self, entry) -> None:
        """Route one in-flight deque entry: a megastep window resolves
        through its single-sync path, a per-batch tuple through
        `_complete` as before."""
        if isinstance(entry, _MegaWindow):
            self._complete_megastep(entry)
        else:
            self._complete(*entry)

    def _complete_megastep(self, win: _MegaWindow) -> None:
        """Resolve one in-flight megastep window: host-rule lanes for
        ALL K slices first (the device is still computing — same
        overlap per-batch completion gets), then ONE device sync for
        the whole window, then each slice resolves through `_complete`
        handed its precomputed host+device lanes — every post/floor/
        spill/route/provenance behavior is the shared code path, not a
        clone. A sync failure demotes the megastep rung and serves the
        window bit-identically from the interpreter."""
        from .engine.verdict import host_rule_lanes

        hosts = [host_rule_lanes(self.plan, s.raw, self.lists)
                 for s in win.slices]
        lanes = hits = aux = ep_out = None
        t0 = time.time()
        if win.dev_out is not None:
            try:
                with self._hb_busy():  # one sync per K slices
                    lanes = np.asarray(win.dev_out[0])
                    hits = np.asarray(win.dev_out[1])
                    aux = np.asarray(win.dev_out[2])
                    ep_out = np.asarray(win.dev_out[3])
                self._note_device_success()
                self.ladder.note_success("megastep")
            except Exception as exc:
                self.ladder.note_failure("megastep", exc)
                lanes = None
        wait_s = time.time() - t0
        self.device_wait_s += wait_s
        self._stage["device_compute"].observe(wait_s * 1e3)
        t_sync = time.monotonic()
        self._pipe.note_stage(win.slices[0].pipe_slot, "compute",
                              win.t_launch, t_sync)
        window_ms = (t_sync - win.t_launch) * 1e3
        # Cost feed: the window wall teaches the megastep EWMA (K
        # sizing) and, split per slice, the compute-stage EWMA
        # (admission slack) — never K near-zero syncs.
        self.sched.observe_stage_cost("compute", self.max_batch,
                                      window_ms / max(1, win.k))
        # EWMA keyed by the SHIPPED K (the compiled shape that set the
        # window's cost), not the filled count.
        self.sched.observe_megastep_cost(win.k_ship, self.max_batch,
                                         window_ms)
        for j, s in enumerate(win.slices):
            if ep_out is not None and int(ep_out[j]) != s.epoch:
                # The device program echoes each slice's staged epoch
                # untouched; a mismatch would mean a slice crossed a
                # swap boundary (tests assert this stays 0).
                self.mega_echo_mismatch += 1
            self._complete(
                s.parts, s.slots, s.raw, None,
                (hits[j] if lanes is not None and self._provenance_on
                 else None),
                (aux[j] if lanes is not None and self._pf_fn is not None
                 else None),
                s.n, skip_masks=s.skip_masks, t_disp=None,
                slot_buf=s.slot_buf, pipe_slot=s.pipe_slot,
                meta={"megastep_window": win.window_id,
                      "megastep_k": win.k_ship,
                      "staging_mode": "full"},
                host=hosts[j],
                dev_lanes=(lanes[j][:, :s.n] if lanes is not None
                           else None))

    def _enrich_slots(self, slots: np.ndarray) -> None:
        """Fill asn/country in place for rows the producer enqueued with
        the unknown markers (asn 0 + country "XX"). GeoipDB caches both
        hits and misses (host/geoip.py), so steady-state cost per row is
        one dict probe; everything downstream (device batch encoding AND
        overflow-spill re-interpretation) reads the enriched slots."""
        import ipaddress

        need = (slots["asn"] == 0) & (slots["country"] == b"XX")
        if not need.any():
            return
        ips16 = slots["ip"].reshape(-1, 16)
        for i in np.nonzero(need)[0]:
            addr = ipaddress.ip_address(bytes(ips16[i]))
            mapped = getattr(addr, "ipv4_mapped", None)
            try:
                rec = self.geoip.lookup(mapped or addr)
            except Exception:
                continue  # not found / loopback: keep the XX/0 markers
            slots["asn"][i] = rec.asn
            cc = rec.country.encode("ascii", "replace")[:2]
            if len(cc) == 2:
                slots["country"][i] = cc

    def _complete(self, parts, slots, raw_batch, dev, rule_hits, pf_aux,
                  n: int, skip_masks=None, t_disp=None, slot_buf=None,
                  pipe_slot=None, meta=None, host=None,
                  dev_lanes=None) -> None:
        from .engine.verdict import host_rule_lanes, merge_lanes

        # Megastep slices (ISSUE 12) arrive with host AND device lanes
        # already resolved by _complete_megastep's single window sync —
        # `pre` skips the per-batch sync and its compute-cost feeds
        # (the window attributed them once; K near-zero observations
        # would drag the compute EWMA toward zero).
        pre = dev_lanes is not None
        # Host-interpreted rules run on the UNPADDED batch while the
        # device lanes are still in flight (jax dispatch is async).
        if host is None:
            host = host_rule_lanes(self.plan, raw_batch, self.lists)
        tc0 = time.monotonic()
        t0 = time.time()
        if not pre and dev is not None:
            try:
                with self._hb_busy():  # device sync can block for ms-s
                    dev_lanes = np.asarray(dev)[:, :n]  # drop padding
                self._note_device_success()
            except Exception as exc:
                # jax dispatch is async — a device/runtime error only
                # surfaces at this sync. Demote (ladder device rung)
                # and serve the batch from the interpreter below
                # instead of killing the drain thread.
                self._note_device_failure(exc)
        wait_s = time.time() - t0
        tc1 = time.monotonic()
        self.device_wait_s += wait_s
        if not pre:
            self._stage["device_compute"].observe(wait_s * 1e3)
        # The pipeline's compute window runs dispatch-end -> results
        # ready, NOT just the residual block at the sync (which shrinks
        # to ~0 precisely when overlap works): it is the window the
        # executor hides other batches' host stages behind (the
        # overlap-ratio denominator, obs/pipeline.py) and the cost a
        # row's deadline must still cover after launch (the compute
        # budget slice _dispatch charges in _failopen_late_rows).
        tcs = t_disp if t_disp is not None else tc0
        if not pre:
            if pipe_slot is not None:
                self._pipe.note_stage(pipe_slot, "compute", tcs, tc1)
            self.sched.observe_stage_cost("compute", self.max_batch,
                                          (tc1 - tcs) * 1e3)
        if t_disp is not None:
            # EWMA cost-model feedback: launch -> device result wall
            # for the padded size. With stage observations present the
            # cost model estimates from per-stage EWMAs (this wall
            # double-counts host work overlapped with OTHER batches);
            # the legacy wall still feeds the baseline fallback.
            self.sched.observe_cost(self.max_batch,
                                    (time.monotonic() - t_disp) * 1e3)
        if pf_aux is not None:
            # Resolved long before the lane sync above; aux int32 lanes.
            vals = np.asarray(pf_aux)
            denom = self.max_batch * self._pf_gated_banks
            if denom:
                self._pf_rate_gauge.set(int(vals[0]) / denom)
            self._pf_skip_counter.inc(int(vals[1]))
            if self._pf_attr is not None:
                self._pf_attr.observe(vals, self.max_batch)
        from .engine.verdict import dfa_dispatch_counts

        dfa_mode, dfa_banks, dfa_rechecks = dfa_dispatch_counts(self.plan)
        if dfa_banks:
            ctr = self._dfa_banks_counter.get(dfa_mode)
            if ctr is not None:
                ctr.inc(dfa_banks)
            if dfa_rechecks:
                self._dfa_recheck_counter.inc(dfa_rechecks)
        t_resolve = time.monotonic()
        self.chaos.stage("resolve")
        self.batches += 1
        route = None
        if dev_lanes is None:
            # Ladder device-rung fallback: the host interpreter — the
            # parity oracle every fast path is tested against — serves
            # the whole batch, bit-identically, at host speed.
            with self._hb_busy():  # host interpret blocks the loop
                unverified, verified_block, route = self._interpret_batch(
                    parts, raw_batch)
        else:
            unverified, verified_block = merge_lanes(dev_lanes, host)
        # Rows the producer flagged as truncated (a field exceeded its
        # 2048-byte slot cap) were matched on the slot view — the widest
        # bytes this plane carries. Count them so the residual truncation
        # window (>2048B fields) is observable; the Python plane
        # re-evaluates such rows on fully untruncated strings
        # (engine/service.py).
        self.truncated_rows += int(
            ((slots["flags"] & SLOT_FLAG_TRUNCATED) != 0).sum())
        # Per-row route: each ring's rows read THEIR listener group's
        # route lane (make_lane_fn stacks one lane per distinct service
        # order at rows 3..3+G; the reference binds a service list per
        # listener, config.rs:241-253). Rows from rings with no service
        # group keep route 0 — their consumer never reads bits 3-7.
        if self._groups and dev_lanes is not None:
            route = np.zeros(n, dtype=np.int64)
            group_rows: list[list] = [[] for _ in self._groups]
            off = 0
            for ring, part in parts:
                gi = self._ring_group_of.get(id(ring))
                m = len(part)
                if gi is not None:
                    route[off:off + m] = np.asarray(
                        dev_lanes[3 + gi][off:off + m], dtype=np.int64)
                    group_rows[gi].append(np.arange(off, off + m))
                off += m
            contexts = None
            for gi, chunks in enumerate(group_rows):
                if not self._host_routes[gi] or not chunks:
                    continue
                rows = np.concatenate(chunks)
                from .engine.batch import batch_to_contexts
                from .expr import execute_as_bool

                for order, prog in self._host_routes[gi]:
                    better = rows[route[rows] > order]
                    if not len(better):
                        continue
                    if contexts is None:
                        contexts = batch_to_contexts(raw_batch, self.lists)
                    for i in better:
                        try:
                            hit = prog is None or execute_as_bool(
                                prog, contexts[i])
                        except Exception:
                            hit = False  # route errors fail to no-match
                        if hit:
                            route[i] = order
        # Rows whose url/path overflowed the slot caps carry their FULL
        # strings in the owning ring's spill area: re-evaluate every
        # lane for those rows through the host interpreter over the
        # untruncated bytes — exact parity with the reference, which
        # matches full strings (http_listener.rs:140-141). Rows flagged
        # truncated WITHOUT a spill slot (pool exhausted / > 64 KiB)
        # keep the slot-view verdict and remain visible in
        # truncated_rows above.
        off = 0
        for ring, part in parts:
            gi = self._ring_group_of.get(id(ring))
            svcs = self._groups[gi] if gi is not None else None
            spilled = np.nonzero(part["spill_idx"] != SPILL_NONE)[0]
            for j in spilled:
                idx = int(part["spill_idx"][j])
                full = ring.spill_read(idx)
                if full is not None:
                    unv, vblk, rt = self._interpret_overflow_row(
                        part[j], full[0], full[1], svcs)
                    unverified[off + j] = unv
                    verified_block[off + j] = vblk
                    if route is not None and gi is not None:
                        route[off + j] = rt
                    self.spilled_rows += 1
                ring.spill_release(idx)
            off += len(part)
        # Depth-capped rows (ISSUE 15, PINGOO_STAGING=compact with a
        # PINGOO_STAGING_DEPTH clamp below a field's required depth):
        # the device matched a plan-capped prefix narrower than the
        # slot bytes, so re-serve every lane for those rows from the
        # FULL slot view through the host interpreter — the same
        # exactness contract as the spill loop above. Spilled rows
        # already re-evaluated over their untruncated strings; with no
        # clamp the encoder's thresholds equal the slot caps and this
        # mask is empty by construction.
        over = getattr(raw_batch, "overflow", None)
        if over is not None and over[:n].any():
            off = 0
            for ring, part in parts:
                gi = self._ring_group_of.get(id(ring))
                svcs = self._groups[gi] if gi is not None else None
                rows = np.nonzero(over[off:off + len(part)]
                                  & (part["spill_idx"] == SPILL_NONE))[0]
                for j in rows:
                    s = part[j]
                    unv, vblk, rt = self._interpret_overflow_row(
                        s, bytes(s["url"][:int(s["url_len"])]),
                        bytes(s["path"][:int(s["path_len"])]), svcs)
                    unverified[off + j] = unv
                    verified_block[off + j] = vblk
                    if route is not None and gi is not None:
                        route[off + j] = rt
                    self.depth_overflow_rows += 1
                off += len(part)
        # Verdict byte carries BOTH client-state lanes (the reference
        # action loop diverges for captcha-verified clients,
        # http_listener.rs:251-264): bits 0-1 = unverified action
        # (0 none / 1 block / 2 captcha), bit 2 = verified-block, and —
        # when this sidecar routes for a native listener — bits 3-7 =
        # the first matching service's order (31 = no service matched,
        # reference service-selection loop http_listener.rs:266-270).
        actions = unverified | (verified_block.astype(np.int32) << 2)
        if route is not None:
            actions = actions | (np.minimum(route, 31).astype(np.int32) << 3)
        acts = actions[:n].astype(np.uint8)
        off = 0
        for pi, (ring, part) in enumerate(parts):  # scatter per ring
            m = len(part)
            # Rows the scheduler already failed open at launch
            # (skip_masks, PINGOO_SCHED_FAILOPEN=allow) were posted
            # then; posting again would hand their consumer a second
            # verdict for the same ticket.
            if skip_masks is not None and not skip_masks[pi].all():
                keep = skip_masks[pi]
                tickets = np.ascontiguousarray(part["ticket"][keep],
                                               dtype=np.uint64)
                pacts = np.ascontiguousarray(acts[off:off + m][keep])
                waits = part["enq_ms"][keep]
            else:
                tickets = np.ascontiguousarray(part["ticket"],
                                               dtype=np.uint64)
                pacts = acts[off:off + m]
                waits = part["enq_ms"]
            k = len(tickets)
            done = 0
            while done < k:  # one FFI hop per batch, resume on a full ring
                if self.chaos.verdict_full():  # injected full-ring stall
                    time.sleep(self.idle_sleep_s)
                    continue
                done += ring.post_verdicts(tickets[done:], pacts[done:])
                if done < k:
                    if self._stop:  # a dead consumer must not wedge stop()
                        if pipe_slot is not None:
                            self._pipe.exit()
                        return
                    time.sleep(self.idle_sleep_s)
            # Telemetry: enqueue -> verdict-post wall time for this
            # ring's rows lands in the shm wait histogram (one FFI hop).
            ring.record_waits(waits)
            # Posted-floor advance (ring v5, docs/RESILIENCE.md): every
            # ticket of this part now has a verdict (skip-mask rows
            # were posted at launch), and parts complete in FIFO order,
            # so posted tickets form a prefix — a reattaching sidecar's
            # orphan scan starts above this mark.
            if m:
                ring.set_posted_floor(int(part["ticket"].max()) + 1)
            off += m
        # Deadline accounting on the ring clock: rows posted after
        # their PINGOO_DEADLINE_MS budget count as misses (one
        # vectorized compare per batch).
        post_ms = int(self.ring.lib.pingoo_ring_now_ms())
        self.sched.note_misses(int(
            ((post_ms - slots["enq_ms"].astype(np.int64))
             > self.sched.config.deadline_ms).sum()))
        t_res_end = time.monotonic()
        self._stage["resolve"].observe((t_res_end - t_resolve) * 1e3)
        if pipe_slot is not None:
            self._pipe.note_stage(pipe_slot, "resolve", t_resolve,
                                  t_res_end)
        t_prov = time.monotonic()
        if self._attribution is not None and dev_lanes is not None:
            # Interpreter-served batches (device rung demoted) skip
            # attribution/parity: the aux lane never ran, and auditing
            # the oracle against itself proves nothing.
            self._observe_provenance(slots, rule_hits, dev_lanes, host,
                                     raw_batch, unverified,
                                     verified_block, wait_s, n,
                                     pipe_slot=pipe_slot, meta=meta)
        self._stage["provenance"].observe(
            (time.monotonic() - t_prov) * 1e3)
        # Cross-plane timeline (ISSUE 17): per-batch cost while
        # unsampled is the one add+compare inside sample(). The rows'
        # enq_ms stamps are the NATIVE producer's ring clock — same
        # CLOCK_MONOTONIC timebase as the sidecar stamps, which is what
        # joins the ring-wait span across planes.
        if self._timeline.sample():
            m = meta or {}
            tl_args = {"staging_mode": m.get("staging_mode", "full")}
            if "megastep_window" in m:
                tl_args["megastep_window"] = m["megastep_window"]
                tl_args["megastep_k"] = m.get("megastep_k")
            self._timeline.batch_sidecar(
                t0=m.get("t0", 0.0), t1=m.get("t1", 0.0),
                tpf=m.get("tpf", 0.0), t2=m.get("t2", 0.0),
                t_sync=tc1, t_resolve=t_resolve, t_end=t_res_end,
                rows=[(f"t-{int(slots['ticket'][i])}",
                       int(slots["enq_ms"][i]))
                      for i in range(
                          min(n, self._timeline.rows_per_batch))],
                args=tl_args)
        self.processed += n
        # The batch is fully resolved: its accumulation buffer returns
        # to the pool and its pipeline slot retires.
        if slot_buf is not None:
            self._slot_pool.append(slot_buf)
        if pipe_slot is not None:
            self._pipe.exit()
        self.chaos.on_batch_done(self.batches)

    def _observe_provenance(self, slots, rule_hits, dev_lanes, host,
                            raw_batch, unverified, verified_block,
                            device_wait_s, n: int,
                            pipe_slot=None, meta=None) -> None:
        """Sidecar-plane provenance (ISSUE 5): fold the on-device
        attribution aux lane, flight-record the batch, and hand the
        FINAL served lanes (spill rewrites included) to the parity
        sampler. Registered hot in the analyze-lint registries — the
        aux lane resolved with the batch's lane sync, so nothing here
        may wait on the device. Lane-plane attribution covers the
        DEVICE-resident rules (the match matrix never leaves the chip);
        host-fallback rules are attributed on the Python plane, where
        the full matrix exists."""
        import zlib as _zlib

        from .engine.verdict import LANE_NONE

        if rule_hits is not None and len(self._dev_cols):
            self._attribution.fold_batch(rule_hits,
                                         indices=self._dev_cols)
        trace_ids = [f"t-{int(t)}" for t in slots["ticket"]]
        recorder = self.flight_recorder
        # Merged first-acting rule index per row (device lanes already
        # host-resident; host lanes are numpy) for the record's
        # matched-rule attribution — the lanes carry no full bitmap.
        act_idx = np.minimum(dev_lanes[0], host[0])
        now_ms = int(self.ring.lib.pingoo_ring_now_ms())
        enq_ms = slots["enq_ms"]
        compute_ms = round(device_wait_s * 1e3, 3)
        start = max(0, n - recorder.capacity)
        for i in range(start, n):
            crc = _zlib.crc32(slots["method"][i].tobytes())
            for f in ("host", "path", "url", "user_agent", "ip"):
                crc = _zlib.crc32(slots[f][i].tobytes(), crc)
            first = int(act_idx[i])
            stages = {
                "enqueue_to_post_ms": max(
                    0, now_ms - int(enq_ms[i])),
                "device_compute_ms": compute_ms,
            }
            if pipe_slot is not None:
                # Pipeline slot id (ISSUE 9): lines this record up
                # against the pingoo_pipeline_* series — which batches
                # were in flight together when this request was served.
                stages["pipeline_slot"] = int(pipe_slot)
            if meta is not None:
                # Window id + K rung + staging mode (ISSUE 17
                # satellite): flight rows predate the megastep —
                # without these, stranded-slice reconciliation after a
                # mid-window SIGKILL cannot tell which window a row
                # rode.
                if "megastep_window" in meta:
                    stages["megastep_window"] = meta["megastep_window"]
                    stages["megastep_k"] = meta.get("megastep_k")
                stages["staging_mode"] = meta.get("staging_mode",
                                                  "full")
            recorder.record(
                trace_id=trace_ids[i],
                digest=f"{crc & 0xFFFFFFFF:08x}",
                stages=stages,
                matched_rules=(first,) if first < LANE_NONE else (),
                action=int(unverified[i]),
                ticket=int(slots["ticket"][i]))
        if self.parity is not None:
            # Truncated/spilled rows were served from a different string
            # view than the slot arrays — excluded from the audit.
            skip = ((slots["flags"] & SLOT_FLAG_TRUNCATED) != 0) \
                | (slots["spill_idx"] != SPILL_NONE)
            over = getattr(raw_batch, "overflow", None)
            if over is not None:
                # Depth-capped rows (ISSUE 15) were re-served from the
                # full slot view, not the capped staging arrays the
                # audit would rebuild contexts from — excluded like
                # spilled rows.
                skip = skip | np.asarray(over[:n], dtype=bool)
            raw_for_audit = raw_batch
            if self._zero_copy and self.parity.sample > 0.0:
                # The auditor's contexts_builder runs LATER on its
                # worker thread, but zero-copy `raw_batch` arrays are
                # views into the rotating staging buffers — recycled a
                # few batches from now. Snapshot them while they are
                # still this batch's bytes (audit-mode-only copy; with
                # sampling off the closure is never invoked).
                from .engine.batch import RequestBatch

                raw_for_audit = RequestBatch(
                    size=raw_batch.size,
                    arrays={k: np.array(v, copy=True)
                            for k, v in raw_batch.arrays.items()})

            def contexts_builder(raw=raw_for_audit, lists=self.lists):
                from .engine.batch import batch_to_contexts

                contexts = batch_to_contexts(raw, lists)
                paths = [c.variables["http_request"]["path"]
                         for c in contexts]
                return contexts, paths

            self.parity.submit_lanes(
                contexts_builder, unverified[:n].copy(),
                verified_block[:n].copy(), skip_mask=skip,
                trace_ids=trace_ids)

    # -- degradation ladder (ISSUE 10, docs/RESILIENCE.md) --------------------

    def _rebuild_lane_fn(self, dfa_off: bool) -> None:
        """Re-trace the lane fn with the lowered DFAs in or out. The
        plan-level default is what `_resolve_dfa_mode` falls back to
        when PINGOO_DFA is unset, so the demotion is per-plan, not
        process-global. The next dispatch pays one re-jit (a bounded
        stall during an already-degraded event)."""
        from .engine.verdict import donate_batch_buffers, make_lane_fn
        from .obs.perf import (instrument_jit, plan_fingerprint,
                               staging_widths)

        self.plan.dfa_default_mode = "off" if dfa_off else self._dfa_mode0
        fp = plan_fingerprint(self.plan)
        widths = staging_widths(self.plan)
        self._lane_fn = instrument_jit(make_lane_fn(
            self.plan, service_groups=self._groups or None,
            with_rule_hits=self._provenance_on,
            donate=donate_batch_buffers()), "lanes", plane="sidecar",
            fingerprint=fp, widths=widths)
        if self._packed_lane_fn is not None:
            # The packed twin embeds the same DFA dispatch decision;
            # keep it in lockstep with the per-batch program.
            from .engine.verdict import make_packed_lane_fn

            self._packed_lane_fn = instrument_jit(make_packed_lane_fn(
                self.plan, service_groups=self._groups or None,
                with_rule_hits=self._provenance_on,
                donate=donate_batch_buffers()), "lanes",
                plane="sidecar", fingerprint=fp, widths=widths)
        if self._mega_fn is not None:
            # The megastep embeds the same lane body — keep its DFA
            # dispatch in lockstep with the per-batch program.
            from .engine.verdict import make_megastep_fn
            from .obs.perf import instrument_megastep

            self._mega_fn = instrument_megastep(
                make_megastep_fn(
                    self.plan, kind="lanes",
                    service_groups=self._groups or None,
                    with_rule_hits=self._provenance_on),
                plane="sidecar", fingerprint=fp, widths=widths)

    def _dfa_rung_tick(self) -> None:
        """Demoted-dfa probe: when the backoff window opens, restore
        the lowered-DFA dispatch for one batch; `_note_device_success`
        / `_note_device_failure` then promote or re-demote."""
        if not self.ladder.healthy("dfa") and not self._dfa_probe \
                and self.ladder.try_rung("dfa"):
            self._rebuild_lane_fn(dfa_off=False)
            self._dfa_probe = True

    def _note_device_failure(self, exc: BaseException) -> None:
        """Cheapest-rung-first demotion: a device error with lowered
        DFAs active drops them back to the exact NFA scan before
        giving up on the device entirely; only a failure with the DFAs
        already out (or pinned by PINGOO_DFA) demotes the device rung
        to the host interpreter."""
        from .engine.verdict import dfa_dispatch_counts

        if self._dfa_probe:
            self.ladder.note_failure("dfa", exc)
            self._rebuild_lane_fn(dfa_off=True)
            self._dfa_probe = False
        elif self.ladder.healthy("dfa") \
                and not os.environ.get("PINGOO_DFA") \
                and dfa_dispatch_counts(self.plan)[1] > 0:
            self.ladder.note_failure("dfa", exc)
            self._rebuild_lane_fn(dfa_off=True)
        else:
            self.ladder.note_failure("device", exc)

    def _note_device_success(self) -> None:
        if self._dfa_probe:
            self.ladder.note_success("dfa")
            self._dfa_probe = False
        self.ladder.note_success("device")

    def _interpret_batch(self, parts, raw_batch):
        """Device-rung fallback: serve the whole batch through the
        host interpreter — the parity oracle every fast path is tested
        against, so the verdict bytes are identical, just slower.
        Returns (unverified, verified_block, route-or-None), the same
        lanes `_complete` composes from the device path."""
        from .engine.batch import batch_to_contexts
        from .engine.verdict import LANE_NONE, action_lanes, \
            interpret_rules_row

        contexts = batch_to_contexts(raw_batch, self.lists)
        if contexts:
            rows = np.stack([interpret_rules_row(self.plan, c)
                             for c in contexts])
        else:
            rows = np.zeros((0, len(self.plan.rules)), dtype=bool)
        unv, vblk = action_lanes(self.plan, rows)
        route = None
        if self._groups:
            route = np.full(len(contexts), int(LANE_NONE),
                            dtype=np.int64)
            off = 0
            for ring, part in parts:
                gi = self._ring_group_of.get(id(ring))
                if gi is not None:
                    svcs = self._groups[gi]
                    for i in range(off, off + len(part)):
                        for order, name in enumerate(svcs):
                            ridx = self.plan.route_index.get(name)
                            if ridx is None or rows[i, ridx]:
                                route[i] = order
                                break
                off += len(part)
        return (np.asarray(unv, dtype=np.int32),
                np.asarray(vblk, dtype=bool), route)

    # -- crash-reattach reconciliation (ISSUE 10, docs/RESILIENCE.md) ---------

    def _reconcile_orphans(self) -> None:
        """Resolve tickets the PREVIOUS sidecar epoch dequeued but
        never answered. posted_floor only advances once a part's
        verdicts are all posted, and parts complete in FIFO order, so
        every ticket below the floor has a verdict and the orphan
        window is exactly [posted_floor, req_tail). Slots whose bytes
        survived the crash (wedged mid-dequeue, or consumed but not
        yet overwritten — the C reclaim's seqlock proves which) are
        RE-EVALUATED through the host interpreter; recycled slots fail
        open (allow), the same posture as every other unanswerable
        path. Each orphan resolves exactly once: this scan runs before
        the drain loop starts (no race with this epoch's posts), and a
        duplicate post for a ticket the data plane already timed out
        is dropped by its unknown-ticket check."""
        for ring in self.rings:
            lv = ring.liveness()
            floor, tail = lv["posted_floor"], lv["req_tail"]
            if tail <= floor:
                continue
            # A pre-v5 (or never-completing) epoch leaves the floor at
            # 0; slots more than one capacity old are certainly
            # recycled, so bound the scan — everything below `start`
            # long ago hit the data plane's own verdict timeout.
            start = max(floor, tail - ring.capacity)
            for ticket in range(start, tail):
                slot = ring.reclaim(ticket)
                action = 0
                kind = "failopen"
                if slot is not None:
                    try:
                        action = self._reeval_reclaimed(ring, slot)
                        kind = "reeval"
                    except Exception:
                        action = 0  # interpreter error: fail open
                self._post_one(ring, ticket, action)
                self.reconciled[kind] += 1
                self._reattach_counters[kind].inc()
                if self.flight_recorder is not None:
                    self.flight_recorder.record(
                        trace_id=f"t-{ticket}",
                        digest="reattach",
                        stages={"reattach": kind, "epoch": self.epoch},
                        matched_rules=(),
                        action=action & 3,
                        ticket=ticket)
            ring.set_posted_floor(tail)

    def _reeval_reclaimed(self, ring: Ring, slots1: np.ndarray) -> int:
        """Verdict byte for one reclaimed orphan slot via the host
        interpreter — the same lane composition `_complete` posts:
        bits 0-1 unverified, bit 2 verified-block, bits 3-7 route
        (when the slot's ring has a service group)."""
        if self.geoip is not None:
            self._enrich_slots(slots1)
        s = slots1[0]
        url = bytes(s["url"][:int(s["url_len"])])
        path = bytes(s["path"][:int(s["path_len"])])
        idx = int(s["spill_idx"])
        if idx != SPILL_NONE:
            full = ring.spill_read(idx)
            if full is not None:
                url, path = full
            ring.spill_release(idx)
        gi = self._ring_group_of.get(id(ring))
        svcs = self._groups[gi] if gi is not None else None
        unv, vblk, rt = self._interpret_overflow_row(s, url, path, svcs)
        action = unv | (int(vblk) << 2)
        if svcs is not None:
            action |= min(rt, 31) << 3
        return action

    def _post_one(self, ring: Ring, ticket: int, action: int) -> None:
        tickets = np.asarray([ticket], dtype=np.uint64)
        acts = np.asarray([action & 0xFF], dtype=np.uint8)
        # Bounded retry: a full verdict ring with a LIVE consumer
        # drains in microseconds; a dead consumer must not wedge
        # reattach forever (its tickets are long failed open anyway).
        for _ in range(10000):
            if ring.post_verdicts(tickets, acts):
                return
            if self._stop:
                return
            time.sleep(self.idle_sleep_s)

    def _interpret_overflow_row(self, slot, url: bytes, path: bytes,
                                services=None) -> tuple[int, bool, int]:
        """(unverified, verified_block, route) for one overflow row via
        the host interpreter over the UNTRUNCATED url/path (the parity
        oracle), reproducing the reference's full-string matching.
        `services` is the row's ring's service order (its listener's
        group) — routes evaluate against THAT order."""
        import ipaddress

        from .engine.batch import RequestTuple, tuple_to_context
        from .engine.verdict import LANE_NONE, action_lanes, \
            interpret_rules_row

        def field(name, ln):
            return bytes(slot[name][:slot[ln]]).decode("latin-1")

        addr = ipaddress.ip_address(bytes(slot["ip"]))
        v4 = getattr(addr, "ipv4_mapped", None)
        tup = RequestTuple(
            host=field("host", "host_len"),
            url=url.decode("latin-1"),
            path=path.decode("latin-1"),
            method=field("method", "method_len"),
            user_agent=field("user_agent", "ua_len"),
            ip=str(v4 or addr),
            remote_port=int(slot["remote_port"]),
            asn=int(slot["asn"]),
            country=bytes(slot["country"]).decode("latin-1"),
        )
        ctx = tuple_to_context(tup, self.lists)
        row = interpret_rules_row(self.plan, ctx)[None, :]
        unv, vblk = action_lanes(self.plan, row)
        rt = int(LANE_NONE)
        for order, name in enumerate(services or []):
            ridx = self.plan.route_index.get(name)
            if ridx is None or row[0, ridx]:
                rt = order
                break
        return int(unv[0]), bool(vblk[0]), rt

    def ring_telemetry(self) -> dict:
        """Aggregate shm telemetry across this sidecar's rings: sum the
        monotonic counters and the wait histogram, max the depth marks
        (the per-ring blocks stay available via Ring.telemetry())."""
        agg = {name: 0 for name in TELEMETRY_FIELDS}
        agg["wait_hist"] = [0] * 8
        for ring in self.rings:
            t = ring.telemetry()
            for name in TELEMETRY_FIELDS:
                if name in ("depth", "depth_hwm"):
                    agg[name] = max(agg[name], t[name])
                else:
                    agg[name] += t[name]
            agg["wait_hist"] = [a + b for a, b in
                                zip(agg["wait_hist"], t["wait_hist"])]
        return agg

    def _export_ring_telemetry(self) -> None:
        """Registry collector: fold the rings' telemetry blocks into the
        shared exposition (pingoo_ring_* metrics, obs/schema.py). Runs
        at scrape time; must never touch a ring after stop()."""
        if not self._collector_live:
            return
        from .obs import schema

        t = self.ring_telemetry()
        reg = self._registry
        lab = {"plane": "sidecar"}
        for name, field in (
                ("pingoo_ring_enqueued_total", "enqueued"),
                ("pingoo_ring_dequeued_total", "dequeued"),
                ("pingoo_ring_enqueue_full_total", "enqueue_full"),
                ("pingoo_ring_verdicts_posted_total", "verdicts_posted"),
                ("pingoo_ring_verdict_post_full_total",
                 "verdict_post_full")):
            reg.counter(name, schema.RING_METRICS[name],
                        labels=lab).set_total(t[field])
        reg.gauge("pingoo_ring_depth",
                  schema.RING_METRICS["pingoo_ring_depth"],
                  labels=lab).set(t["depth"])
        reg.gauge("pingoo_ring_depth_hwm",
                  schema.RING_METRICS["pingoo_ring_depth_hwm"],
                  labels=lab).set(t["depth_hwm"])
        reg.histogram(
            schema.SHARED_WAIT_HISTOGRAM,
            "verdict wait: ring enqueue -> verdict post (ms)",
            buckets=WAIT_BUCKET_BOUNDS_MS,
            labels=lab).set_bucket_counts(
                t["wait_hist"], total_sum=float(t["wait_sum_ms"]))

    def stats(self) -> dict:
        """Observability surface for the serving path (SURVEY §5):
        scraped by operators next to the native plane's
        /__pingoo/metrics endpoint."""
        return {
            "processed": self.processed,
            "batches": self.batches,
            "batch_occupancy": round(self.processed / self.batches, 2)
            if self.batches else 0.0,
            "device_wait_ms_per_batch": round(
                1e3 * self.device_wait_s / self.batches, 3)
            if self.batches else 0.0,
            "truncated_rows": self.truncated_rows,
            "spilled_rows": self.spilled_rows,
            "rings": len(self.rings),
            "ring_telemetry": self.ring_telemetry(),
            "sched": self.sched.snapshot(),
            "mesh": self.mesh.describe(),
            "pipeline": self._pipe.snapshot(),
            "megastep": {
                "mode": self._mega_mode,
                "k_cap": self._mega_k,
                "windows": self.mega_windows,
                "echo_mismatch": self.mega_echo_mismatch,
            },
            "ladder": self.ladder.snapshot(),
            "supervision": {"epoch": self.epoch,
                            "reconciled": dict(self.reconciled)},
        }

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Signal the drain loop to exit and WAIT for it (when called
        from another thread): only after this returns may the caller
        close/unmap the rings — the loop may be mid-FFI into the
        mapping, and pulling it out from under the call is a segfault,
        not an exception."""
        import threading as _threading

        # Detach the registry collector FIRST: a scrape after the
        # caller unmaps the rings would be a use-after-munmap in the
        # telemetry snapshot FFI call.
        self._collector_live = False
        self._registry.unregister_collector(self._export_ring_telemetry)
        # Durable cost ledger (ISSUE 17): persist the measured EWMAs on
        # drain so the next boot estimates from THIS run's costs.
        try:
            from .sched.scheduler import save_cost_ledger

            save_cost_ledger(self.sched.cost,
                             backend=self._backend_label,
                             fingerprint=self._plan_fp, plane="sidecar")
        except Exception:
            pass
        if self.parity is not None:
            self.parity.stop()
        if self._attribution is not None:
            self._attribution.close()
        self._stop = True
        t = self._thread
        if t is not None and t.is_alive()                 and t is not _threading.current_thread():
            t.join(timeout=join_timeout_s)
        # Join the heartbeat watchdog too (exits within one 0.1 s tick
        # of _stop): a stamp against an unmapped ring would be the same
        # use-after-munmap the drain-loop join exists to prevent.
        w = getattr(self, "_hb_watchdog", None)
        if w is not None and w.is_alive()                 and w is not _threading.current_thread():
            w.join(timeout=join_timeout_s)
