"""Live mesh execution for the verdict engine (ISSUE 6 tentpole).

The dp×tp×sp mesh (parallel/mesh.py) existed only as an offline dryrun
(__graft_entry__.dryrun_multichip — MULTICHIP_r05: 8 devices, parity
ok); this module promotes it into the SERVING path. At service startup
both engine planes build a `MeshExecutor` from `PINGOO_MESH=dpxtpxsp`
(default `1x1x1`, which is a strict no-op — single-device behavior and
compiled programs are unchanged):

  * the plan's pattern/word axes are padded to tp multiples
    (parallel/mesh.pad_tables_for_tp; padding rows are inert by
    construction, so verdicts are bit-identical),
  * device tables are placed under `table_shardings` (rule/NFA-word
    axes on tp, incl. the GSPMD halo exchange for multi-word spans
    straddling a shard cut — compiler/nfa.py pack_span),
  * each launched batch is placed under `batch_shardings` (request
    axis on dp) before the jitted prefilter/verdict/lane programs run,
    so XLA inserts the ICI collectives (scaling-book recipe: pick a
    mesh, annotate, let the compiler do the rest).

The executor is deliberately dumb about FAILURE: a spec needing more
devices than the backend has raises `MeshUnavailable` at startup, and
callers degrade to the single-device path (serve first, scale second —
the same fail-open posture as the rest of the boot sequence). The
per-plane `pingoo_mesh_devices` gauge reports what actually serves.

`shard_batch` runs per batch between encode and dispatch — registered
hot in the analyze-lint registries; it may only issue async
`jax.device_put` placements, never a host sync.
"""

from __future__ import annotations

import os
from typing import Optional

from ..parallel.mesh import parse_mesh_spec


class MeshUnavailable(RuntimeError):
    """The configured mesh cannot be built on this backend."""


def mesh_env_spec() -> tuple[int, int, int]:
    """(dp, tp, sp) from PINGOO_MESH (default 1x1x1). Raises ValueError
    on a malformed spec — callers at boot fail fast with the message
    rather than silently serving unsharded."""
    return parse_mesh_spec(os.environ.get("PINGOO_MESH", "1x1x1"))


class MeshExecutor:
    """Owns one plane's mesh + sharding placement for the serving path.

    Inactive (dp*tp*sp == 1) executors are pure pass-throughs: every
    method returns its input untouched and no jax symbol is imported,
    so single-device serving pays nothing for the new layer.
    """

    def __init__(self, plan, spec: Optional[tuple[int, int, int]] = None,
                 plane: str = "python", metrics=None):
        if spec is None:
            spec = mesh_env_spec()
        self.dp, self.tp, self.sp = spec
        self.plane = plane
        self.devices = self.dp * self.tp * self.sp
        self.mesh = None
        self._batch_specs: dict = {}  # arrays signature -> shardings
        if self.devices > 1:
            import jax

            from ..parallel.mesh import make_mesh, pad_tables_for_tp

            have = len(jax.devices())
            if have < self.devices:
                raise MeshUnavailable(
                    f"PINGOO_MESH={self.dp}x{self.tp}x{self.sp} needs "
                    f"{self.devices} devices, backend has {have}")
            if self.tp > 1:
                # Pad pattern/word axes so rule tables shard evenly;
                # padded rows are inert (can never match), so the
                # compiled programs stay bit-identical. The plan keeps
                # the padded tables: a co-resident plane reusing this
                # plan builds the same shapes.
                plan.np_tables = pad_tables_for_tp(plan.np_tables,
                                                   tp=self.tp)
            self.mesh = make_mesh(self.dp, self.tp, self.sp)
        if metrics is not None:
            metrics.mesh_devices.set(self.devices)

    @property
    def active(self) -> bool:
        return self.mesh is not None

    def align_batch(self, padded_size: int) -> int:
        """Smallest launch size >= `padded_size` that shards evenly on
        dp (GSPMD wants the batch axis divisible by the dp extent).
        With the engine's pow2 padding and a pow2 dp this is the
        identity."""
        if self.dp <= 1:
            return padded_size
        rem = padded_size % self.dp
        return padded_size if rem == 0 else padded_size + (self.dp - rem)

    def place_tables(self, tables: dict) -> dict:
        """Device tables -> mesh placement under table_shardings (tp on
        the rule/word axes, replicate the rest). One-time at startup."""
        if not self.active:
            return tables
        import jax

        from ..parallel.mesh import table_shardings

        specs = table_shardings(self.mesh, tables)
        return {key: jax.device_put(val, specs[key])
                for key, val in tables.items()}

    def shard_batch(self, arrays: dict) -> dict:
        """Batch pytree -> dp placement (request axis sharded). Runs per
        batch on the hot path: device_put is an async transfer issue,
        never a sync (lint-registered hot)."""
        if not self.active:
            return arrays
        import jax

        from ..parallel.mesh import batch_shardings

        # Sharding specs depend only on array names/ranks; cache per
        # signature so steady-state batches skip the spec rebuild.
        sig = tuple(sorted(arrays))
        specs = self._batch_specs.get(sig)
        if specs is None:
            specs = batch_shardings(self.mesh, arrays)
            self._batch_specs[sig] = specs
        return {key: jax.device_put(val, specs[key])
                for key, val in arrays.items()}

    def describe(self) -> dict:
        return {"dp": self.dp, "tp": self.tp, "sp": self.sp,
                "devices": self.devices, "active": self.active}
