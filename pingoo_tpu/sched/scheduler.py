"""Deadline-aware continuous-batching admission scheduler (ISSUE 6).

The pre-scheduler serving path admitted work through FIXED batch-
assembly windows: the Python collector waited `max_wait_us` after the
first request of every batch, and the ring sidecar dispatched whatever
one dequeue pass returned. Both couple latency to an arbitrary timer
instead of to the thing the north star actually budgets — each
request's remaining deadline slack (p99 < 2 ms end to end).

This module is the plane-agnostic admission core both engine planes
drive (engine/service.py collector, native_ring.RingSidecar drain):

  * every request carries its ADMIT timestamp and a latency budget
    (`PINGOO_DEADLINE_MS`, default the 2 ms north-star budget);
  * the scheduler keeps filling the in-flight batch while the OLDEST
    request's slack still covers the estimated dispatch+compute cost
    of serving the batch — "launch when full OR slack <= estimate";
  * the cost estimate is an EWMA per padded-batch-size bucket
    (`CostModel`), seeded from bench history (`BENCH_history.jsonl`
    p_batch_ms) so the very first batches after boot already launch
    against a plausible cost instead of a blind timer;
  * a request whose deadline is UNMEETABLE (remaining slack below the
    estimate even if launched immediately) can fail open per
    `PINGOO_SCHED_FAILOPEN`: `serve` (default — serve late, count the
    miss), `allow` (resolve immediately with the fail-open verdict),
    or `interpret` (evaluate on the host interpreter, off the device
    path);
  * every launch/resolve feeds the `pingoo_sched_*` metrics
    (obs/schema.SCHED_METRICS) on the plane's label.

`PINGOO_SCHED_MODE=fixed` keeps the legacy fixed-window assembly (the
A/B arm `bench.py --mesh` measures against); `continuous` is the
default. The admission loop and the EWMA update are registered hot in
the analyze-lint registries (tools/analyze/lint_config.py): nothing
here may allocate arrays or touch the device — it is pure float math
on the collector/drain thread between dispatch and resolve.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

# The north-star latency budget (BASELINE.md: p99 added verdict
# latency < 2 ms) is the default per-request deadline.
DEFAULT_DEADLINE_MS = 2.0

# Default EWMA smoothing for the per-bucket cost model: heavy enough to
# converge within tens of batches after boot, light enough that one
# GC-hiccup outlier cannot triple the estimate.
DEFAULT_ALPHA = 0.2

# Fallback seed when neither PINGOO_SCHED_SEED_MS nor a bench-history
# entry is available: the measured full-batch verdict cost on a v5e
# (bench.py p_batch_ms ~1.4 at B=2048).
DEFAULT_SEED_MS = 1.5

SCHED_MODES = ("continuous", "fixed")
FAILOPEN_POLICIES = ("serve", "allow", "interpret")

# Per-stage cost decomposition for the overlapped executor (ISSUE 9,
# docs/EXECUTOR.md): once stages overlap across in-flight batches, the
# single encode->result wall double-counts the time a batch spent
# waiting on another batch's stage token, so the planes feed each
# stage's ACTIVE wall separately and the estimate is their sum.
PIPELINE_COST_STAGES = ("encode", "dispatch", "compute")

# How the affine seed splits across stages before any per-stage
# observation lands (fractions sum to 1.0 so a pure-seed estimate
# matches the legacy single-wall seed exactly).
STAGE_SEED_SPLIT = {"encode": 0.3, "dispatch": 0.2, "compute": 0.5}

# pingoo_sched_batch_size histogram bounds: pow2 ladder matching the
# padded launch sizes the engine actually compiles for.
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024,
                      2048, 4096)


@dataclass(frozen=True)
class SchedulerConfig:
    """Static admission policy for one plane's scheduler."""

    mode: str = "continuous"
    deadline_ms: float = DEFAULT_DEADLINE_MS
    failopen: str = "serve"
    max_batch: int = 1024

    @classmethod
    def from_env(cls, max_batch: int) -> "SchedulerConfig":
        mode = os.environ.get("PINGOO_SCHED_MODE", "continuous")
        if mode not in SCHED_MODES:
            mode = "continuous"
        try:
            deadline_ms = float(
                os.environ.get("PINGOO_DEADLINE_MS", DEFAULT_DEADLINE_MS))
        except ValueError:
            deadline_ms = DEFAULT_DEADLINE_MS
        failopen = os.environ.get("PINGOO_SCHED_FAILOPEN", "serve")
        if failopen not in FAILOPEN_POLICIES:
            failopen = "serve"
        return cls(mode=mode, deadline_ms=deadline_ms, failopen=failopen,
                   max_batch=max_batch)


def seed_from_bench_history(path: Optional[str] = None) -> Optional[float]:
    """Newest usable `p_batch_ms` from BENCH_history.jsonl (bench.py
    --history appends one JSON object per run). Best-effort: a missing
    or corrupt history just returns None and the static seed applies.
    Read back to front so the seed tracks the latest measurement."""
    import json

    path = path or os.environ.get("BENCH_HISTORY_FILE",
                                  "BENCH_history.jsonl")
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            entry = json.loads(line)
        except ValueError:
            continue
        val = entry.get("p_batch_ms")
        if isinstance(val, (int, float)) and val > 0:
            return float(val)
    return None


def seed_stages_from_bench_history(
        path: Optional[str] = None) -> Optional[dict]:
    """Newest usable per-stage EWMA map from BENCH_history.jsonl
    (ISSUE 12 satellite): bench.py --history flattens the pipelined
    arm's cost snapshot as `pipeline_on_stage_ewma_ms` =
    {stage: {"<bucket>": ms}}. Returns {stage: {bucket:int -> ms}} or
    None. Best-effort like seed_from_bench_history — a missing/corrupt
    history leaves the affine STAGE_SEED_SPLIT fallback in charge, but
    when history exists the very first megastep K-sizing runs against
    MEASURED dispatch/compute walls instead of the 1.5 ms seed."""
    import json

    path = path or os.environ.get("BENCH_HISTORY_FILE",
                                  "BENCH_history.jsonl")
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            entry = json.loads(line)
        except ValueError:
            continue
        raw = entry.get("pipeline_on_stage_ewma_ms")
        if not isinstance(raw, dict):
            continue
        out: dict = {}
        for stage, buckets in raw.items():
            if stage not in STAGE_SEED_SPLIT \
                    or not isinstance(buckets, dict):
                continue
            per_bucket = {}
            for b, ms in buckets.items():
                try:
                    bucket = int(b)
                    val = float(ms)
                except (TypeError, ValueError):
                    continue
                if bucket > 0 and val > 0:
                    per_bucket[bucket] = val
            if per_bucket:
                out[stage] = per_bucket
        if out:
            return out
    return None


def _pow2_bucket(n: int, cap: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def _pow2_kb_bucket(nbytes: int) -> int:
    """Staged-bytes bucket for the dispatch cost model (ISSUE 15):
    pow2 KB, floor 1 KB — coarse enough that one serving config lands
    in one bucket, fine enough that full (~4.6 KB/req) and compact
    (few hundred B/req) staging never share one."""
    kb = max(1, (max(0, int(nbytes)) + 1023) // 1024)
    b = 1
    while b < kb:
        b *= 2
    return b


class CostModel:
    """EWMA per-batch-size dispatch-cost estimates (milliseconds).

    Buckets follow the engine's pow2 batch padding — the cost of a
    batch is a function of its PADDED size, which is what the XLA
    program actually runs. Unobserved buckets fall back to an affine
    seed (half fixed dispatch cost, half size-proportional), so the
    model orders sizes sensibly before the first measurements land.

    `observe` runs per batch on the collector/drain hot path
    (registered in lint_config.HOT_FUNCTIONS): one dict probe and two
    float ops, no arrays, no device access.
    """

    def __init__(self, max_batch: int = 1024,
                 seed_ms: Optional[float] = None,
                 alpha: float = DEFAULT_ALPHA):
        self.max_batch = max(1, int(max_batch))
        seeded_from_env_or_arg = (
            seed_ms is not None
            or bool(os.environ.get("PINGOO_SCHED_SEED_MS")))
        if seed_ms is None:
            env = os.environ.get("PINGOO_SCHED_SEED_MS")
            if env:
                try:
                    seed_ms = float(env)
                except ValueError:
                    seed_ms = None
            if seed_ms is None:
                seed_ms = seed_from_bench_history()
            if seed_ms is None:
                seed_ms = DEFAULT_SEED_MS
        self.seed_ms = max(float(seed_ms), 1e-3)
        self.alpha = float(alpha)
        self._ewma: dict[int, float] = {}
        # Per-stage ACTIVE-wall EWMAs (ISSUE 9): stage -> bucket -> ms.
        # Populated by the overlapped executor; once any stage has
        # data, estimate() is the SUM of stage estimates — the single
        # encode->result wall includes stage-token waits under overlap
        # and would inflate should_launch's slack math.
        #
        # Boot-seeded from bench history (ISSUE 12 satellite, gated the
        # same way as the batch-cost seed: only when no explicit seed
        # was pinned) so the first megastep K-sizing decisions run on
        # measured dispatch/compute walls. Live observations EWMA-blend
        # over the seed from the first batch.
        self._stage_ewma: dict[str, dict[int, float]] = {}
        if seeded_from_env_or_arg is False:
            hist = seed_stages_from_bench_history()
            if hist:
                self._stage_ewma = {s: dict(b) for s, b in hist.items()}
        # Per-(K, bucket) megastep window EWMAs (ISSUE 12): the wall of
        # ONE K-slice device-resident dispatch. Unobserved pairs fall
        # back to the amortization model dispatch + K * compute.
        self._mega_ewma: dict[tuple[int, int], float] = {}
        # First observation per (K, bucket), tracked SEPARATELY (ISSUE
        # 15 satellite): the first window of a new (K, rows) shape pays
        # the cold XLA compile (BENCH_pipeline showed 4x2048 seeded at
        # ~9.5 s), and letting it seed the EWMA meant `auto` could
        # never size K up past the poisoned rung again.
        self._mega_first: dict[tuple[int, int], float] = {}
        # Dispatch-stage EWMAs keyed by staged-BYTES bucket (ISSUE 15):
        # the dispatch wall is bytes-proportional host staging, so with
        # compact staging in play the pow2 row bucket alone conflates
        # full and compact batches of the same size. Bytes-keyed
        # observations take precedence in estimate_dispatch.
        self._dispatch_bytes_ewma: dict[int, float] = {}

    def _seed_for(self, bucket: int) -> float:
        cap = _pow2_bucket(self.max_batch, self.max_batch)
        return self.seed_ms * (0.5 + 0.5 * bucket / cap)

    def _baseline(self, bucket: int) -> float:
        """Whole-batch wall estimate for one bucket: the legacy EWMA
        when observed, the affine seed otherwise."""
        est = self._ewma.get(bucket)
        if est is None:
            return self._seed_for(bucket)
        return est

    def estimate(self, batch_size: int) -> float:
        """Expected dispatch+compute wall (ms) for a batch whose padded
        size covers `batch_size` rows. Stage-decomposed when the
        executor feeds per-stage costs; unobserved stages fall back to
        their STAGE_SEED_SPLIT share of the whole-batch baseline."""
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        if not self._stage_ewma:
            return self._baseline(bucket)
        base = self._baseline(bucket)
        total = 0.0
        for stage in PIPELINE_COST_STAGES:
            est = self._stage_ewma.get(stage, {}).get(bucket)
            if est is None:
                est = STAGE_SEED_SPLIT[stage] * base
            total += est
        return total

    def estimate_stage(self, stage: str, batch_size: int) -> float:
        """Expected ACTIVE wall (ms) of ONE executor stage — the
        per-stage fail-open budget checks size their remaining-work
        slack with this instead of the whole-batch estimate."""
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        est = self._stage_ewma.get(stage, {}).get(bucket)
        if est is None:
            split = STAGE_SEED_SPLIT.get(stage, 1.0)
            return split * self._baseline(bucket)
        return est

    def observe(self, batch_size: int, ms: float) -> None:
        """EWMA update from one served batch's measured cost (hot)."""
        if ms < 0:
            return
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        prev = self._ewma.get(bucket)
        if prev is None:
            self._ewma[bucket] = ms
        else:
            self._ewma[bucket] = prev + self.alpha * (ms - prev)

    def observe_stage(self, stage: str, batch_size: int,
                      ms: float) -> None:
        """EWMA update for one executor stage's ACTIVE wall (hot) —
        callers must exclude time spent waiting on stage tokens."""
        if ms < 0 or stage not in STAGE_SEED_SPLIT:
            return
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        stages = self._stage_ewma.get(stage)
        if stages is None:
            stages = self._stage_ewma[stage] = {}
        prev = stages.get(bucket)
        if prev is None:
            stages[bucket] = ms
        else:
            stages[bucket] = prev + self.alpha * (ms - prev)

    def estimate_megastep(self, k: int, batch_size: int) -> float:
        """Expected wall (ms) of ONE K-slice megastep window (hot;
        ISSUE 12) — the admission loop sizes K down the pow2 ladder
        against the oldest slice's deadline slack with this. Unobserved
        (K, bucket) pairs fall back to the amortization model that is
        the megastep's whole point: one dispatch + K compute walls."""
        k = max(1, int(k))
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        est = self._mega_ewma.get((k, bucket))
        if est is not None:
            return est
        return (self.estimate_stage("dispatch", batch_size)
                + k * self.estimate_stage("compute", batch_size))

    def observe_megastep(self, k: int, batch_size: int,
                         ms: float) -> None:
        """EWMA update from one completed K-slice megastep window's
        measured dispatch->sync wall (hot)."""
        if ms < 0:
            return
        k = max(1, int(k))
        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)
        key = (k, bucket)
        if key not in self._mega_first:
            # The first window of a (K, bucket) shape pays the cold XLA
            # compile; absorb it here so estimate_megastep keeps using
            # the amortization model until a STEADY window lands.
            self._mega_first[key] = ms
            return
        prev = self._mega_ewma.get(key)
        if prev is None:
            self._mega_ewma[key] = ms
        else:
            self._mega_ewma[key] = prev + self.alpha * (ms - prev)

    def estimate_dispatch(self, batch_size: int,
                          staged_bytes: Optional[int] = None) -> float:
        """Expected dispatch-stage wall (ms), preferring the staged-
        BYTES-bucket EWMA when that bucket has been observed (ISSUE 15:
        compact staging ships a fraction of full mode's bytes at the
        same row count, so row-bucket estimates conflate the two)."""
        if staged_bytes:
            est = self._dispatch_bytes_ewma.get(
                _pow2_kb_bucket(staged_bytes))
            if est is not None:
                return est
        return self.estimate_stage("dispatch", batch_size)

    def observe_dispatch_bytes(self, staged_bytes: int,
                               ms: float) -> None:
        """EWMA update for the dispatch stage keyed by the batch's
        staged-bytes pow2-KB bucket (hot)."""
        if ms < 0 or not staged_bytes or staged_bytes <= 0:
            return
        bucket = _pow2_kb_bucket(staged_bytes)
        prev = self._dispatch_bytes_ewma.get(bucket)
        if prev is None:
            self._dispatch_bytes_ewma[bucket] = ms
        else:
            self._dispatch_bytes_ewma[bucket] = \
                prev + self.alpha * (ms - prev)

    def snapshot(self) -> dict:
        return {"seed_ms": round(self.seed_ms, 4),
                "ewma_ms": {b: round(v, 4)
                            for b, v in sorted(self._ewma.items())},
                "stage_ewma_ms": {
                    stage: {b: round(v, 4)
                            for b, v in sorted(buckets.items())}
                    for stage, buckets in sorted(
                        self._stage_ewma.items())},
                "megastep_ewma_ms": {
                    f"{k}x{b}": round(v, 4)
                    for (k, b), v in sorted(self._mega_ewma.items())},
                "megastep_first_ms": {
                    f"{k}x{b}": round(v, 4)
                    for (k, b), v in sorted(self._mega_first.items())},
                "dispatch_bytes_ewma_ms": {
                    f"{kb}kb": round(v, 4)
                    for kb, v in sorted(
                        self._dispatch_bytes_ewma.items())}}

    def restore(self, snap: dict) -> bool:
        """Inverse of snapshot(): overwrite this model's state from a
        durable cost-ledger entry (ISSUE 17). Snapshot keys arrive
        JSON-round-tripped — int bucket keys are strings, megastep keys
        are "KxB", bytes keys "<kb>kb" — so each map is re-parsed;
        unparseable entries are skipped, and the method returns True if
        ANY state was restored. Overwrite (not blend) semantics: a
        ledger measured on the actual backend beats both the static
        seed and the lossy BENCH_history p_batch_ms seeding this path
        replaces."""
        if not isinstance(snap, dict):
            return False
        restored = False
        seed = snap.get("seed_ms")
        if isinstance(seed, (int, float)) and seed > 0:
            self.seed_ms = max(float(seed), 1e-3)
            restored = True

        def _fbuckets(raw):
            out = {}
            if isinstance(raw, dict):
                for b, v in raw.items():
                    try:
                        bucket, val = int(b), float(v)
                    except (TypeError, ValueError):
                        continue
                    if bucket > 0 and val >= 0:
                        out[bucket] = val
            return out

        ewma = _fbuckets(snap.get("ewma_ms"))
        if ewma:
            self._ewma = ewma
            restored = True
        stage_raw = snap.get("stage_ewma_ms")
        if isinstance(stage_raw, dict):
            stage = {}
            for name, buckets in stage_raw.items():
                if name not in STAGE_SEED_SPLIT:
                    continue
                parsed = _fbuckets(buckets)
                if parsed:
                    stage[name] = parsed
            if stage:
                self._stage_ewma = stage
                restored = True

        def _mega(raw):
            out = {}
            if isinstance(raw, dict):
                for key, v in raw.items():
                    try:
                        k_s, b_s = str(key).split("x", 1)
                        out[(int(k_s), int(b_s))] = float(v)
                    except (TypeError, ValueError):
                        continue
            return out

        mega = _mega(snap.get("megastep_ewma_ms"))
        if mega:
            self._mega_ewma = mega
            restored = True
        # _mega_first travels too: it records which (K, bucket) shapes
        # already paid their cold compile, and with the compilation
        # cache cold on a fresh boot that absorption must happen AGAIN
        # — but restoring the map preserves the prior run's measured
        # cold walls for the compile ledger cross-check, and a reloaded
        # steady EWMA above means estimate_megastep never consults it.
        first = _mega(snap.get("megastep_first_ms"))
        if first:
            self._mega_first = first
            restored = True
        disp_raw = snap.get("dispatch_bytes_ewma_ms")
        if isinstance(disp_raw, dict):
            disp = {}
            for key, v in disp_raw.items():
                try:
                    kb = int(str(key).rstrip("kb"))
                    val = float(v)
                except (TypeError, ValueError):
                    continue
                if kb > 0 and val >= 0:
                    disp[kb] = val
            if disp:
                self._dispatch_bytes_ewma = disp
                restored = True
        return restored


# ----------------------------------------------------------------------
# Durable cost ledger (ISSUE 17): CostModel snapshots persisted on
# drain and reloaded at boot, versioned per backend + ruleset
# fingerprint so the future autotuner only ever selects from costs
# measured on the ACTUAL backend under the ACTUAL plan. This replaces
# the lossy BENCH_history seeding path: a reload overwrites whatever
# seed the constructor derived.

COST_LEDGER_VERSION = 1
DEFAULT_COST_LEDGER = "COST_LEDGER.json"


def cost_ledger_path() -> Optional[str]:
    """PINGOO_COST_LEDGER: unset/empty -> the default path (the ledger
    is on by default — it is pure boot-time/drain-time IO, never hot);
    `0`/`off` -> disabled; anything else is the path."""
    raw = os.environ.get("PINGOO_COST_LEDGER", "").strip()
    if raw.lower() in ("0", "off", "false", "none"):
        return None
    if not raw or raw.lower() in ("1", "on", "true"):
        return DEFAULT_COST_LEDGER
    return raw


def _reload_counter(plane: str, result: str, registry=None):
    if registry is None:
        from ..obs import REGISTRY as registry  # noqa: N813
    from ..obs import schema

    return registry.counter(
        "pingoo_costmodel_reload_total",
        schema.PERF_METRICS["pingoo_costmodel_reload_total"],
        labels={"plane": plane, "result": result})


def load_cost_ledger(cost: CostModel, *, backend: str, fingerprint: str,
                     plane: str, path: Optional[str] = None,
                     registry=None) -> str:
    """Boot-time reload of this plane's persisted CostModel snapshot.
    Returns the counted result label: `ok` (EWMAs restored), `stale`
    (version or ruleset-fingerprint mismatch — discarded), `missing`
    (no file / no entry for this backend+plane), `error` (unreadable),
    or `disabled` (gated off, nothing counted)."""
    import json

    if path is None:
        path = cost_ledger_path()
    if path is None:
        return "disabled"
    # Eager zero-valued series so the inventory is scrapeable from
    # boot regardless of which result fires.
    for result in ("ok", "stale", "missing", "error"):
        _reload_counter(plane, result, registry)
    entry_key = f"{backend}|{plane}"
    try:
        if not os.path.exists(path):
            _reload_counter(plane, "missing", registry).inc()
            return "missing"
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        _reload_counter(plane, "error", registry).inc()
        return "error"
    if not isinstance(doc, dict) \
            or doc.get("version") != COST_LEDGER_VERSION:
        _reload_counter(plane, "stale", registry).inc()
        return "stale"
    entry = (doc.get("entries") or {}).get(entry_key)
    if not isinstance(entry, dict):
        _reload_counter(plane, "missing", registry).inc()
        return "missing"
    if entry.get("fingerprint") != fingerprint:
        _reload_counter(plane, "stale", registry).inc()
        return "stale"
    if not cost.restore(entry.get("cost") or {}):
        _reload_counter(plane, "error", registry).inc()
        return "error"
    _reload_counter(plane, "ok", registry).inc()
    return "ok"


def save_cost_ledger(cost: CostModel, *, backend: str, fingerprint: str,
                     plane: str, path: Optional[str] = None) -> bool:
    """Drain-time persist of this plane's CostModel snapshot:
    read-merge-write (other backend|plane entries survive), atomic via
    tmp+rename, best-effort — a failed save never blocks shutdown."""
    import json
    import time

    if path is None:
        path = cost_ledger_path()
    if path is None:
        return False
    doc: dict = {"version": COST_LEDGER_VERSION, "entries": {}}
    try:
        with open(path) as f:
            prior = json.load(f)
        if isinstance(prior, dict) \
                and prior.get("version") == COST_LEDGER_VERSION \
                and isinstance(prior.get("entries"), dict):
            doc["entries"] = prior["entries"]
    except (OSError, ValueError):
        pass
    doc["entries"][f"{backend}|{plane}"] = {
        "ts": round(time.time(), 3),
        "backend": backend,
        "plane": plane,
        "fingerprint": fingerprint,
        "cost": cost.snapshot(),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


class SchedMetrics:
    """The plane's `pingoo_sched_*` instruments (obs/schema.py
    SCHED_METRICS). Created eagerly so both planes expose the full
    inventory from boot (zero-valued until traffic moves them)."""

    def __init__(self, plane: str, registry=None):
        if registry is None:
            from ..obs import REGISTRY as registry  # noqa: N813
        from ..obs import schema

        labels = {"plane": plane}
        self.queue_depth = registry.gauge(
            "pingoo_sched_queue_depth",
            schema.SCHED_METRICS["pingoo_sched_queue_depth"],
            labels=labels)
        self.batch_size = registry.histogram(
            "pingoo_sched_batch_size",
            schema.SCHED_METRICS["pingoo_sched_batch_size"],
            buckets=BATCH_SIZE_BUCKETS, labels=labels)
        self.deadline_miss = registry.counter(
            "pingoo_sched_deadline_miss_total",
            schema.SCHED_METRICS["pingoo_sched_deadline_miss_total"],
            labels=labels)
        self.failopen = registry.counter(
            "pingoo_sched_failopen_total",
            schema.SCHED_METRICS["pingoo_sched_failopen_total"],
            labels=labels)
        self.mesh_devices = registry.gauge(
            "pingoo_mesh_devices",
            schema.SCHED_METRICS["pingoo_mesh_devices"], labels=labels)
        self.mesh_devices.set(1)


class Scheduler:
    """One plane's admission scheduler: launch-timing policy + deadline
    accounting over the shared cost model.

    All timestamps are `time.monotonic()` seconds on the Python plane;
    the sidecar converts the ring's `enq_ms` clock before calling in.
    The policy methods are pure float math (hot path — see module
    docstring); the metrics sinks are O(1) registry instruments.
    """

    def __init__(self, config: SchedulerConfig, plane: str = "python",
                 cost_model: Optional[CostModel] = None, registry=None):
        self.config = config
        self.plane = plane
        self.cost = cost_model or CostModel(max_batch=config.max_batch)
        self.metrics = SchedMetrics(plane, registry=registry)
        self.launches = 0
        self.deadline_misses = 0
        self.failopens = 0

    # -- launch policy (hot) -------------------------------------------------

    def wait_budget_s(self, n_pending: int, oldest_admit_s: float,
                      now_s: float) -> float:
        """How much longer the plane may keep assembling this batch
        (seconds, <= 0 means launch NOW): the oldest request's
        remaining deadline slack minus the estimated cost of serving
        the batch at its current size."""
        if n_pending >= self.config.max_batch:
            return 0.0
        deadline_at = oldest_admit_s + self.config.deadline_ms / 1e3
        est_s = self.cost.estimate(n_pending) / 1e3
        return (deadline_at - now_s) - est_s

    def should_launch(self, n_pending: int, oldest_admit_s: float,
                      now_s: float) -> bool:
        """Launch when full OR when the oldest request's slack no
        longer covers the dispatch estimate."""
        return (n_pending >= self.config.max_batch
                or self.wait_budget_s(n_pending, oldest_admit_s,
                                      now_s) <= 0.0)

    def unmeetable(self, admit_s: float, now_s: float,
                   batch_size: int) -> bool:
        """True when this request's deadline cannot be met even by an
        immediate launch — the fail-open trigger."""
        deadline_at = admit_s + self.config.deadline_ms / 1e3
        return now_s + self.cost.estimate(batch_size) / 1e3 > deadline_at

    # -- accounting sinks ----------------------------------------------------

    def note_launch(self, batch_size: int, queue_depth: int) -> None:
        """One batch left admission for the device (hot)."""
        self.launches += 1
        self.metrics.batch_size.observe(batch_size)
        self.metrics.queue_depth.set(queue_depth)

    def note_resolved(self, admit_s: float, resolve_s: float) -> bool:
        """Per-request deadline accounting at resolve time; returns
        True when the request missed its deadline."""
        missed = (resolve_s - admit_s) * 1e3 > self.config.deadline_ms
        if missed:
            self.deadline_misses += 1
            self.metrics.deadline_miss.inc()
        return missed

    def note_misses(self, n: int) -> None:
        """Batched deadline-miss accounting (the sidecar counts misses
        with one vectorized compare per batch)."""
        if n > 0:
            self.deadline_misses += n
            self.metrics.deadline_miss.inc(n)

    def note_failopen(self, n: int = 1) -> None:
        self.failopens += n
        self.metrics.failopen.inc(n)

    def observe_cost(self, batch_size: int, ms: float) -> None:
        self.cost.observe(batch_size, ms)

    def observe_stage_cost(self, stage: str, batch_size: int,
                           ms: float) -> None:
        """Per-stage ACTIVE-wall feed from the overlapped executor
        (hot; ISSUE 9) — keeps should_launch's slack estimate honest
        once stages overlap across in-flight batches."""
        self.cost.observe_stage(stage, batch_size, ms)

    def observe_megastep_cost(self, k: int, batch_size: int,
                              ms: float) -> None:
        """One completed K-slice megastep window's measured wall
        (hot; ISSUE 12)."""
        self.cost.observe_megastep(k, batch_size, ms)

    def observe_dispatch_bytes(self, staged_bytes: int,
                               ms: float) -> None:
        """Dispatch-stage wall keyed by the batch's staged-bytes bucket
        (hot; ISSUE 15 compact staging)."""
        self.cost.observe_dispatch_bytes(staged_bytes, ms)

    def size_megastep_k(self, k_ladder, batch_size: int,
                        oldest_admit_s: float, now_s: float) -> int:
        """Largest K rung whose estimated megastep window still fits
        the OLDEST pending slice's remaining deadline slack (ISSUE 12).
        Never below 1 — a megastep with a blown budget still launches
        immediately at K=1 rather than stalling (the miss is counted at
        resolve like every other late batch)."""
        slack_ms = (oldest_admit_s + self.config.deadline_ms / 1e3
                    - now_s) * 1e3
        k = 1
        for rung in k_ladder:
            if rung == 1:
                continue
            if self.cost.estimate_megastep(rung, batch_size) <= slack_ms:
                k = rung
        return k

    def snapshot(self) -> dict:
        return {
            "mode": self.config.mode,
            "deadline_ms": self.config.deadline_ms,
            "failopen_policy": self.config.failopen,
            "launches": self.launches,
            "deadline_misses": self.deadline_misses,
            "failopens": self.failopens,
            "cost_model": self.cost.snapshot(),
        }
