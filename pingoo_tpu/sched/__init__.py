"""Serving-mesh scheduler subsystem (ISSUE 6, docs/SCHEDULER.md).

The layer between the admission queues (the Python listener's asyncio
queue, the native plane's shm ring) and the compiled verdict programs:

  * `scheduler.Scheduler` — deadline-aware continuous-batching
    admission (launch when full OR when the oldest request's slack no
    longer covers the EWMA dispatch estimate), per-request deadline
    accounting, and the fail-open policy for unmeetable deadlines.
  * `mesh_exec.MeshExecutor` — live dp×tp×sp mesh execution: shard the
    rule tables on tp and each request batch on dp at serve time
    (PINGOO_MESH; 1x1x1 keeps single-device behavior bit-identical).
"""

from .mesh_exec import MeshExecutor, MeshUnavailable, mesh_env_spec
from .scheduler import (BATCH_SIZE_BUCKETS, PIPELINE_COST_STAGES,
                        CostModel, SchedMetrics,
                        Scheduler, SchedulerConfig,
                        seed_from_bench_history)

__all__ = [
    "BATCH_SIZE_BUCKETS",
    "PIPELINE_COST_STAGES",
    "CostModel",
    "MeshExecutor",
    "MeshUnavailable",
    "SchedMetrics",
    "Scheduler",
    "SchedulerConfig",
    "mesh_env_spec",
    "seed_from_bench_history",
]
