"""Ruleset plan: device table assembly + the batched evaluator builder.

`compile_ruleset` takes the validated rules (config/schema.py RuleConfig)
plus loaded lists and produces a `RulesetPlan`:

  * every device-lowerable rule becomes a BoolIR over deduplicated leaf
    predicates (compiler/lowering.py);
  * leaves are grouped into per-field pattern tables (ops/match_ops.py),
    per-field NFA banks (compiler/nfa.py -> ops/nfa_scan.py), CIDR/int
    membership tables (ops/cidr.py);
  * rules outside the subset keep their compiled Program and are
    interpreted on host over the same truncated request view, preserving
    exact verdict parity (the fallback split in SURVEY.md §7).

The plan's `device_tables()` returns one pytree of jnp arrays; the
verdict function over (tables, batch) lives in engine/verdict.py and is
traced from the static plan structure, so the whole ruleset compiles to
one XLA program per batch shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Optional

import numpy as np

from ..config.schema import Action, RuleConfig
from ..expr import Program
from ..expr.values import Ip
from . import repat
from .lowering import (
    DEFAULT_FIELD_SPECS,
    IntListPred,
    IpListPred,
    IpPred,
    LeafRegistry,
    Lowerer,
    LowerError,
    NfaPred,
    NumCmp,
    StrListPred,
    StrPred,
)
from .nfa import build_bank
from ..ops.cidr import build_cidr_table, build_int_set, build_v4_buckets, ip_to_words
from ..ops.match_ops import build_pattern_table, build_suffix_table
from ..ops.nfa_scan import bank_to_tables
from ..ops.window_match import build_window_table


@dataclass
class PlannedRule:
    name: str
    actions: tuple[Action, ...]
    index: int  # original rule order (first-match semantics on host)
    ir: Optional[object]  # BoolIR when device-lowered
    program: Optional[Program]  # for host fallback / no-expression rules
    host: bool  # True -> interpret on host
    always: bool = False  # rule with no expression matches everything


@dataclass
class LeafBinding:
    """Where a leaf's [B] result comes from at eval time."""

    kind: str
    # kind-specific static metadata:
    field: str = ""
    group: str = ""  # 'eq' | 'prefix' | 'suffix'
    col: int = -1
    span: tuple[int, int] = (0, 0)  # NFA slot range / eq-col range
    table_key: str = ""  # key into plan tables dict
    pred: Any = None  # NumCmp / IntListPred probe IR


@dataclass
class RulesetPlan:
    field_specs: dict[str, int]
    rules: list[PlannedRule]
    leaves: list[object]
    bindings: dict[int, LeafBinding]
    # static (host-side numpy) table constructors' outputs:
    np_tables: dict[str, Any] = dc_field(default_factory=dict)
    stats: dict[str, int] = dc_field(default_factory=dict)
    # service name -> pseudo-rule column for its route predicate
    route_index: dict[str, int] = dc_field(default_factory=dict)

    def device_tables(self) -> dict[str, Any]:
        """Materialize all tables as device arrays (a pytree)."""
        import jax.numpy as jnp

        out: dict[str, Any] = {}
        for key, val in self.np_tables.items():
            if isinstance(val, np.ndarray):
                out[key] = jnp.asarray(val)
            elif isinstance(val, dict):
                out[key] = {k: jnp.asarray(v) for k, v in val.items()}
            else:
                out[key] = val  # already a NamedTuple pytree of jnp arrays
        return out

    @property
    def device_rule_indices(self) -> list[int]:
        return [r.index for r in self.rules if not r.host]

    @property
    def host_rules(self) -> list[PlannedRule]:
        return [r for r in self.rules if r.host]


def compile_ruleset(
    rules: list[RuleConfig],
    lists: dict[str, list],
    field_specs: Optional[dict[str, int]] = None,
    routes: Optional[list[tuple[str, Optional[Program]]]] = None,
) -> RulesetPlan:
    """Compile WAF rules (+ optional service `route:` predicates) into
    one plan. Routes become extra actionless pseudo-rule columns of the
    SAME batched verdict — route semantics are exactly rule semantics
    (exact-true match, error -> no-match, no expression -> match-all;
    reference services/mod.rs match_request + http_proxy_service.rs:
    84-95), so the per-request route interpretation on the listener hot
    path collapses into the batch. `plan.route_index[name]` gives each
    service's column in the match matrix."""
    field_specs = dict(field_specs or DEFAULT_FIELD_SPECS)
    registry = LeafRegistry()
    lowerer = Lowerer(lists, registry, field_specs)

    def lower_one(name: str, actions, idx: int,
                  program: Optional[Program]) -> PlannedRule:
        if program is None:
            # No expression -> always matches (pingoo/rules.rs:48-50).
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=None, program=None, host=False, always=True)
        mark = registry.mark()
        try:
            ir = lowerer.lower_rule(program.root)
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=ir, program=program, host=False)
        except LowerError:
            registry.rollback(mark)  # don't ship a host rule's partial leaves
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=None, program=program, host=True)

    planned: list[PlannedRule] = []
    for idx, rule in enumerate(rules):
        planned.append(lower_one(rule.name, rule.actions, idx,
                                 rule.expression))
    route_index: dict[str, int] = {}
    for name, program in routes or []:
        idx = len(planned)
        route_index[name] = idx
        planned.append(lower_one(f"route:{name}", (), idx, program))

    plan = RulesetPlan(
        field_specs=field_specs,
        rules=planned,
        leaves=registry.leaves,
        bindings={},
        route_index=route_index,
    )
    _assemble_tables(plan)
    # Stats count REAL rules only — route pseudo-columns get their own
    # counters so bench/metrics numbers don't inflate with services.
    real = planned[: len(rules)]
    pseudo = planned[len(rules):]
    plan.stats = {
        "rules": len(real),
        "device_rules": sum(1 for r in real if not r.host),
        "host_rules": sum(1 for r in real if r.host),
        "routes": len(pseudo),
        "host_routes": sum(1 for r in pseudo if r.host),
        "leaves": len(registry.leaves),
    }
    return plan


def _assemble_tables(plan: RulesetPlan) -> None:
    # Group string predicates per (field, kind).
    str_groups: dict[tuple[str, str], list[tuple[int, StrPred]]] = {}
    nfa_groups: dict[str, list[tuple[int, NfaPred]]] = {}
    ip_preds: list[tuple[int, IpPred]] = []

    for leaf_id, leaf in enumerate(plan.leaves):
        if isinstance(leaf, StrPred):
            str_groups.setdefault((leaf.field, leaf.kind), []).append(
                (leaf_id, leaf))
        elif isinstance(leaf, NfaPred):
            nfa_groups.setdefault(leaf.field, []).append((leaf_id, leaf))
        elif isinstance(leaf, IpPred):
            ip_preds.append((leaf_id, leaf))
        elif isinstance(leaf, StrListPred):
            key = f"strlist_{leaf_id}"
            plan.np_tables[key] = build_pattern_table(
                [(e, False) for e in leaf.entries] or [(b"\x00nevermatch", False)]
            )
            plan.bindings[leaf_id] = LeafBinding(
                kind="str_list", field=leaf.field, table_key=key,
                span=(0, len(leaf.entries)))
        elif isinstance(leaf, IpListPred):
            entries = [Ip(e) for e in leaf.entries]
            key = f"iplist_{leaf_id}"
            if len(entries) <= 2048:
                plan.np_tables[key] = build_cidr_table(entries)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="ip_list_small", table_key=key)
            else:
                plan.np_tables[key] = build_v4_buckets(entries)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="ip_list_large", table_key=key)
        elif isinstance(leaf, IntListPred):
            key = f"intlist_{leaf_id}"
            plan.np_tables[key] = build_int_set(list(leaf.values))
            plan.bindings[leaf_id] = LeafBinding(
                kind="int_list", table_key=key, pred=leaf.probe)
        elif isinstance(leaf, NumCmp):
            plan.bindings[leaf_id] = LeafBinding(kind="num_cmp", pred=leaf)
        else:
            raise AssertionError(f"unbound leaf {leaf!r}")

    for (field, kind), entries in str_groups.items():
        key = f"{kind}_{field}"
        pats = [(leaf.pattern, leaf.ci) for _, leaf in entries]
        if kind == "suffix":
            plan.np_tables[key] = build_suffix_table(pats)
        else:
            plan.np_tables[key] = build_pattern_table(pats)
        for col, (leaf_id, _) in enumerate(entries):
            plan.bindings[leaf_id] = LeafBinding(
                kind="str", field=field, group=kind, col=col, table_key=key)

    for field, entries in nfa_groups.items():
        patterns = []
        win_patterns: list = []
        for leaf_id, leaf in entries:
            if leaf.kind == "contains":
                alts = [repat.literal_pattern(
                    leaf.pattern.encode("latin-1"), case_insensitive=leaf.ci)]
            else:
                alts = repat.compile_regex(leaf.pattern)
            # Fixed-shape literal-ish leaves skip the serial NFA scan
            # entirely: every alternative must lower to a window pattern
            # (ops/window_match.py — one MXU conv pair per field instead
            # of one VPU step per byte).
            wins = [repat.to_window(lp) for lp in alts
                    if not lp.never_match]
            if wins and all(w is not None for w in wins):
                start = len(win_patterns)
                win_patterns.extend(wins)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="window", field=field,
                    span=(start, len(win_patterns)),
                    table_key=f"win_{field}")
                continue
            start = len(patterns)
            patterns.extend(alts)
            plan.bindings[leaf_id] = LeafBinding(
                kind="nfa", field=field, span=(start, len(patterns)),
                table_key=f"nfa_{field}")
        if patterns:
            bank = build_bank(patterns)
            plan.np_tables[f"nfa_{field}"] = bank_to_tables(bank)
        if win_patterns:
            plan.np_tables[f"win_{field}"] = build_window_table(win_patterns)

    if ip_preds:
        nets = np.zeros((len(ip_preds), 4), dtype=np.uint32)
        masks = np.zeros((len(ip_preds), 4), dtype=np.uint32)
        from ..ops.cidr import _prefix_masks

        for col, (leaf_id, leaf) in enumerate(ip_preds):
            m = _prefix_masks(leaf.prefix)
            nets[col] = np.array(leaf.words, dtype=np.uint32) & m
            masks[col] = m
            plan.bindings[leaf_id] = LeafBinding(kind="ip_one", col=col,
                                                 table_key="ip_preds")
        plan.np_tables["ip_preds"] = {"nets": nets, "masks": masks}
