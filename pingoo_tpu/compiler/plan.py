"""Ruleset plan: device table assembly + the batched evaluator builder.

`compile_ruleset` takes the validated rules (config/schema.py RuleConfig)
plus loaded lists and produces a `RulesetPlan`:

  * every device-lowerable rule becomes a BoolIR over deduplicated leaf
    predicates (compiler/lowering.py);
  * leaves are grouped into per-field pattern tables (ops/match_ops.py),
    per-field NFA banks (compiler/nfa.py -> ops/nfa_scan.py), CIDR/int
    membership tables (ops/cidr.py);
  * rules outside the subset keep their compiled Program and are
    interpreted on host over the same truncated request view, preserving
    exact verdict parity (the fallback split in SURVEY.md §7).

The plan's `device_tables()` returns one pytree of jnp arrays; the
verdict function over (tables, batch) lives in engine/verdict.py and is
traced from the static plan structure, so the whole ruleset compiles to
one XLA program per batch shape.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field as dc_field, replace as dc_replace
from typing import Any, Optional

import numpy as np

from ..config.schema import Action, RuleConfig
from ..expr import Program
from ..expr.values import Ip
from . import repat
from .lowering import (
    DEFAULT_FIELD_SPECS,
    IntListPred,
    IpListPred,
    IpPred,
    LeafRegistry,
    Lowerer,
    LowerError,
    NBin,
    NfaPred,
    NLen,
    NNeg,
    NumCmp,
    StrListPred,
    StrPred,
    nfa_leaf_patterns,
)
from .nfa import build_bank
from ..ops.cidr import build_cidr_table, build_int_set, build_v4_buckets, ip_to_words
from ..ops.match_ops import build_pattern_table, build_suffix_table
from ..ops.nfa_scan import bank_to_tables
from ..ops.window_match import build_window_table


# -- NFA scan strategy selection ---------------------------------------------
#
# The roofline (docs/ROOFLINE.md) showed the verdict kernel bound by the
# serial NFA scan chain: per-LOOP-ITERATION dispatch/dependency latency,
# not per-byte work. The levers that cut iterations (pair stepping, the
# within-device halo split) and the fused Pallas kernel that cuts
# per-iteration cost used to hang off env knobs (PINGOO_NFA_LOOKUP /
# PINGOO_HALO_SPLIT); they are now selected PER BANK at plan time, the
# choice travels with the plan through the ruleset artifact cache
# (compiler/cache.py), and bench.py's micro-autotune hook can re-select
# from measured per-iteration costs (`reselect_scan_strategies`).

# Relative cost of ONE scan-loop iteration per strategy kind. The
# defaults are placeholders that encode the dispatch-bound ordering the
# roofline measured (a fused kernel iteration ~ the execution floor, a
# pair iteration slightly dearer than a single gather but half as many
# of them); bench.py --autotune replaces them with measured values on a
# live backend.
DEFAULT_STEP_COSTS = {
    "scan": 1.0,        # lax.scan, one [256/C, W] gather per byte
    "pair": 1.3,        # lax.scan, one [C^2, 2W] gather per TWO bytes
    "pallas": 0.25,     # fused kernel, one fused lookup+advance per byte
    "pallas_pair": 0.35,  # fused kernel, two bytes per loop iteration
    # Bitsplit DFA (ISSUE 8): one [S, C]-row gather per byte, ~4
    # lane-ops/byte, no dependent matmul and no opt-propagation passes.
    "dfa": 0.15,
}

DFA_KIND = "dfa"

# -- Compact staging (ISSUE 15, docs/EXECUTOR.md "Compact staging") ----------
#
# The dispatch wall is bytes-proportional host staging (BENCH_pipeline:
# ~39.6 ms/batch at B=2048 is the staging copy, not launches). Most
# rulesets only inspect a small prefix of each string field, so the
# compile pass below derives, per field, the maximum byte position any
# compiled scanner can depend on, and `PINGOO_STAGING=compact` stages
# only that capped prefix. The cap is quantized to this pow2 rung
# ladder (à la megastep K) so hot-swapping between tenants whose caps
# land on the same rung reuses the XLA compile.
STAGING_RUNGS = (16, 32, 64, 128, 256, 512, 1024, 2048)


def quantize_stage_cap(depth: int, spec: int) -> int:
    """Smallest rung >= depth, clamped to the field's full spec (a
    2-byte country code never pads out to rung 16)."""
    for rung in STAGING_RUNGS:
        if rung >= depth:
            return min(rung, spec)
    return spec


def _kind_cost(c: dict, kind: str, default: float = 1.0) -> float:
    """Forward-compatible cost lookup: a measured/partial cost dict (or
    a cached plan from a build that didn't know `kind` yet) falls back
    to DEFAULT_STEP_COSTS, then to `default`, instead of KeyError-ing."""
    v = c.get(kind)
    if v is None:
        v = DEFAULT_STEP_COSTS.get(kind, default)
    return float(v)


@dataclass(frozen=True)
class ScanStrategy:
    """One bank's selected scan execution strategy (static plan metadata).

    kind    — "scan" (lax.scan) or "pallas" (fused kernel,
              ops/pallas_scan.py)
    pair    — advance two bytes per loop iteration (the pair lookup for
              lax.scan, 2x-unrolled stepping inside the Pallas kernel)
    halo_k  — maximum within-device halo split factor to ATTEMPT at
              trace time (halo_split_k re-checks eligibility against the
              actual bucketed length; 1 disables)
    source  — "default" (cost model), "measured" (bench autotune),
              "env" (PINGOO_SCAN_STRATEGY override)
    cost    — modeled relative per-iteration cost at selection time
    """

    kind: str = "scan"
    pair: bool = False
    halo_k: int = 1
    source: str = "default"
    cost: float = 0.0


@dataclass(frozen=True)
class NfaScanPlan:
    """Plan-time scan decisions for one field's NFA bank (static; rides
    the plan pickle into the artifact cache).

    When the halo partition is active, `split` names the two np_tables
    sub-bank keys ("<key>@short" halo-splittable, "<key>@rest"
    residual) and `slot_perm[p]` maps logical pattern slot p to its
    column in concat(short_hits, rest_hits); the whole-bank table stays
    at `key` for the parallel (mesh/ring) paths."""

    key: str
    strategy: ScanStrategy
    split: tuple[str, str] | None = None
    short_strategy: ScanStrategy | None = None
    rest_strategy: ScanStrategy | None = None
    slot_perm: tuple[int, ...] | None = None
    extended: bool = False  # footprint-extension rewrote the main bank
    # Bitsplit-DFA lowering (ISSUE 8): when the bank subset-constructed
    # within the state budget, `dfa_key` names its DfaTables in
    # np_tables and `dfa_strategy` carries the modeled cost. `strategy`
    # stays the best NON-DFA kind (the recheck/fallback path needs it);
    # `dfa_auto` records whether the cost model prefers the DFA —
    # PINGOO_DFA=auto honors it, =force overrides it per bank.
    dfa_key: str | None = None
    dfa_strategy: ScanStrategy | None = None
    dfa_auto: bool = False


def _pallas_ok() -> bool:
    try:
        from ..ops.pallas_scan import pallas_available

        return pallas_available()
    except Exception:
        return False


def select_scan_strategy(tables, costs: dict | None = None,
                         pallas_ok: bool | None = None,
                         source: str = "default") -> ScanStrategy:
    """Pick the cheapest (kind, pair) for one bank under a per-iteration
    cost model; iteration counts scale the pair variants by 1/2, so the
    ranking is independent of the (trace-time) field length. halo_k is
    eligibility metadata: halo re-checks profitability at trace time."""
    c = dict(costs or {})
    if pallas_ok is None:
        pallas_ok = _pallas_ok()
    cands = [("scan", False, _kind_cost(c, "scan")),
             ("scan", True, _kind_cost(c, "pair") / 2)]
    if pallas_ok:
        cands += [("pallas", False, _kind_cost(c, "pallas")),
                  ("pallas", True, _kind_cost(c, "pallas_pair") / 2)]
    kind, pair, cost = min(cands, key=lambda x: x[2])
    halo_k = 8 if tables.halo_ok else 1
    return ScanStrategy(kind=kind, pair=pair, halo_k=halo_k,
                        source=source, cost=cost)


def select_dfa_strategy(costs: dict | None = None,
                        source: str = "default") -> ScanStrategy:
    """Strategy record for a lowered bank's bitsplit-DFA path. Same
    per-byte normalization as select_scan_strategy's candidates (one
    loop iteration consumes one byte, no pair variant)."""
    return ScanStrategy(kind=DFA_KIND, pair=False, halo_k=1, source=source,
                        cost=_kind_cost(costs or {}, DFA_KIND))


def strategy_steps(tables, L: int, strat: ScanStrategy) -> int:
    """Dependent-step count of `strat` on this bank at bucketed length L
    (the roofline convention: loop iterations x opt-propagation passes).
    Accounts for a trace-time halo split when the strategy would take
    it."""
    from ..ops.nfa_scan import halo_split_k

    if strat.kind == DFA_KIND:
        # One [S, C]-row gather per byte: no opt-propagation passes, no
        # pair variant — the dependent chain is exactly L steps.
        return L
    passes = 1 + tables.extra_passes
    iters = (L + 1) // 2 if strat.pair else L
    if strat.halo_k > 1:
        k = halo_split_k(tables, L, max_k=strat.halo_k)
        if k > 1:
            halo_iters = L // k + int(tables.max_footprint)
            if halo_iters < iters:
                iters = halo_iters
    return iters * passes


def _halo_fp_budget() -> int:
    return int(os.environ.get("PINGOO_HALO_FP_BUDGET", "16"))


def _split_enabled() -> bool:
    return os.environ.get("PINGOO_NFA_SPLIT", "0") != "0"


def _dfa_lower_enabled() -> bool:
    """PINGOO_DFA_LOWER=0 is the compile-time kill switch: no DFA tables
    are built at all (PINGOO_DFA=off merely skips them at trace time)."""
    return os.environ.get("PINGOO_DFA_LOWER", "1") != "0"


def split_config_token() -> str:
    """The plan-shaping env knobs, hashed into the artifact-cache
    fingerprint: plans built under different split settings have
    different np_tables layouts."""
    from .nfa import _dfa_merge_depths, _dfa_state_budget

    dfa = (f"dfa={int(_dfa_lower_enabled())}"
           f":s={_dfa_state_budget(None)}"
           f":m={','.join(str(d) for d in _dfa_merge_depths(None))}")
    return f"nfa_split={int(_split_enabled())}:fp={_halo_fp_budget()}:{dfa}"


def _halo_partition(patterns, field_len: int):
    """Footprint-extension pass + partition for one field's patterns.

    Each pattern is made halo-compatible when possible: rep-free already,
    or rewritten by repat.extend_footprint (exact over the field's
    device byte cap). Patterns whose bounded footprint fits the halo
    budget form the `short` (halo-splittable) set; the rest keep their
    original form. Returns (short_idx, rest_idx, short_pats, rest_pats)
    or None when the partition is degenerate (no residual bank needed —
    caller handles the all-short case via whole-bank extension)."""
    from .nfa import MAX_SCAN_BITS, pattern_footprint, scan_bits_needed

    budget = _halo_fp_budget()
    short_idx, rest_idx = [], []
    short_pats, rest_pats = [], []
    for i, lp in enumerate(patterns):
        cand = lp
        if repat.has_unbounded_rep(lp):
            cand = repat.extend_footprint(lp, field_len)
        ok = cand is not None and not repat.has_unbounded_rep(cand)
        if ok:
            try:
                ok = (pattern_footprint(cand) <= budget
                      and scan_bits_needed(cand) <= MAX_SCAN_BITS)
            except repat.Unsupported:
                ok = False
        if ok:
            short_idx.append(i)
            short_pats.append(cand)
        else:
            rest_idx.append(i)
            rest_pats.append(lp)
    if not short_idx or not rest_idx:
        return None
    return short_idx, rest_idx, short_pats, rest_pats


# -- literal-prefilter cascade (Stage A metadata) -----------------------------
#
# ISSUE 4: each contains/regex pattern gets a *necessary literal factor*
# at compile time (compiler/repat.necessary_factor) — a byte-class run
# that must appear in the field for the pattern to match. Factors are
# deduplicated per field and packed into one shift-AND bank
# (ops/prefilter.py) scanned ONCE per batch; engine/verdict.py consults
# the per-bank candidate masks to skip or compact the exact NFA scans.
# The prefilter may only PRUNE, never decide: final verdicts are
# bit-identical across PINGOO_PREFILTER=off|banks|compact
# (tests/test_prefilter.py asserts this structurally).

PF_ALWAYS = -1  # slot has no extractable factor: its bank always scans
PF_NEVER = -2  # slot never matches: contributes nothing to candidates

PREFILTER_MODES = ("off", "banks", "compact")


@dataclass
class FieldFactors:
    """One byte field's deduplicated factor inventory."""

    field: str
    table_key: str  # np_tables key of the PrefilterTables ("pf_<field>")
    num_factors: int
    # The factor byte-class tuples themselves (small; kept for the
    # differential property tests and plan introspection).
    factors: tuple[tuple[frozenset, ...], ...]


@dataclass
class PrefilterPlan:
    """Static Stage-A metadata riding the RulesetPlan into the artifact
    cache (FORMAT_VERSION bump in compiler/cache.py)."""

    fields: dict[str, FieldFactors] = dc_field(default_factory=dict)
    bank_field: dict[str, str] = dc_field(default_factory=dict)
    # np_tables bank key -> bool [F] mask over its field's factors.
    bank_masks: dict[str, Any] = dc_field(default_factory=dict)
    # bank key -> True when EVERY slot is factor-gated (or never-match):
    # only then may the whole bank be skipped/compacted.
    bank_gated: dict[str, bool] = dc_field(default_factory=dict)
    # bank key -> per-slot factor index (PF_ALWAYS / PF_NEVER sentinels).
    slot_codes: dict[str, tuple] = dc_field(default_factory=dict)
    # Strategy used when the PINGOO_PREFILTER env override is unset;
    # bench.py's autotune records the measured best mode here and
    # persists it through compiler.cache.update_cached_plan.
    default_mode: str = "banks"


def _plan_field_prefilter(plan: "RulesetPlan", field: str,
                          bank_slots: dict[str, list],
                          nfa_key: Optional[str] = None,
                          split_idx=None) -> None:
    """Extract + pack one field's factors; register per-bank masks.

    `bank_slots` maps each of the field's scan banks (the NFA bank AND
    the MXU window bank — both are gated by the cascade) to its per-slot
    source LinearPatterns. The factor table is shared per FIELD (one
    Stage-A scan feeds every bank); `split_idx` additionally registers
    the NFA halo-partition @short/@rest sub-bank subsets. Fields with no
    extractable factor get no table."""
    from ..ops.prefilter import (build_prefilter_bank,
                                 bank_to_prefilter_tables)

    pf = plan.prefilter
    if pf is None or not bank_slots:
        return
    factors: list = []
    index: dict = {}

    def code_of(lp) -> int:
        if lp.never_match:
            return PF_NEVER
        fac = repat.necessary_factor(lp)
        if fac is None:
            return PF_ALWAYS
        idx = index.get(fac)
        if idx is None:
            idx = len(factors)
            index[fac] = idx
            factors.append(fac)
        return idx

    bank_codes = {bkey: [code_of(lp) for lp in pats]
                  for bkey, pats in bank_slots.items()}
    if not factors:
        return
    bank = build_prefilter_bank(factors)
    table_key = f"pf_{field}"
    plan.np_tables[table_key] = bank_to_prefilter_tables(bank)
    pf.fields[field] = FieldFactors(
        field=field, table_key=table_key, num_factors=len(factors),
        factors=tuple(factors))

    def register(bank_key: str, codes) -> None:
        codes = tuple(codes)
        mask = np.zeros(len(factors), dtype=bool)
        for c in codes:
            if c >= 0:
                mask[c] = True
        pf.bank_field[bank_key] = field
        pf.bank_masks[bank_key] = mask
        pf.bank_gated[bank_key] = all(c != PF_ALWAYS for c in codes)
        pf.slot_codes[bank_key] = codes

    for bkey, codes in bank_codes.items():
        register(bkey, codes)
    if nfa_key is not None and split_idx is not None:
        nfa_codes = bank_codes[nfa_key]
        register(f"{nfa_key}@short",
                 [nfa_codes[i] for i in split_idx[0]])
        register(f"{nfa_key}@rest",
                 [nfa_codes[i] for i in split_idx[1]])


def reselect_scan_strategies(plan: "RulesetPlan",
                             costs: dict | None = None,
                             source: str = "measured") -> None:
    """Re-run strategy selection (e.g. with measured per-iteration costs
    from bench.py's autotune hook) and update the plan in place. Callers
    persist via compiler.cache.update_cached_plan."""
    for key, entry in list(plan.scan_plans.items()):
        strategy = select_scan_strategy(
            plan.np_tables[key], costs, source=source)
        kwargs = {"strategy": strategy}
        if entry.split:
            kwargs["short_strategy"] = select_scan_strategy(
                plan.np_tables[entry.split[0]], costs, source=source)
            kwargs["rest_strategy"] = select_scan_strategy(
                plan.np_tables[entry.split[1]], costs, source=source)
        if entry.dfa_key is not None:
            # Re-rank the DFA against the measured non-DFA best; the
            # cost dict may predate the "dfa" kind (_kind_cost falls
            # back to the model default instead of KeyError-ing).
            dfa_strategy = select_dfa_strategy(costs, source=source)
            kwargs["dfa_strategy"] = dfa_strategy
            kwargs["dfa_auto"] = dfa_strategy.cost < strategy.cost
        plan.scan_plans[key] = dc_replace(entry, **kwargs)


@dataclass
class PlannedRule:
    name: str
    actions: tuple[Action, ...]
    index: int  # original rule order (first-match semantics on host)
    ir: Optional[object]  # BoolIR when device-lowered
    program: Optional[Program]  # for host fallback / no-expression rules
    host: bool  # True -> interpret on host
    always: bool = False  # rule with no expression matches everything


@dataclass
class LeafBinding:
    """Where a leaf's [B] result comes from at eval time."""

    kind: str
    # kind-specific static metadata:
    field: str = ""
    group: str = ""  # 'eq' | 'prefix' | 'suffix'
    col: int = -1
    span: tuple[int, int] = (0, 0)  # NFA slot range / eq-col range
    table_key: str = ""  # key into plan tables dict
    pred: Any = None  # NumCmp / IntListPred probe IR


@dataclass
class RulesetPlan:
    field_specs: dict[str, int]
    rules: list[PlannedRule]
    leaves: list[object]
    bindings: dict[int, LeafBinding]
    # static (host-side numpy) table constructors' outputs:
    np_tables: dict[str, Any] = dc_field(default_factory=dict)
    stats: dict[str, int] = dc_field(default_factory=dict)
    # service name -> pseudo-rule column for its route predicate
    route_index: dict[str, int] = dc_field(default_factory=dict)
    # per-NFA-bank scan strategy decisions (static; cached with the plan)
    scan_plans: dict[str, NfaScanPlan] = dc_field(default_factory=dict)
    # Stage-A literal-prefilter metadata (None for factor-less rulesets)
    prefilter: Optional[PrefilterPlan] = None
    # Bitsplit-DFA mode when the PINGOO_DFA env override is unset
    # (off|auto|force); bench.py's --dfa arm records the measured best
    # and persists it through compiler.cache.update_cached_plan.
    dfa_default_mode: str = "auto"
    # Lowered MXU window banks (ISSUE 8): "win_<field>" ->
    # "dfa_win_<field>" in np_tables. The window conv is serial-free on
    # the MXU, so the DFA replaces it only where per-row work dominates
    # (CPU diagnostic backend under auto, any backend under force) —
    # engine/verdict._dfa_win_active.
    win_dfa: dict[str, str] = dc_field(default_factory=dict)
    # Compact staging (ISSUE 15): per-field raw dependent byte depth
    # and the quantized staged cap PINGOO_STAGING=compact copies.
    # Empty on plans cached before FORMAT_VERSION 11 — consumers fall
    # back to field_specs (full staging) via getattr.
    staging_required: dict[str, int] = dc_field(default_factory=dict)
    staging_caps: dict[str, int] = dc_field(default_factory=dict)

    def device_tables(self) -> dict[str, Any]:
        """Materialize all tables as device arrays (a pytree)."""
        import jax.numpy as jnp

        out: dict[str, Any] = {}
        for key, val in self.np_tables.items():
            if isinstance(val, np.ndarray):
                out[key] = jnp.asarray(val)
            elif isinstance(val, dict):
                out[key] = {k: jnp.asarray(v) for k, v in val.items()}
            else:
                out[key] = val  # already a NamedTuple pytree of jnp arrays
        return out

    @property
    def device_rule_indices(self) -> list[int]:
        return [r.index for r in self.rules if not r.host]

    @property
    def host_rules(self) -> list[PlannedRule]:
        return [r for r in self.rules if r.host]

    @property
    def rule_names(self) -> tuple[str, ...]:
        """Rule names in ORIGINAL index order (route pseudo-rules
        included) — the label space of the per-rule attribution lanes
        and the flight recorder (obs/provenance.py, ISSUE 5)."""
        return tuple(r.name for r in self.rules)

    def provenance_labels(self) -> dict:
        """Static label inventory the provenance layer exports against:
        rule names, the device-column -> original-index mapping for the
        on-device attribution fold, and the cascade-gated bank keys for
        banks-skipped attribution. Everything here is plan-static, so
        label cardinality is fixed at compile time."""
        pf = self.prefilter
        gated = tuple(k for k, g in pf.bank_gated.items() if g) \
            if pf is not None else ()
        return {
            "rules": self.rule_names,
            "device_cols": tuple(self.device_rule_indices),
            "gated_banks": gated,
        }


def compile_ruleset(
    rules: list[RuleConfig],
    lists: dict[str, list],
    field_specs: Optional[dict[str, int]] = None,
    routes: Optional[list[tuple[str, Optional[Program]]]] = None,
) -> RulesetPlan:
    """Compile WAF rules (+ optional service `route:` predicates) into
    one plan. Routes become extra actionless pseudo-rule columns of the
    SAME batched verdict — route semantics are exactly rule semantics
    (exact-true match, error -> no-match, no expression -> match-all;
    reference services/mod.rs match_request + http_proxy_service.rs:
    84-95), so the per-request route interpretation on the listener hot
    path collapses into the batch. `plan.route_index[name]` gives each
    service's column in the match matrix."""
    field_specs = dict(field_specs or DEFAULT_FIELD_SPECS)
    registry = LeafRegistry()
    lowerer = Lowerer(lists, registry, field_specs)

    def lower_one(name: str, actions, idx: int,
                  program: Optional[Program]) -> PlannedRule:
        if program is None:
            # No expression -> always matches (pingoo/rules.rs:48-50).
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=None, program=None, host=False, always=True)
        mark = registry.mark()
        try:
            ir = lowerer.lower_rule(program.root)
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=ir, program=program, host=False)
        except LowerError:
            registry.rollback(mark)  # don't ship a host rule's partial leaves
            return PlannedRule(name=name, actions=actions, index=idx,
                               ir=None, program=program, host=True)

    planned: list[PlannedRule] = []
    for idx, rule in enumerate(rules):
        planned.append(lower_one(rule.name, rule.actions, idx,
                                 rule.expression))
    route_index: dict[str, int] = {}
    for name, program in routes or []:
        idx = len(planned)
        route_index[name] = idx
        planned.append(lower_one(f"route:{name}", (), idx, program))

    plan = RulesetPlan(
        field_specs=field_specs,
        rules=planned,
        leaves=registry.leaves,
        bindings={},
        route_index=route_index,
        prefilter=PrefilterPlan(),
    )
    _assemble_tables(plan)
    if plan.prefilter is not None and not plan.prefilter.fields:
        plan.prefilter = None  # nothing extractable: Stage A is a no-op
    # Stats count REAL rules only — route pseudo-columns get their own
    # counters so bench/metrics numbers don't inflate with services.
    real = planned[: len(rules)]
    pseudo = planned[len(rules):]
    pf = plan.prefilter
    plan.stats = {
        "rules": len(real),
        "device_rules": sum(1 for r in real if not r.host),
        "host_rules": sum(1 for r in real if r.host),
        "routes": len(pseudo),
        "host_routes": sum(1 for r in pseudo if r.host),
        "leaves": len(registry.leaves),
        "prefilter_factors": (sum(f.num_factors for f in pf.fields.values())
                              if pf else 0),
        "prefilter_gated_banks": (sum(1 for g in pf.bank_gated.values() if g)
                                  if pf else 0),
        "dfa_banks": sum(
            1 for e in plan.scan_plans.values() if e.dfa_key)
        + len(plan.win_dfa),
        "dfa_states_total": sum(
            plan.np_tables[e.dfa_key].num_states
            for e in plan.scan_plans.values() if e.dfa_key)
        + sum(plan.np_tables[k].num_states
              for k in plan.win_dfa.values()),
    }
    derive_staging_caps(plan)
    return plan


def _num_ir_len_fields(ir) -> set[str]:
    """Fields whose length() an arithmetic IR reads (NLen nodes)."""
    out: set[str] = set()
    stack = [ir]
    while stack:
        node = stack.pop()
        if isinstance(node, NLen):
            out.add(node.field)
        elif isinstance(node, NBin):
            stack.append(node.left)
            stack.append(node.right)
        elif isinstance(node, NNeg):
            stack.append(node.x)
    return out


def derive_staging_caps(plan: RulesetPlan) -> None:
    """Per-field maximum dependent byte depth across every compiled
    scanner, -> plan.staging_required (raw) and plan.staging_caps
    (quantized to STAGING_RUNGS, clamped to the spec).

    Soundness is structural — the staged view must only ever PRUNE
    bytes no scanner reads, never change a verdict:

      * eq needs |pattern|+1 bytes: the device compares exact `lens`
        (full true values ride regardless of the staged width), and the
        +1 guard keeps interpreter contexts built from staged bytes
        exact too — a string truncated at cap >= |pat|+1 still has
        length > |pat|, so equality stays False either way.
      * prefix needs exactly |pattern| bytes.
      * suffix anchors at the END of the true string -> full spec.
      * contains/regex (NFA, bitsplit-DFA, window/MXU banks) and the
        Stage-A prefilter scan the whole field -> full spec.
      * length() inside device arithmetic (NLen) pins the field so the
        interpreter fallback/parity contexts — whose length() comes
        from the staged bytes — agree with the device's exact lens.
      * host rules and host route predicates re-evaluate on contexts
        built from the staged bytes, so every string field their AST
        references is pinned to full spec.

    Rows whose TRUE length exceeds a below-spec cap are flagged
    overflow by the encoder and re-interpreted from the untruncated
    source (the existing over-long backstop), which is what makes the
    caps verdict-preserving without per-rule reasoning at eval time."""
    specs = plan.field_specs
    required: dict[str, int] = {f: 0 for f in specs}

    def need(field: str, depth: int) -> None:
        if field in required:
            required[field] = max(required[field], int(depth))

    def pin(field: str) -> None:
        if field in required:
            required[field] = int(specs[field])

    for leaf in plan.leaves:
        if isinstance(leaf, StrPred):
            if leaf.kind == "eq":
                need(leaf.field, len(leaf.pattern) + 1)
            elif leaf.kind == "prefix":
                need(leaf.field, len(leaf.pattern))
            else:  # suffix: anchored at the true end of the string
                pin(leaf.field)
        elif isinstance(leaf, StrListPred):
            need(leaf.field, max(
                (len(e) for e in leaf.entries), default=0) + 1)
        elif isinstance(leaf, NfaPred):
            pin(leaf.field)
        elif isinstance(leaf, NumCmp):
            for f in _num_ir_len_fields(leaf.left):
                pin(f)
            for f in _num_ir_len_fields(leaf.right):
                pin(f)
        elif isinstance(leaf, IntListPred):
            for f in _num_ir_len_fields(leaf.probe):
                pin(f)
    from ..expr import ast as _east

    for rule in plan.rules:
        if rule.host and rule.program is not None:
            for node in _east.walk(rule.program.root):
                if not isinstance(node, _east.Member) \
                        or not isinstance(node.obj, _east.Ident):
                    continue
                if node.obj.name == "http_request" \
                        and node.attr in specs:
                    pin(node.attr)
                elif node.obj.name == "client" \
                        and node.attr == "country":
                    pin("country")
    plan.staging_required = dict(required)
    plan.staging_caps = {
        f: quantize_stage_cap(required[f], spec)
        for f, spec in specs.items()
    }


def _assemble_tables(plan: RulesetPlan) -> None:
    # Group string predicates per (field, kind).
    str_groups: dict[tuple[str, str], list[tuple[int, StrPred]]] = {}
    nfa_groups: dict[str, list[tuple[int, NfaPred]]] = {}
    ip_preds: list[tuple[int, IpPred]] = []

    for leaf_id, leaf in enumerate(plan.leaves):
        if isinstance(leaf, StrPred):
            str_groups.setdefault((leaf.field, leaf.kind), []).append(
                (leaf_id, leaf))
        elif isinstance(leaf, NfaPred):
            nfa_groups.setdefault(leaf.field, []).append((leaf_id, leaf))
        elif isinstance(leaf, IpPred):
            ip_preds.append((leaf_id, leaf))
        elif isinstance(leaf, StrListPred):
            key = f"strlist_{leaf_id}"
            plan.np_tables[key] = build_pattern_table(
                [(e, False) for e in leaf.entries] or [(b"\x00nevermatch", False)]
            )
            plan.bindings[leaf_id] = LeafBinding(
                kind="str_list", field=leaf.field, table_key=key,
                span=(0, len(leaf.entries)))
        elif isinstance(leaf, IpListPred):
            entries = [Ip(e) for e in leaf.entries]
            key = f"iplist_{leaf_id}"
            if len(entries) <= 2048:
                plan.np_tables[key] = build_cidr_table(entries)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="ip_list_small", table_key=key)
            else:
                plan.np_tables[key] = build_v4_buckets(entries)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="ip_list_large", table_key=key)
        elif isinstance(leaf, IntListPred):
            key = f"intlist_{leaf_id}"
            plan.np_tables[key] = build_int_set(list(leaf.values))
            plan.bindings[leaf_id] = LeafBinding(
                kind="int_list", table_key=key, pred=leaf.probe)
        elif isinstance(leaf, NumCmp):
            plan.bindings[leaf_id] = LeafBinding(kind="num_cmp", pred=leaf)
        else:
            raise AssertionError(f"unbound leaf {leaf!r}")

    for (field, kind), entries in str_groups.items():
        key = f"{kind}_{field}"
        pats = [(leaf.pattern, leaf.ci) for _, leaf in entries]
        if kind == "suffix":
            plan.np_tables[key] = build_suffix_table(pats)
        else:
            plan.np_tables[key] = build_pattern_table(pats)
        for col, (leaf_id, _) in enumerate(entries):
            plan.bindings[leaf_id] = LeafBinding(
                kind="str", field=field, group=kind, col=col, table_key=key)

    for field, entries in nfa_groups.items():
        patterns = []
        win_patterns: list = []
        win_srcs: list = []  # window slots' source LinearPatterns
        for leaf_id, leaf in entries:
            alts = nfa_leaf_patterns(leaf)
            # Fixed-shape literal-ish leaves skip the serial NFA scan
            # entirely: every alternative must lower to a window pattern
            # (ops/window_match.py — one MXU conv pair per field instead
            # of one VPU step per byte).
            live = [lp for lp in alts if not lp.never_match]
            wins = [repat.to_window(lp) for lp in live]
            if wins and all(w is not None for w in wins):
                start = len(win_patterns)
                win_patterns.extend(wins)
                win_srcs.extend(live)
                plan.bindings[leaf_id] = LeafBinding(
                    kind="window", field=field,
                    span=(start, len(win_patterns)),
                    table_key=f"win_{field}")
                continue
            start = len(patterns)
            patterns.extend(alts)
            plan.bindings[leaf_id] = LeafBinding(
                kind="nfa", field=field, span=(start, len(patterns)),
                table_key=f"nfa_{field}")
        split_idx = None
        if patterns:
            split_idx = _plan_nfa_bank(plan, field, patterns)
        if win_patterns:
            plan.np_tables[f"win_{field}"] = build_window_table(win_patterns)
            # Bitsplit-DFA lowering of the WINDOW bank (ISSUE 8): the
            # window slots' source LinearPatterns are fixed-shape
            # literal-ish, so the subset construction is small (an
            # Aho-Corasick-style multi-literal DFA) and almost always
            # exact. The conv table stays — it is the serial-free MXU
            # path and the recheck/fallback — the DFA replaces it only
            # where row work dominates (engine/verdict._dfa_win_active).
            if _dfa_lower_enabled():
                from .nfa import lower_bank_to_dfa
                from ..ops.bitsplit_dfa import dfa_to_tables

                win_dfa_bank = lower_bank_to_dfa(win_srcs)
                if win_dfa_bank is not None:
                    plan.np_tables[f"dfa_win_{field}"] = \
                        dfa_to_tables(win_dfa_bank)
                    plan.win_dfa[f"win_{field}"] = f"dfa_win_{field}"
        # Stage-A factor pass covers BOTH of the field's scan banks (the
        # serial NFA bank and the MXU window bank) from one shared
        # factor table; factors come from the ORIGINAL patterns (any
        # footprint-extended rewrites are match-equivalent over the
        # field cap, so necessity transfers unchanged).
        bank_slots: dict[str, list] = {}
        if patterns:
            bank_slots[f"nfa_{field}"] = patterns
        if win_patterns:
            bank_slots[f"win_{field}"] = win_srcs
        _plan_field_prefilter(
            plan, field, bank_slots,
            nfa_key=f"nfa_{field}" if patterns else None,
            split_idx=split_idx)

    if ip_preds:
        nets = np.zeros((len(ip_preds), 4), dtype=np.uint32)
        masks = np.zeros((len(ip_preds), 4), dtype=np.uint32)
        from ..ops.cidr import _prefix_masks

        for col, (leaf_id, leaf) in enumerate(ip_preds):
            m = _prefix_masks(leaf.prefix)
            nets[col] = np.array(leaf.words, dtype=np.uint32) & m
            masks[col] = m
            plan.bindings[leaf_id] = LeafBinding(kind="ip_one", col=col,
                                                 table_key="ip_preds")
        plan.np_tables["ip_preds"] = {"nets": nets, "masks": masks}


def _plan_nfa_bank(plan: RulesetPlan, field: str,
                   patterns: list):
    """Build one field's NFA tables + scan plan; returns the halo
    partition's (short_idx, rest_idx) slot subsets (None when the bank
    is not partitioned) for the prefilter sub-bank registration.

    Footprint-extension / halo pipeline (docs/ROOFLINE.md lever 1):

      * if EVERY pattern is halo-compatible after repat.extend_footprint
        (exact over the field's device byte cap), the main bank itself is
        rebuilt bounded — whole-bank halo_ok, no extra tables;
      * else, with PINGOO_NFA_SPLIT=1, the bank is PARTITIONED: patterns
        whose bounded footprint fits the halo budget form a
        halo-splittable `@short` sub-bank, the rest (wide spans,
        unboundable reps) a `@rest` residual sub-bank stepping by pairs —
        the whole-bank table stays for the mesh/ring parallel paths;
      * the scan strategy (lax.scan vs fused Pallas, single vs pair
        step) is selected per bank by the cost model and recorded in
        plan.scan_plans, so it persists through the artifact cache.
    """
    from .nfa import MAX_SCAN_BITS, pattern_footprint, scan_bits_needed

    key = f"nfa_{field}"
    field_len = plan.field_specs.get(field, 2048)
    bank = build_bank(patterns)
    tables = bank_to_tables(bank)
    extended = False
    if not tables.halo_ok:
        # Whole-bank footprint extension: only worth the extra width if
        # every rep pattern bounds within the device caps.
        cands = []
        for lp in patterns:
            cand = repat.extend_footprint(lp, field_len) \
                if repat.has_unbounded_rep(lp) else lp
            if cand is None or repat.has_unbounded_rep(cand):
                cands = None
                break
            try:
                if scan_bits_needed(cand) > MAX_SCAN_BITS:
                    cands = None
                    break
            except repat.Unsupported:
                cands = None
                break
            cands.append(cand)
        if cands is not None:
            ext_tables = bank_to_tables(build_bank(cands))
            if ext_tables.halo_ok:
                tables = ext_tables
                extended = True
    plan.np_tables[key] = tables

    # Bitsplit-DFA lowering (ISSUE 8): subset-construct the WHOLE bank
    # when it fits the state budget (exact first, then the approximate
    # merge ladder; compiler/nfa.lower_bank_to_dfa). The ORIGINAL
    # patterns are lowered — a footprint-extension rewrite above is
    # match-equivalent over the field's device byte cap, so per-slot
    # semantics line up. The @short/@rest halo partition keeps the NFA
    # path; the DFA dispatch in engine/verdict.py only takes the
    # non-split whole-bank branch.
    dfa_key = None
    dfa_strategy = None
    dfa_auto = False
    if _dfa_lower_enabled():
        from .nfa import lower_bank_to_dfa
        from ..ops.bitsplit_dfa import dfa_to_tables

        dfa_bank = lower_bank_to_dfa(patterns)
        if dfa_bank is not None:
            dfa_key = f"dfa_{field}"
            plan.np_tables[dfa_key] = dfa_to_tables(dfa_bank)
            dfa_strategy = select_dfa_strategy()

    split = None
    short_strategy = rest_strategy = None
    slot_perm = None
    split_idx = None
    if _split_enabled() and not tables.halo_ok:
        parts = _halo_partition(patterns, field_len)
        if parts is not None:
            short_idx, rest_idx, short_pats, rest_pats = parts
            split_idx = (short_idx, rest_idx)
            short_tables = bank_to_tables(build_bank(short_pats))
            rest_tables = bank_to_tables(build_bank(rest_pats))
            plan.np_tables[f"{key}@short"] = short_tables
            plan.np_tables[f"{key}@rest"] = rest_tables
            order = short_idx + rest_idx
            perm = [0] * len(order)
            for col, p in enumerate(order):
                perm[p] = col
            slot_perm = tuple(perm)
            split = (f"{key}@short", f"{key}@rest")
            short_strategy = select_scan_strategy(short_tables)
            rest_strategy = select_scan_strategy(rest_tables)
    strategy = select_scan_strategy(tables)
    if dfa_strategy is not None:
        dfa_auto = dfa_strategy.cost < strategy.cost
    plan.scan_plans[key] = NfaScanPlan(
        key=key,
        strategy=strategy,
        split=split,
        short_strategy=short_strategy,
        rest_strategy=rest_strategy,
        slot_perm=slot_perm,
        extended=extended,
        dfa_key=dfa_key,
        dfa_strategy=dfa_strategy,
        dfa_auto=dfa_auto,
    )
    return split_idx
