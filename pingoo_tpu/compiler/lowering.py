"""Rule AST -> device predicate IR.

Lowers each compiled rule expression (expr/ast.py) into:

  * a set of deduplicated *leaf predicates* executed batched on device —
    string matches (eq/prefix/suffix via ops/match_ops.py, contains/regex
    via the NFA bank), ip/CIDR membership, int-set membership, numeric
    comparisons over request columns; and
  * a boolean IR tree combining leaf results with error lanes that
    reproduce the interpreter's exact error semantics: `&&`/`||`
    short-circuit left-to-right, every other operator evaluates both
    sides, and a top-level error means no-match (fail-open, reference
    pingoo/rules.rs:41-44).

Anything outside the device subset raises LowerError and the whole rule
falls back to host interpretation (the parity oracle) — never silently
approximated. Subtrees referencing only `lists` are constant-folded with
the interpreter at compile time.

Value-category model during lowering:
  LBool(ir)      — boolean IR tree
  LNum(numexpr)  — int64 scalar expression over request columns
  LStrField(f)   — a request byte field (path/url/host/method/user_agent/
                   country)
  LStrLit(s)     — compile-time string
  LIp            — the client ip column
  LList(...)     — a statically-resolved list (config lists or literals)
  LErr           — subtree that always errors at runtime (missing list
                   key, type mismatch): usable, but poisons via err lane
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..expr import ast
from ..expr.errors import EvalError
from ..expr.interp import Context, evaluate
from ..expr.values import I64_MAX, I64_MIN, Ip
from . import repat

# Request byte fields and their device capacities (bytes). The reference
# caps UA at 256 (empty + 403 on overflow, http_listener.rs:159,196-198)
# and host at 256 (EMPTY on overflow, get_host http_listener.rs:284-296,
# http_utils.rs:20-21) but matches the FULL path/url. The listener
# reproduces the UA/host caps before encoding, so those fields never
# overflow; path/url/method get generous device capacities and any
# request whose field still exceeds its capacity is re-evaluated on the
# host interpreter over the UNTRUNCATED strings (engine/service.py), so
# on the Python plane padding a URL can never bypass a content rule.
# (The native ring plane carries the same 2048-byte caps in its slots;
# overflow rows ship their FULL url/path through the ring's spill area
# and are re-evaluated untruncated by the sidecar — native_ring.py
# _interpret_overflow_row — so both planes match full strings.)
DEFAULT_FIELD_SPECS = {
    "host": 256,
    "url": 2048,
    "path": 2048,
    "method": 16,
    "user_agent": 256,
    "country": 2,
}
NUM_COLUMNS = ("asn", "remote_port")
MAX_INLINE_STR_LIST = 1024
MAX_SMALL_CIDR_LIST = 2048


class LowerError(Exception):
    """Expression is outside the device subset -> host-interpreted rule."""


def _lit_bytes(value: str) -> bytes | None:
    """Literal -> canonical bytes (latin-1 view, expr/values.py). None if
    the literal contains chars > 0xFF, which can never match byte data."""
    try:
        return value.encode("latin-1")
    except UnicodeEncodeError:
        return None


# -- boolean IR --------------------------------------------------------------


@dataclass(frozen=True)
class BConst:
    value: bool


@dataclass(frozen=True)
class BErrConst:
    """Always-error subtree (e.g. missing list key, type mismatch)."""


@dataclass(frozen=True)
class BLeaf:
    leaf_id: int


@dataclass(frozen=True)
class BNot:
    x: "BoolIR"


@dataclass(frozen=True)
class BAnd:
    left: "BoolIR"
    right: "BoolIR"


@dataclass(frozen=True)
class BOr:
    left: "BoolIR"
    right: "BoolIR"


@dataclass(frozen=True)
class BEqBool:
    """Bool == Bool (both sides evaluated; no short-circuit)."""

    left: "BoolIR"
    right: "BoolIR"
    negate: bool


BoolIR = object  # union of the above


# -- numeric IR --------------------------------------------------------------


@dataclass(frozen=True)
class NConst:
    value: int


@dataclass(frozen=True)
class NCol:
    name: str  # 'asn' | 'remote_port'


@dataclass(frozen=True)
class NLen:
    field: str


@dataclass(frozen=True)
class NBin:
    op: str  # + - * / %
    left: "NumIR"
    right: "NumIR"


@dataclass(frozen=True)
class NNeg:
    x: "NumIR"


NumIR = object


# -- leaf predicates ---------------------------------------------------------


@dataclass(frozen=True)
class StrPred:
    """eq / prefix / suffix over a byte field."""

    kind: str  # 'eq' | 'prefix' | 'suffix'
    field: str
    pattern: bytes
    ci: bool = False


@dataclass(frozen=True)
class NfaPred:
    """contains-literal or regex over a byte field."""

    field: str
    kind: str  # 'contains' | 'regex'
    pattern: str  # literal text or regex source
    ci: bool = False


@dataclass(frozen=True)
class IpPred:
    """client.ip vs one literal address/CIDR."""

    words: tuple[int, int, int, int]
    prefix: int


@dataclass(frozen=True)
class IpListPred:
    """client.ip in a CIDR list (config list or inline array)."""

    entries: tuple[str, ...]  # canonical text forms


@dataclass(frozen=True)
class IntListPred:
    """NumExpr value in a sorted int set."""

    values: tuple[int, ...]
    probe: object  # NumIR


@dataclass(frozen=True)
class StrListPred:
    """Byte field equals any of N strings (exact match set)."""

    field: str
    entries: tuple[bytes, ...]


@dataclass(frozen=True)
class NumCmp:
    """Numeric comparison leaf: lhs <op> rhs over int64 lanes."""

    op: str  # '==' '!=' '<' '<=' '>' '>='
    left: object  # NumIR
    right: object  # NumIR


LeafPred = object  # union


def nfa_leaf_patterns(leaf: "NfaPred") -> list["repat.LinearPattern"]:
    """The linear-pattern alternatives one NFA leaf scans (match = any).

    Single source of truth for the plan's bank assembly AND the
    prefilter factor pass (compiler/plan.py): both must see the exact
    same alternatives or the candidate sets could drift from the scanned
    patterns. Raises repat.Unsupported only for regex leaves that never
    passed lowering (callers hold already-lowered leaves)."""
    if leaf.kind == "contains":
        return [repat.literal_pattern(leaf.pattern.encode("latin-1"),
                                      case_insensitive=leaf.ci)]
    return repat.compile_regex(leaf.pattern)


class LeafRegistry:
    """Deduplicating allocator of leaf predicate ids."""

    def __init__(self) -> None:
        self.leaves: list[LeafPred] = []
        self._index: dict[LeafPred, int] = {}

    def add(self, leaf: LeafPred) -> int:
        idx = self._index.get(leaf)
        if idx is None:
            idx = len(self.leaves)
            self.leaves.append(leaf)
            self._index[leaf] = idx
        return idx

    def mark(self) -> int:
        return len(self.leaves)

    def rollback(self, mark: int) -> None:
        """Drop leaves registered after `mark` — used when a rule fails to
        lower mid-way, so its partial leaves don't bloat device tables.
        Leaves shared with earlier rules predate the mark and survive."""
        for leaf in self.leaves[mark:]:
            del self._index[leaf]
        del self.leaves[mark:]


# -- lowered value categories ------------------------------------------------


@dataclass(frozen=True)
class LBool:
    ir: object


@dataclass(frozen=True)
class LNum:
    ir: object


@dataclass(frozen=True)
class LStrField:
    field: str


@dataclass(frozen=True)
class LStrLit:
    value: str


@dataclass(frozen=True)
class LIp:
    pass


@dataclass(frozen=True)
class LList:
    values: tuple  # resolved items
    elem: str  # 'String' | 'Int' | 'Ip' | 'mixed'


@dataclass(frozen=True)
class LErr:
    """Always-raises subtree."""


class Lowerer:
    def __init__(self, lists: dict[str, list], registry: LeafRegistry,
                 field_specs: Optional[dict[str, int]] = None):
        self.lists = lists
        self.reg = registry
        self.field_specs = field_specs or DEFAULT_FIELD_SPECS
        self._fold_ctx = Context({"lists": lists})

    # -- public --------------------------------------------------------------

    def lower_rule(self, root: ast.Node) -> object:
        """Lower a rule expression to BoolIR. Raises LowerError."""
        self._rule_scan_bits = 0  # per-RULE NFA footprint accumulator
        val = self.lower(root)
        return self._as_bool(val)

    def _charge_scan_bits(self, bits: int) -> None:
        """Count NFA state bits against the per-rule cap — across ALL of
        the rule's matches()/contains() predicates, so one rule can't
        blow up the bank's lane count through many medium literals."""
        from .nfa import MAX_RULE_SCAN_BITS

        self._rule_scan_bits = getattr(self, "_rule_scan_bits", 0) + bits
        if self._rule_scan_bits > MAX_RULE_SCAN_BITS:
            raise LowerError("rule NFA footprint exceeds the per-rule bit cap")

    # -- helpers -------------------------------------------------------------

    def _as_bool(self, val: object) -> object:
        if isinstance(val, LBool):
            return val.ir
        if isinstance(val, LErr):
            return BErrConst()
        # Rule result must be exactly `true` (pingoo/rules.rs:47); any
        # other type is a constant no-match, not an error.
        return BConst(False)

    def _try_fold(self, node: ast.Node) -> object | None:
        """Constant-fold subtrees that reference at most `lists`."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Ident) and sub.name != "lists":
                return None
        try:
            value = evaluate(node, self._fold_ctx)
        except EvalError:
            return LErr()
        return self._value_to_lowered(value)

    def _value_to_lowered(self, value: object) -> object:
        if isinstance(value, bool):
            return LBool(BConst(value))
        if isinstance(value, int):
            return LNum(NConst(value))
        if isinstance(value, str):
            return LStrLit(value)
        if isinstance(value, float):
            raise LowerError("float values are host-evaluated")
        if isinstance(value, Ip):
            raise LowerError("bare ip constant")
        if isinstance(value, list):
            return self._list_to_lowered(value)
        raise LowerError(f"constant of unsupported type {type(value).__name__}")

    def _list_to_lowered(self, items: list) -> LList:
        if all(isinstance(i, str) for i in items):
            return LList(tuple(items), "String")
        if all(isinstance(i, int) and not isinstance(i, bool) for i in items):
            return LList(tuple(items), "Int")
        if all(isinstance(i, Ip) for i in items):
            return LList(tuple(items), "Ip")
        return LList(tuple(items), "mixed")

    # -- dispatch ------------------------------------------------------------

    def lower(self, node: ast.Node) -> object:
        folded = self._try_fold(node)
        if folded is not None:
            return folded
        if isinstance(node, ast.Member):
            return self._lower_member(node)
        if isinstance(node, ast.Index):
            return self._lower_index(node)
        if isinstance(node, ast.Call):
            return self._lower_call(node)
        if isinstance(node, ast.Unary):
            return self._lower_unary(node)
        if isinstance(node, ast.Logical):
            return self._lower_logical(node)
        if isinstance(node, ast.Binary):
            return self._lower_binary(node)
        if isinstance(node, ast.Ident):
            # A bare struct variable has no device value category.
            raise LowerError(f"bare variable {node.name!r}")
        raise LowerError(f"unsupported node {type(node).__name__}")

    def _lower_member(self, node: ast.Member) -> object:
        if isinstance(node.obj, ast.Ident):
            base = node.obj.name
            if base == "http_request":
                if node.attr in ("host", "url", "path", "method", "user_agent"):
                    return LStrField(node.attr)
                return LErr()  # unknown field -> runtime error in interp
            if base == "client":
                if node.attr == "ip":
                    return LIp()
                if node.attr == "country":
                    return LStrField("country")
                if node.attr in NUM_COLUMNS:
                    return LNum(NCol(node.attr))
                return LErr()
        raise LowerError("unsupported member access")

    def _lower_index(self, node: ast.Index) -> object:
        # lists["name"] — static resolution; other indexing is host-only.
        if (
            isinstance(node.obj, ast.Ident)
            and node.obj.name == "lists"
            and isinstance(node.key, ast.Literal)
            and isinstance(node.key.value, str)
        ):
            name = node.key.value
            if name not in self.lists:
                return LErr()  # missing key raises at runtime -> err lane
            return self._list_to_lowered(self.lists[name])
        raise LowerError("unsupported indexing")

    # -- calls ---------------------------------------------------------------

    def _lower_call(self, node: ast.Call) -> object:
        if node.recv is None:
            if node.func == "length" and len(node.args) == 1:
                return self._lower_length(self.lower(node.args[0]))
            raise LowerError(f"unsupported function {node.func}")
        recv = self.lower(node.recv)
        if node.func == "length" and not node.args:
            return self._lower_length(recv)
        if len(node.args) != 1:
            return LErr()  # arity error raises in interp
        arg = self.lower(node.args[0])

        if node.func in ("starts_with", "ends_with"):
            if isinstance(recv, LStrField) and isinstance(arg, LStrLit):
                pat = _lit_bytes(arg.value)
                if pat is None:
                    return LBool(BConst(False))  # >0xFF chars never match
                kind = "prefix" if node.func == "starts_with" else "suffix"
                leaf = self.reg.add(
                    StrPred(kind=kind, field=recv.field, pattern=pat))
                return LBool(BLeaf(leaf))
            if isinstance(recv, LErr) or isinstance(arg, LErr):
                return LErr()
            if isinstance(recv, LStrLit) and isinstance(arg, LStrLit):
                # handled by folding; only reachable with odd shapes
                raise LowerError("static starts_with not folded")
            if not isinstance(recv, (LStrField, LStrLit)) or not isinstance(
                    arg, (LStrField, LStrLit)):
                return LErr()  # type error in interp
            raise LowerError(f"{node.func} with dynamic argument")

        if node.func == "contains":
            return self._lower_contains(recv, arg)

        if node.func == "matches":
            if isinstance(recv, LStrField) and isinstance(arg, LStrLit):
                try:
                    alts = repat.compile_regex(arg.value)
                    from .nfa import MAX_SCAN_BITS, scan_bits_needed

                    total = 0
                    for lp in alts:
                        need = scan_bits_needed(lp)
                        total += need
                        if need > MAX_SCAN_BITS:
                            raise repat.Unsupported(
                                "expanded pattern exceeds the multi-word cap")
                except repat.Unsupported as exc:
                    raise LowerError(f"regex outside device subset: {exc}")
                except Exception:
                    return LErr()  # invalid regex raises EvalError in interp
                self._charge_scan_bits(total)
                leaf = self.reg.add(
                    NfaPred(field=recv.field, kind="regex", pattern=arg.value))
                return LBool(BLeaf(leaf))
            if isinstance(recv, LErr) or isinstance(arg, LErr):
                return LErr()
            if not isinstance(recv, (LStrField, LStrLit)):
                return LErr()
            raise LowerError("matches with dynamic pattern")

        return LErr()  # unknown function raises in interp

    def _lower_length(self, recv: object) -> object:
        if isinstance(recv, LStrField):
            return LNum(NLen(recv.field))
        if isinstance(recv, LErr):
            return LErr()
        if isinstance(recv, LList):
            return LNum(NConst(len(recv.values)))
        if isinstance(recv, LStrLit):
            # Char count == byte count under the latin-1 canonical view
            # (expr/interp.py _length).
            return LNum(NConst(len(recv.value)))
        return LErr()  # length() of num/bool/ip raises in interp

    def _lower_contains(self, recv: object, arg: object) -> object:
        if isinstance(recv, LErr) or isinstance(arg, LErr):
            return LErr()
        if isinstance(recv, LStrField):
            if isinstance(arg, LStrLit):
                lit = _lit_bytes(arg.value)
                if lit is None:
                    return LBool(BConst(False))  # >0xFF chars never match
                from .nfa import MAX_SCAN_BITS

                if len(lit) + 2 > MAX_SCAN_BITS:  # guard + positions + sticky
                    raise LowerError("contains literal too long for NFA span")
                self._charge_scan_bits(len(lit) + 2)
                leaf = self.reg.add(
                    NfaPred(field=recv.field, kind="contains", pattern=arg.value))
                return LBool(BLeaf(leaf))
            if isinstance(arg, (LNum, LBool, LIp, LList)):
                return LErr()  # String.contains(non-string) raises
            raise LowerError("contains with dynamic argument")
        if isinstance(recv, LList):
            return self._lower_list_contains(recv, arg)
        if isinstance(recv, (LNum, LBool, LIp)):
            return LErr()  # contains() on non-string/array raises
        raise LowerError("contains on dynamic receiver")

    def _lower_list_contains(self, recv: LList, arg: object) -> object:
        has_ip = recv.elem == "Ip" or any(isinstance(v, Ip) for v in recv.values)
        if isinstance(arg, LIp):
            # CIDR-aware membership (interp _contains: any ip item or ip
            # arg -> items converted lazily via _as_ip). The interpreter's
            # any() short-circuits: entries BEFORE the first bad one can
            # still produce True; reaching the bad entry raises. Model
            # that as (prefix-list hit) || <error>.
            entries = []
            bad_tail = False
            for item in recv.values:
                if isinstance(item, Ip):
                    entries.append(str(item))
                    continue
                if isinstance(item, str):
                    try:
                        entries.append(str(Ip(item)))
                        continue
                    except EvalError:
                        pass
                bad_tail = True
                break
            if bad_tail and not entries:
                return LErr()
            leaf = self.reg.add(IpListPred(entries=tuple(entries)))
            ir: object = BLeaf(leaf)
            if bad_tail:
                ir = BOr(ir, BErrConst())
            return LBool(ir)
        if has_ip:
            # Ip list with non-ip arg: interp converts arg via _as_ip —
            # LStrLit handled by folding; anything else errs or is host.
            if isinstance(arg, (LNum, LBool)):
                return LErr()
            raise LowerError("ip list with dynamic non-ip argument")
        if recv.elem == "Int":
            if isinstance(arg, LNum):
                leaf = self.reg.add(
                    IntListPred(values=tuple(recv.values), probe=arg.ir))
                return LBool(BLeaf(leaf))
            if isinstance(arg, (LBool, LStrLit, LStrField)):
                # equality across types never matches, never errors
                # (interp _contains swallows per-item EvalError).
                return LBool(BConst(False))
            raise LowerError("int list with unsupported argument")
        if recv.elem == "String":
            if isinstance(arg, LStrField):
                if len(recv.values) > MAX_INLINE_STR_LIST:
                    raise LowerError("string list too large for device eq table")
                # Entries with >0xFF chars can never equal a byte field.
                entries = tuple(
                    b for b in (_lit_bytes(v) for v in recv.values) if b is not None
                )
                leaf = self.reg.add(StrListPred(field=arg.field, entries=entries))
                return LBool(BLeaf(leaf))
            if isinstance(arg, (LNum, LBool)):
                return LBool(BConst(False))
            raise LowerError("string list with unsupported argument")
        if not recv.values:
            if isinstance(arg, (LNum, LStrField, LStrLit, LBool)):
                return LBool(BConst(False))
            raise LowerError("empty list with unsupported argument")
        raise LowerError("mixed-type list")

    # -- operators -----------------------------------------------------------

    def _lower_unary(self, node: ast.Unary) -> object:
        val = self.lower(node.operand)
        if node.op == "!":
            if isinstance(val, LBool):
                return LBool(BNot(val.ir))
            if isinstance(val, LErr):
                return LErr()
            return LErr()  # !non-bool raises
        if node.op == "-":
            if isinstance(val, LNum):
                return LNum(NNeg(val.ir))
            if isinstance(val, LErr):
                return LErr()
            return LErr()
        raise LowerError(f"unary {node.op}")

    def _lower_logical(self, node: ast.Logical) -> object:
        left = self.lower(node.left)
        right = self.lower(node.right)
        lb = self._operand_bool(left)
        rb = self._operand_bool(right)
        if node.op == "&&":
            return LBool(BAnd(lb, rb))
        return LBool(BOr(lb, rb))

    def _operand_bool(self, val: object) -> object:
        """Logical operand: non-bool operands error at runtime (interp
        _logical), which the err lane models as a constant error."""
        if isinstance(val, LBool):
            return val.ir
        return BErrConst()

    def _lower_binary(self, node: ast.Binary) -> object:
        op = node.op
        left = self.lower(node.left)
        right = self.lower(node.right)
        if op in ("==", "!="):
            return self._lower_eq(op, left, right)
        if op in ("<", "<=", ">", ">="):
            if isinstance(left, LNum) and isinstance(right, LNum):
                leaf = self.reg.add(NumCmp(op=op, left=left.ir, right=right.ir))
                return LBool(BLeaf(leaf))
            if isinstance(left, (LStrField, LStrLit)) and isinstance(
                    right, (LStrField, LStrLit)):
                raise LowerError("string ordering is host-evaluated")
            return LErr()  # cross-type ordering raises
        # arithmetic
        if isinstance(left, LNum) and isinstance(right, LNum):
            return LNum(NBin(op=op, left=left.ir, right=right.ir))
        if isinstance(left, LErr) or isinstance(right, LErr):
            return LErr()
        if isinstance(left, (LStrField, LStrLit)) and isinstance(
                right, (LStrField, LStrLit)) and op == "+":
            raise LowerError("string concatenation is host-evaluated")
        return LErr()  # type errors raise

    def _lower_eq(self, op: str, left: object, right: object) -> object:
        negate = op == "!="
        # Normalize literal-on-left.
        if isinstance(left, (LStrLit, LNum)) and isinstance(
                right, (LStrField, LIp)):
            left, right = right, left

        if isinstance(left, LErr) or isinstance(right, LErr):
            return LErr()
        if isinstance(left, LStrField) and isinstance(right, LStrLit):
            pat = _lit_bytes(right.value)
            if pat is None:
                return LBool(BConst(negate))  # >0xFF chars never equal a field
            leaf = self.reg.add(StrPred(kind="eq", field=left.field, pattern=pat))
            ir: object = BLeaf(leaf)
            return LBool(BNot(ir) if negate else ir)
        if isinstance(left, LIp) and isinstance(right, LStrLit):
            try:
                ip = Ip(right.value)
            except EvalError:
                return LErr()  # bad ip text raises at runtime
            if ip.is_network:
                # Interp equality is strict: an address never equals a
                # network value (expr/values.py Ip.__eq__) — containment
                # is spelled contains(), not ==.
                return LBool(BConst(negate))
            from ..ops.cidr import ip_to_words  # local import to avoid cycle

            words, prefix = ip_to_words(ip)
            leaf = self.reg.add(IpPred(words=tuple(int(w) for w in words),
                                       prefix=prefix))
            ir = BLeaf(leaf)
            return LBool(BNot(ir) if negate else ir)
        if isinstance(left, LNum) and isinstance(right, LNum):
            leaf = self.reg.add(NumCmp(op=op, left=left.ir, right=right.ir))
            return LBool(BLeaf(leaf))
        if isinstance(left, LBool) and isinstance(right, LBool):
            return LBool(BEqBool(left=left.ir, right=right.ir, negate=negate))
        if isinstance(left, LStrField) and isinstance(right, LStrField):
            raise LowerError("field-to-field comparison is host-evaluated")
        if isinstance(left, LIp) and isinstance(right, LIp):
            raise LowerError("ip-to-ip comparison is host-evaluated")
        # Cross-type equality raises in the interpreter.
        return LErr()
