"""Rule compiler: AST -> predicate IR -> device tables (TPU lowering).

Submodules import lazily: ops/ modules import compiler.nfa at module
scope, so eagerly importing plan here (which imports ops back) would
cycle.
"""

from .lowering import DEFAULT_FIELD_SPECS, LowerError

__all__ = [
    "DEFAULT_FIELD_SPECS",
    "LowerError",
    "RulesetPlan",
    "compile_ruleset",
]


def __getattr__(name):
    if name in ("RulesetPlan", "compile_ruleset"):
        from . import plan

        return getattr(plan, name)
    raise AttributeError(name)
