"""Rule compiler: AST -> predicate IR -> device tables (TPU lowering)."""

from .lowering import DEFAULT_FIELD_SPECS, LowerError
from .plan import RulesetPlan, compile_ruleset

__all__ = [
    "DEFAULT_FIELD_SPECS",
    "LowerError",
    "RulesetPlan",
    "compile_ruleset",
]
