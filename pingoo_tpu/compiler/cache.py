"""Compiled-ruleset artifact cache.

The reference's persistent state is all auto-managed files re-read at
boot (SURVEY.md §5 checkpoint/resume). The TPU equivalent called for
there: a compiled-ruleset artifact cache — ruleset hash -> lowered plan
(device tables + predicate bindings + boolean IR) — so a restart skips
recompilation of large rulesets (regex parsing, NFA packing, bitset
construction for 1M-entry lists).

Artifacts are pickles of the RulesetPlan's numpy/static state keyed by a
fingerprint of (rule sources, actions, list contents, format version).
The cache directory is private to the server (like /etc/pingoo's
auto-managed files); artifacts are only ever loaded if their fingerprint
matches, so a stale or foreign file is simply ignored.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

from ..config.schema import RuleConfig
from ..expr.values import Ip
from .plan import RulesetPlan, compile_ruleset, split_config_token

FORMAT_VERSION = 11  # bump when plan/table layout changes
# v8: scan_plans (per-bank strategy selection, halo partition sub-banks)
# v9: PrefilterPlan + pf_<field> factor tables (literal-prefilter cascade)
# v10: bitsplit-DFA lowering — dfa_<field> DfaTables, NfaScanPlan
#      dfa_key/dfa_strategy/dfa_auto, RulesetPlan.dfa_default_mode
# v11: compact staging — RulesetPlan.staging_required/staging_caps


def ruleset_fingerprint(rules: list[RuleConfig], lists: dict,
                        field_specs=None, routes=None,
                        tenant: str = "") -> str:
    from .lowering import DEFAULT_FIELD_SPECS

    h = hashlib.sha256()
    h.update(str(FORMAT_VERSION).encode())
    if tenant:
        # Multi-tenant hot-swap (ISSUE 11): identical rulesets under
        # different tenants stay distinct artifacts, so one tenant's
        # tuned plan (update_cached_plan) never leaks into another's.
        # Empty tenant hashes nothing — pre-tenant artifacts stay valid.
        h.update(b"\x04tenant:" + tenant.encode() + b"\x05")
    # Plan-shaping env knobs (halo partition on/off + footprint budget)
    # change the np_tables layout, so they are part of the identity.
    h.update(split_config_token().encode())
    h.update(repr(sorted((field_specs or DEFAULT_FIELD_SPECS).items())).encode())
    for rule in rules:
        h.update(rule.name.encode())
        h.update((rule.expression.source if rule.expression else "").encode())
        h.update(",".join(a.value for a in rule.actions).encode())
        h.update(b"\x00")
    for name, program in routes or []:
        h.update(b"\x02" + name.encode() + b"\x03")
        h.update((program.source if program else "").encode())
        h.update(b"\x00")
    for name in sorted(lists):
        h.update(name.encode())
        for item in lists[name]:
            if isinstance(item, Ip):
                h.update(str(item).encode())
            else:
                h.update(repr(item).encode())
            h.update(b"\x01")
    return h.hexdigest()


def compile_ruleset_cached(
    rules: list[RuleConfig],
    lists: dict,
    cache_dir: Optional[str] = None,
    field_specs=None,
    routes=None,
    tenant: str = "",
) -> RulesetPlan:
    """compile_ruleset with a transparent on-disk artifact cache."""
    if cache_dir is None:
        return compile_ruleset(rules, lists, field_specs, routes=routes)
    fingerprint = ruleset_fingerprint(rules, lists, field_specs,
                                      routes=routes, tenant=tenant)
    path = os.path.join(cache_dir, f"ruleset-{fingerprint[:32]}.plan")
    plan = _load(path, fingerprint)
    if plan is not None:
        return plan
    plan = compile_ruleset(rules, lists, field_specs, routes=routes)
    _save(path, fingerprint, plan)
    return plan


def update_cached_plan(
    rules: list[RuleConfig],
    lists: dict,
    plan: RulesetPlan,
    cache_dir: str,
    field_specs=None,
    routes=None,
    tenant: str = "",
) -> str:
    """Re-persist a (mutated) plan under its ruleset fingerprint — the
    path bench.py's micro-autotune uses to record measured scan-strategy
    selections (plan.scan_plans) into the artifact cache so the next
    boot starts from the tuned choice. Returns the artifact path."""
    fingerprint = ruleset_fingerprint(rules, lists, field_specs,
                                      routes=routes, tenant=tenant)
    path = os.path.join(cache_dir, f"ruleset-{fingerprint[:32]}.plan")
    _save(path, fingerprint, plan)
    return path


def _save(path: str, fingerprint: str, plan: RulesetPlan) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"fingerprint": fingerprint, "plan": plan}, f,
                        protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)  # atomic install (acme.rs-style persistence)
    except (OSError, pickle.PicklingError):
        pass  # cache is best-effort


def _load(path: str, fingerprint: str) -> Optional[RulesetPlan]:
    try:
        with open(path, "rb") as f:
            doc = pickle.load(f)
        if doc.get("fingerprint") != fingerprint:
            return None
        plan = doc.get("plan")
        return plan if isinstance(plan, RulesetPlan) else None
    except Exception:
        return None
