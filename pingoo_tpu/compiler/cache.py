"""Compiled-ruleset artifact cache.

The reference's persistent state is all auto-managed files re-read at
boot (SURVEY.md §5 checkpoint/resume). The TPU equivalent called for
there: a compiled-ruleset artifact cache — ruleset hash -> lowered plan
(device tables + predicate bindings + boolean IR) — so a restart skips
recompilation of large rulesets (regex parsing, NFA packing, bitset
construction for 1M-entry lists).

Artifacts are pickles of the RulesetPlan's numpy/static state keyed by a
fingerprint of (rule sources, actions, list contents, format version).
The cache directory is private to the server (like /etc/pingoo's
auto-managed files); artifacts are only ever loaded if their fingerprint
matches, so a stale or foreign file is simply ignored.

Since v12 every artifact also carries a `plan_proof` block — the
discharged soundness obligations from compiler/obligations.py, digest-
sealed against tampering. A cache hit with a valid proof is also a
proof hit (no re-prove at boot); a missing/tampered/failed block forces
a re-prove of the loaded plan, and a plan that fails its obligations is
REFUSED at compile time (ObligationError) rather than cached or served.
Set PINGOO_PROVE=off to skip proving (e.g. while bisecting a prover
regression); refusal semantics only apply when proving runs.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

from ..config.schema import RuleConfig
from ..expr.values import Ip
from .obligations import PlanProof, proof_block_valid, prove_plan, require
from .plan import RulesetPlan, compile_ruleset, split_config_token

FORMAT_VERSION = 12  # bump when plan/table layout changes
# v8: scan_plans (per-bank strategy selection, halo partition sub-banks)
# v9: PrefilterPlan + pf_<field> factor tables (literal-prefilter cascade)
# v10: bitsplit-DFA lowering — dfa_<field> DfaTables, NfaScanPlan
#      dfa_key/dfa_strategy/dfa_auto, RulesetPlan.dfa_default_mode
# v11: compact staging — RulesetPlan.staging_required/staging_caps
# v12: plan_proof block — discharged obligation ledger rides the artifact


def _prove_enabled() -> bool:
    return os.environ.get("PINGOO_PROVE", "on").lower() not in (
        "off", "0", "no", "false")


def ruleset_fingerprint(rules: list[RuleConfig], lists: dict,
                        field_specs=None, routes=None,
                        tenant: str = "") -> str:
    from .lowering import DEFAULT_FIELD_SPECS

    h = hashlib.sha256()
    h.update(str(FORMAT_VERSION).encode())
    if tenant:
        # Multi-tenant hot-swap (ISSUE 11): identical rulesets under
        # different tenants stay distinct artifacts, so one tenant's
        # tuned plan (update_cached_plan) never leaks into another's.
        # Empty tenant hashes nothing — pre-tenant artifacts stay valid.
        h.update(b"\x04tenant:" + tenant.encode() + b"\x05")
    # Plan-shaping env knobs (halo partition on/off + footprint budget)
    # change the np_tables layout, so they are part of the identity.
    h.update(split_config_token().encode())
    h.update(repr(sorted((field_specs or DEFAULT_FIELD_SPECS).items())).encode())
    for rule in rules:
        h.update(rule.name.encode())
        h.update((rule.expression.source if rule.expression else "").encode())
        h.update(",".join(a.value for a in rule.actions).encode())
        h.update(b"\x00")
    for name, program in routes or []:
        h.update(b"\x02" + name.encode() + b"\x03")
        h.update((program.source if program else "").encode())
        h.update(b"\x00")
    for name in sorted(lists):
        h.update(name.encode())
        for item in lists[name]:
            if isinstance(item, Ip):
                h.update(str(item).encode())
            else:
                h.update(repr(item).encode())
            h.update(b"\x01")
    return h.hexdigest()


def compile_ruleset_cached(
    rules: list[RuleConfig],
    lists: dict,
    cache_dir: Optional[str] = None,
    field_specs=None,
    routes=None,
    tenant: str = "",
) -> RulesetPlan:
    """compile_ruleset with a transparent on-disk artifact cache.

    The cached path is also the PROVED path: a fresh compile discharges
    the soundness obligations before the artifact is written (a failure
    raises ObligationError), and a hit re-proves only when the stored
    plan_proof block is missing or fails its digest/fingerprint check.
    """
    if cache_dir is None:
        return compile_ruleset(rules, lists, field_specs, routes=routes)
    fingerprint = ruleset_fingerprint(rules, lists, field_specs,
                                      routes=routes, tenant=tenant)
    path = os.path.join(cache_dir, f"ruleset-{fingerprint[:32]}.plan")
    plan, proof_block = _load(path, fingerprint)
    if plan is not None:
        if _prove_enabled() and not proof_block_valid(proof_block,
                                                      fingerprint):
            # tampered/absent proof: re-prove the loaded plan in place
            # (same plan -> same verdict as a fresh compile would get).
            proof = require(prove_plan(plan, fingerprint))
            _save(path, fingerprint, plan, proof)
        return plan
    plan = compile_ruleset(rules, lists, field_specs, routes=routes)
    proof = None
    if _prove_enabled():
        proof = require(prove_plan(plan, fingerprint))
    _save(path, fingerprint, plan, proof)
    return plan


def update_cached_plan(
    rules: list[RuleConfig],
    lists: dict,
    plan: RulesetPlan,
    cache_dir: str,
    field_specs=None,
    routes=None,
    tenant: str = "",
) -> str:
    """Re-persist a (mutated) plan under its ruleset fingerprint — the
    path bench.py's micro-autotune uses to record measured scan-strategy
    selections (plan.scan_plans) into the artifact cache so the next
    boot starts from the tuned choice. Returns the artifact path."""
    fingerprint = ruleset_fingerprint(rules, lists, field_specs,
                                      routes=routes, tenant=tenant)
    path = os.path.join(cache_dir, f"ruleset-{fingerprint[:32]}.plan")
    proof = None
    if _prove_enabled():
        # tuned plans re-prove before re-persisting: the autotuner only
        # mutates scan strategies, but the artifact contract is that a
        # stored proof always covers the stored plan.
        proof = require(prove_plan(plan, fingerprint))
    _save(path, fingerprint, plan, proof)
    return path


def _save(path: str, fingerprint: str, plan: RulesetPlan,
          proof: Optional[PlanProof] = None) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        doc = {"fingerprint": fingerprint, "plan": plan}
        if proof is not None:
            doc["plan_proof"] = proof.to_dict()
        with open(tmp, "wb") as f:
            pickle.dump(doc, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)  # atomic install (acme.rs-style persistence)
    except (OSError, pickle.PicklingError):
        pass  # cache is best-effort


def _load(path: str,
          fingerprint: str) -> tuple[Optional[RulesetPlan], Optional[dict]]:
    try:
        with open(path, "rb") as f:
            doc = pickle.load(f)
        if doc.get("fingerprint") != fingerprint:
            return None, None
        plan = doc.get("plan")
        if not isinstance(plan, RulesetPlan):
            return None, None
        return plan, doc.get("plan_proof")
    except Exception:
        return None, None
