"""Machine-checked lowering obligations (ISSUE 18, docs/STATIC_ANALYSIS.md).

Every throughput lever the compiler pulls — the Stage-A necessary-factor
prefilter, the (possibly approximate) bitsplit-DFA lowering, the compact
staging caps, the footprint-extension rewrite, the streaming body
scanner's cross-window carry — is only sound under a side condition that
used to live in prose and sampled runtime parity checks.  This module
turns each side condition into a compile-time proof obligation over the
LOWERED artifacts (the tables that actually ship, not a re-derivation of
them) and serializes the discharged obligations as a `plan_proof` block
that rides the artifact cache (compiler/cache.py, FORMAT_VERSION >= 12):
a cache hit is also a proof hit, and a failed obligation refuses the
plan at compile time instead of waiting for ParityAuditor sampling to
catch a bad lowering live.

Obligation catalog (names are stable; docs/STATIC_ANALYSIS.md):

  bank-reconstruction   the leaf bindings' slot spans tile each bank, so
                        the per-slot source patterns are recoverable
                        deterministically (everything below keys off it)
  prefilter-necessity   per factor-gated slot: EVERY accepting run of
                        the source pattern's position NFA completes the
                        assigned factor (product reachability over
                        (position, shift-AND factor state)); PF_NEVER
                        slots are dead in the position NFA
  prefilter-consistency codes in range, bank_masks/bank_gated agree with
                        the codes, halo sub-bank codes agree with the
                        slot permutation
  dfa-containment       the lowered DFA tables over-approximate the
                        position NFA: a union-mask product fixpoint over
                        the SHIPPED transition table proves every
                        co-reachable NFA fire/end slot is contained in
                        step_accept/end_accept (union-linearity of the
                        scan algebra makes the union mask exact)
  dfa-exactness         tables marked exact=True (the engine then skips
                        the NFA recheck) really are the exact subset
                        construction: single-valued subset masks per
                        state and fire/end EQUALITY
  staging-caps          per-field dependent byte depth recomputed by an
                        independent walker over the leaf/host IR matches
                        plan.staging_required, and the quantized caps
                        bound it
  footprint-extension   extended banks: the stored tables equal the
                        rebuild from a structurally certified rewrite
                        (each unbounded rep replaced by exactly
                        max(field_cap - min_len, 0) optionals of the
                        same byte class; everything else untouched)
  body-*                body-plan obligations (prove_body_plan): tables/
                        footprint reconstruction, lazy-gate implications,
                        factor necessity, DFA exactness, and the
                        torn-literal carry closure — every seam position
                        through every match literal, chunked scan with
                        carried state == contiguous scan
                        (compiler/nfa.scan_chunk_numpy)

The checkers are deliberately *independent* implementations: they share
the position-NFA construction with the compiler (slot alignment must be
bit-exact) but never reuse the lowering's own reasoning — the prefilter
check is product reachability where the compiler reasons about factor
windows; the staging check is a fresh IR walker; the DFA check reads the
shipped int32 tables.  `tools/analyze/prove.py` carries mutation tests
proving each checker actually bites.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

from . import repat
from .nfa import (NfaBank, _bank_position_nfa, _bits, _expand_scan_patterns,
                  build_bank, extract_numpy, pattern_footprint,
                  scan_chunk_numpy, scan_numpy)
from .plan import (PF_ALWAYS, PF_NEVER, RulesetPlan, STAGING_RUNGS,
                   quantize_stage_cap)
from .repat import LinearPattern, Pos, Quant

PROOF_FORMAT = 1

# Safety valve for the product reachability checks: a pathological
# pattern x factor pair could blow up the explored state count; past the
# cap the obligation records `skipped` (NOT proved — the detail says
# why) instead of stalling compilation.  No current ruleset comes close.
PRODUCT_STATE_CAP = 500_000


# ---------------------------------------------------------------------------
# proof records


@dataclass
class Obligation:
    """One discharged (or failed / skipped) proof obligation."""

    name: str
    subject: str
    status: str  # 'proved' | 'failed' | 'skipped'
    detail: str = ""

    def to_dict(self) -> dict:
        return {"name": self.name, "subject": self.subject,
                "status": self.status, "detail": self.detail}


@dataclass
class PlanProof:
    """The full obligation ledger for one compiled plan."""

    fingerprint: str = ""
    obligations: list[Obligation] = dc_field(default_factory=list)
    wall_s: float = 0.0
    format: int = PROOF_FORMAT

    @property
    def ok(self) -> bool:
        return all(o.status != "failed" for o in self.obligations)

    def failures(self) -> list[Obligation]:
        return [o for o in self.obligations if o.status == "failed"]

    def counts(self) -> dict[str, int]:
        out = {"proved": 0, "failed": 0, "skipped": 0}
        for o in self.obligations:
            out[o.status] = out.get(o.status, 0) + 1
        return out

    def to_dict(self) -> dict:
        body = {
            "format": self.format,
            "fingerprint": self.fingerprint,
            "ok": self.ok,
            "obligations": [o.to_dict() for o in self.obligations],
            "wall_s": round(self.wall_s, 6),
        }
        body["digest"] = proof_digest(body)
        return body

    @classmethod
    def from_dict(cls, d: dict) -> "PlanProof":
        obs = [Obligation(**o) for o in d.get("obligations", ())]
        return cls(fingerprint=d.get("fingerprint", ""), obligations=obs,
                   wall_s=float(d.get("wall_s", 0.0)),
                   format=int(d.get("format", 0)))


def proof_digest(body: dict) -> str:
    """Tamper-evident digest over the canonical proof body (the cache
    loader re-derives it; a mismatch forces a re-prove)."""
    canon = {k: v for k, v in body.items() if k not in ("digest", "wall_s")}
    blob = json.dumps(canon, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def proof_block_valid(block: Any, fingerprint: str) -> bool:
    """Is a deserialized plan_proof block a usable proof for
    `fingerprint`?  (format + fingerprint + ok + digest must all hold.)"""
    if not isinstance(block, dict):
        return False
    if block.get("format") != PROOF_FORMAT:
        return False
    if fingerprint and block.get("fingerprint") != fingerprint:
        return False
    if not block.get("ok"):
        return False
    try:
        return proof_digest(block) == block.get("digest")
    except Exception:
        return False


class ObligationError(RuntimeError):
    """A compiled plan failed a soundness obligation; the plan is
    refused (never cached, never served)."""

    def __init__(self, proof: PlanProof):
        self.proof = proof
        lines = [f"{o.name}[{o.subject}]: {o.detail}"
                 for o in proof.failures()]
        super().__init__(
            "plan refused — %d failed obligation(s):\n  %s"
            % (len(lines), "\n  ".join(lines)))


# ---------------------------------------------------------------------------
# pattern reconstruction


def bank_source_patterns(plan: RulesetPlan) -> tuple[dict, list]:
    """np_tables bank key -> per-slot source LinearPatterns.

    Plans do not store the compiled LinearPatterns; they are recovered
    by replaying the deterministic leaf -> alternatives lowering
    (compiler/lowering.nfa_leaf_patterns) in leaf order and checking
    each binding's span tiles its bank exactly.  NFA banks keep every
    alternative (never_match included — slot indices must line up);
    window banks keep the live alternatives only, mirroring the
    assembler's win_srcs."""
    from .lowering import nfa_leaf_patterns

    banks: dict[str, list] = {}
    failures: list[Obligation] = []
    for leaf_id in sorted(plan.bindings):
        b = plan.bindings[leaf_id]
        if b.kind not in ("nfa", "window"):
            continue
        alts = nfa_leaf_patterns(plan.leaves[leaf_id])
        if b.kind == "window":
            alts = [lp for lp in alts if not lp.never_match]
        slots = banks.setdefault(b.table_key, [])
        if b.span != (len(slots), len(slots) + len(alts)):
            failures.append(Obligation(
                "bank-reconstruction", b.table_key, "failed",
                f"leaf {leaf_id} span {b.span} != replayed "
                f"({len(slots)}, {len(slots) + len(alts)})"))
            slots.extend(alts)  # keep going; later slots stay aligned
        else:
            slots.extend(alts)
    return banks, failures


# ---------------------------------------------------------------------------
# obligation: prefilter necessity


def _factor_byte_masks(factor: tuple) -> list[int]:
    """fm[b] = bitmask of factor positions byte b can occupy."""
    fm = [0] * 256
    for i, cls in enumerate(factor):
        bit = 1 << i
        for byte in cls:
            fm[byte] |= bit
    return fm


def check_factor_necessity(lp: LinearPattern,
                           factor: tuple | None) -> str | None:
    """Prove `lp matches a field  =>  factor occurs in the field`.

    Explores the product of the pattern's expanded position NFA (one
    accepting run = one sequence of consumed positions) with the
    factor's shift-AND matcher: a reachable accepting event whose factor
    state never completed is a counterexample.  With factor=None the
    claim is `lp` has no accepting run at all (PF_NEVER).

    Sound because anchors/boundaries are pre-compiled into consumed
    positions by _expand_scan_patterns and the consumed word is a
    substring of the field, so a factor completed inside the run occurs
    in the field.  Returns None (proved), a counterexample description,
    or the literal "<capped>" when the product exceeded
    PRODUCT_STATE_CAP.
    """
    if factor is not None and lp.min_len == 0:
        return "pattern admits an empty match; no factor can be necessary"
    subs = _expand_scan_patterns(lp) if not lp.never_match else []
    if factor is None and lp.never_match:
        return None  # parser-proved dead; the bank check covers lowering
    fm = _factor_byte_masks(factor) if factor is not None else None
    done_bit = 1 << (len(factor) - 1) if factor is not None else 0

    for si, sub in enumerate(subs):
        positions = sub.positions
        n = len(positions)
        skippable = [p.quant in (Quant.OPT, Quant.STAR) for p in positions]
        repeat = [p.quant in (Quant.STAR, Quant.PLUS) for p in positions]

        def closure(start: int) -> tuple[int, bool]:
            mask, i = 0, start
            while i < n:
                mask |= 1 << i
                if not skippable[i]:
                    return mask, False
                i += 1
            return mask, sub.sticky

        succ, fire = [], []
        for i in range(n):
            smask, sfire = closure(i + 1)
            if repeat[i]:
                smask |= 1 << i
            succ.append(smask)
            fire.append(sfire)
        start_mask, start_fire = closure(0)
        if start_fire or (n == 0 and sub.accept):
            return (f"alternative {si} accepts with zero consumed bytes"
                    if factor is not None else
                    f"alternative {si} has a zero-byte accepting run")

        if factor is None:
            seen: set = set(_bits(start_mask))
            stack = list(seen)
            while stack:
                q = stack.pop()
                if fire[q] or q in sub.accept:
                    return f"alternative {si} has an accepting run (pos {q})"
                for q2 in _bits(succ[q]):
                    if q2 not in seen:
                        seen.add(q2)
                        stack.append(q2)
            continue

        # product BFS: (position just consumed, factor progress bits).
        # A state whose factor already completed is pruned — no
        # violation can grow out of it.  Bytes within a position's class
        # are deduped by their factor-mask behavior.
        states: set = set()
        stack2 = []
        for q in _bits(start_mask):
            for fmb in {fm[b] for b in positions[q].bytes}:
                f = 1 & fmb
                if f & done_bit:
                    continue
                if (q, f) not in states:
                    states.add((q, f))
                    stack2.append((q, f))
        while stack2:
            q, f = stack2.pop()
            if fire[q] or q in sub.accept:
                return (f"alternative {si}: accepting run reaches pos {q} "
                        f"with factor incomplete (progress {f:#x})")
            for q2 in _bits(succ[q]):
                for fmb in {fm[b] for b in positions[q2].bytes}:
                    f2 = ((f << 1) | 1) & fmb
                    if f2 & done_bit:
                        continue
                    st = (q2, f2)
                    if st not in states:
                        states.add(st)
                        stack2.append(st)
                        if len(states) > PRODUCT_STATE_CAP:
                            return "<capped>"
    return None


def _invert_slot_perm(perm: tuple) -> list[int]:
    order = [0] * len(perm)
    for p, col in enumerate(perm):
        order[col] = p
    return order


def check_prefilter(plan: RulesetPlan, banks: dict) -> list[Obligation]:
    """Discharge prefilter-necessity + prefilter-consistency for every
    bank registered in plan.prefilter.slot_codes."""
    out: list[Obligation] = []
    pf = plan.prefilter
    if pf is None or not pf.slot_codes:
        return out

    # Halo sub-banks carry the same codes as their parent bank filtered
    # through the slot permutation; necessity is proved once on the
    # parent and the sub-bank codes are checked for consistency.
    sub_parent: dict[str, tuple[str, int]] = {}
    for key, entry in plan.scan_plans.items():
        if entry.split and entry.slot_perm is not None:
            sub_parent[entry.split[0]] = (key, 0)
            sub_parent[entry.split[1]] = (key, 1)

    for key, codes in sorted(pf.slot_codes.items()):
        field = pf.bank_field.get(key, "")
        ff = pf.fields.get(field)
        if key in sub_parent:
            parent, which = sub_parent[key]
            entry = plan.scan_plans[parent]
            order = _invert_slot_perm(entry.slot_perm)
            n_short = len(pf.slot_codes.get(entry.split[0], ()))
            idx = order[:n_short] if which == 0 else order[n_short:]
            parent_codes = pf.slot_codes.get(parent, ())
            want = tuple(parent_codes[i] for i in idx)
            if tuple(codes) != want:
                out.append(Obligation(
                    "prefilter-consistency", key, "failed",
                    "sub-bank codes disagree with parent through slot_perm"))
            else:
                out.append(Obligation(
                    "prefilter-consistency", key, "proved",
                    f"{len(codes)} slot codes == parent[{parent}] via perm"))
            continue

        patterns = banks.get(key)
        if patterns is None or len(patterns) != len(codes):
            out.append(Obligation(
                "prefilter-necessity", key, "failed",
                f"bank has {len(codes)} codes but "
                f"{'no' if patterns is None else len(patterns)} "
                "reconstructed slots"))
            continue
        if ff is None:
            out.append(Obligation(
                "prefilter-consistency", key, "failed",
                f"no factor inventory for field {field!r}"))
            continue

        nfa_dead = None  # lazily built position NFA for PF_NEVER checks
        proved = capped = 0
        bad: list[str] = []
        for p, code in enumerate(codes):
            lp = patterns[p]
            if code == PF_ALWAYS:
                continue
            if code == PF_NEVER:
                if nfa_dead is None:
                    nfa_dead = _bank_position_nfa(patterns)
                nfa, slot_always, slot_empty_ok = nfa_dead
                bit = 1 << p
                live = (slot_always[p] or slot_empty_ok[p]
                        or (nfa.fire_u | nfa.fire_a) & bit
                        or any((f | e) & bit
                               for f, e in zip(nfa.fire, nfa.end)))
                if live:
                    bad.append(f"slot {p}: PF_NEVER but live in the "
                               "position NFA")
                else:
                    proved += 1
                continue
            if not 0 <= code < len(ff.factors):
                bad.append(f"slot {p}: factor code {code} out of range")
                continue
            note = check_factor_necessity(lp, ff.factors[code])
            if note == "<capped>":
                capped += 1
            elif note is not None:
                bad.append(f"slot {p} (factor {code}): {note}")
            else:
                proved += 1
        if bad:
            out.append(Obligation("prefilter-necessity", key, "failed",
                                  "; ".join(bad[:4])))
        else:
            status = "skipped" if capped else "proved"
            out.append(Obligation(
                "prefilter-necessity", key, status,
                f"{proved} gated slot(s) proved"
                + (f", {capped} capped" if capped else "")))

        # consistency: gating flag + factor mask agree with the codes.
        problems = []
        want_gated = all(c != PF_ALWAYS for c in codes)
        if bool(pf.bank_gated.get(key)) != want_gated:
            problems.append(
                f"bank_gated={pf.bank_gated.get(key)} but codes say "
                f"{want_gated}")
        mask = pf.bank_masks.get(key)
        if mask is not None:
            got = np.asarray(mask).astype(bool)
            want = np.zeros(ff.num_factors, dtype=bool)
            for c in codes:
                if c >= 0:
                    want[c] = True
            if got.shape != want.shape or not np.array_equal(got, want):
                problems.append("bank_masks disagrees with slot codes")
        out.append(Obligation(
            "prefilter-consistency", key,
            "failed" if problems else "proved",
            "; ".join(problems) if problems else
            f"gated={want_gated}, factor mask consistent"))
    return out


# ---------------------------------------------------------------------------
# obligation: DFA containment / exactness


def _words_to_int(row: np.ndarray) -> int:
    v = 0
    for w, x in enumerate(row):
        v |= int(x) << (32 * w)
    return v


def check_dfa_containment(patterns: list, tables: Any) -> list[str]:
    """Prove the shipped DfaTables over-approximate (and, when marked
    exact, equal) the bank's position NFA.

    Walks a product fixpoint: R[d] = union bitmask of NFA positions
    co-reachable with DFA state d, driven by the SHIPPED int32
    transition table.  Because the scan algebra's fire/end extraction is
    union-linear in the position set, checking fire(R[d]) against
    step_accept[d] (and end(R[d]) against end_accept[d]) is exact for
    containment: no false alarms, no missed violations.  For
    exact-marked tables (the engine skips the NFA recheck for those) a
    second pass additionally requires every state's incoming subset mask
    to be single-valued and the accept lanes to be EQUAL.
    """
    nfa, slot_always, slot_empty_ok = _bank_position_nfa(patterns)
    S = int(tables.num_states)
    C = int(tables.num_classes)
    trans = np.asarray(tables.trans_flat).astype(np.int64).reshape(S, C)
    byte_cls = np.asarray(tables.byte_cls).astype(np.int64)
    step_int = [_words_to_int(r) for r in np.asarray(tables.step_accept)]
    end_int = [_words_to_int(r) for r in np.asarray(tables.end_accept)]
    fails: list[str] = []

    if not np.array_equal(np.asarray(tables.slot_always).astype(bool),
                          slot_always):
        fails.append("slot_always lane disagrees with the position NFA")
    if not np.array_equal(np.asarray(tables.slot_empty_ok).astype(bool),
                          slot_empty_ok):
        fails.append("slot_empty_ok lane disagrees with the position NFA")

    if np.any(trans == 0):
        fails.append("start state 0 is a transition target")
        return fails

    col = [0] * 256
    for q, bs in enumerate(nfa.bytes):
        for b in bs:
            col[b] |= 1 << q
    union_col = [0] * C
    for b in range(256):
        union_col[int(byte_cls[b])] |= col[b]

    def cand_of(d: int, mask: int) -> int:
        if d == 0:
            return nfa.inj_u | nfa.inj_a
        c = nfa.inj_u
        for q in _bits(mask):
            c |= nfa.succ[q]
        return c

    def fire_of(d: int, mask: int) -> int:
        if d == 0:
            return nfa.fire_u | nfa.fire_a
        f = nfa.fire_u
        for q in _bits(mask):
            f |= nfa.fire[q]
        return f

    def end_of(d: int, mask: int) -> int:
        if d == 0:
            return 0
        e = 0
        for q in _bits(mask):
            e |= nfa.end[q]
        return e

    R = [0] * S
    work = {0}
    reached = {0}
    while work:
        d = work.pop()
        cand = cand_of(d, R[d])
        row = trans[d]
        for c in range(C):
            m = cand & union_col[c]
            d2 = int(row[c])
            if not 0 < d2 < S:
                fails.append(f"transition ({d},{c}) -> {d2} out of range")
                return fails
            reached.add(d2)
            if m & ~R[d2]:
                R[d2] |= m
                work.add(d2)

    for d in range(S):
        fire = fire_of(d, R[d])
        end = end_of(d, R[d])
        if fire & ~step_int[d]:
            fails.append(
                f"state {d}: NFA fire slots {fire & ~step_int[d]:#x} "
                "missing from step_accept")
        if end & ~end_int[d]:
            fails.append(
                f"state {d}: NFA end slots {end & ~end_int[d]:#x} "
                "missing from end_accept")
        if len(fails) > 8:
            return fails

    if bool(getattr(tables, "exact", False)):
        # single-valuedness: every edge's subset mask must equal the
        # target's accumulated mask, else two distinct subsets merged.
        for d in range(S):
            cand = cand_of(d, R[d])
            row = trans[d]
            for c in range(C):
                m = cand & union_col[c]
                d2 = int(row[c])
                if m != R[d2]:
                    fails.append(
                        f"exact=True but state {d2} merges distinct "
                        f"subset masks (edge {d}--{c}-->)")
                    return fails
        for d in range(S):
            if fire_of(d, R[d]) != step_int[d]:
                fails.append(
                    f"exact=True but step_accept[{d}] over-fires")
                return fails
            if end_of(d, R[d]) != end_int[d]:
                fails.append(
                    f"exact=True but end_accept[{d}] over-fires")
                return fails
    return fails


def check_plan_dfas(plan: RulesetPlan, banks: dict) -> list[Obligation]:
    """Containment/exactness for every DFA lowering the plan ships."""
    out: list[Obligation] = []
    targets: list[tuple[str, str]] = []
    for key, entry in plan.scan_plans.items():
        if entry.dfa_key:
            targets.append((key, entry.dfa_key))
    for win_key, dfa_key in getattr(plan, "win_dfa", {}).items():
        targets.append((win_key, dfa_key))
    for src_key, dfa_key in sorted(targets):
        patterns = banks.get(src_key)
        tables = plan.np_tables.get(dfa_key)
        if patterns is None or tables is None:
            out.append(Obligation(
                "dfa-containment", dfa_key, "failed",
                f"missing {'patterns' if patterns is None else 'tables'} "
                f"for {src_key}"))
            continue
        fails = check_dfa_containment(patterns, tables)
        exact = bool(getattr(tables, "exact", False))
        name = "dfa-exactness" if exact else "dfa-containment"
        if fails:
            out.append(Obligation(name, dfa_key, "failed",
                                  "; ".join(fails[:4])))
        else:
            out.append(Obligation(
                name, dfa_key, "proved",
                f"{int(tables.num_states)} states x "
                f"{int(tables.num_classes)} classes vs "
                f"{len(patterns)} slots"
                + (", subset masks single-valued" if exact else "")))
    return out


# ---------------------------------------------------------------------------
# obligation: staging caps


def check_staging(plan: RulesetPlan) -> list[Obligation]:
    """Independent recompute of the per-field dependent byte depth.

    Mirrors the staging SEMANTICS (docs/EXECUTOR.md "Compact staging")
    with a fresh walker — eq |pat|+1, prefix |pat|, suffix/NFA/length()
    pin to spec, host rules pin every referenced string field — and
    diffs the result against plan.staging_required / staging_caps."""
    from .lowering import (IntListPred, NBin, NfaPred, NLen, NNeg, NumCmp,
                           StrListPred, StrPred)

    specs = plan.field_specs
    required = {f: 0 for f in specs}

    def bump(f: str, depth: int) -> None:
        # raw dependent depth, NOT clamped to the spec: staging_required
        # records what the leaves ask for; only the cap quantization
        # clamps (a raw depth past the spec pins the whole field).
        if f in required:
            required[f] = max(required[f], int(depth))

    def len_fields(ir) -> list[str]:
        found, stack = [], [ir]
        while stack:
            node = stack.pop()
            if isinstance(node, NLen):
                found.append(node.field)
            elif isinstance(node, NBin):
                stack.extend((node.left, node.right))
            elif isinstance(node, NNeg):
                stack.append(node.x)
        return found

    for leaf in plan.leaves:
        if isinstance(leaf, StrPred):
            if leaf.kind == "eq":
                bump(leaf.field, len(leaf.pattern) + 1)
            elif leaf.kind == "prefix":
                bump(leaf.field, len(leaf.pattern))
            else:
                bump(leaf.field, specs.get(leaf.field, 0))
        elif isinstance(leaf, StrListPred):
            bump(leaf.field,
                 max((len(e) for e in leaf.entries), default=0) + 1)
        elif isinstance(leaf, NfaPred):
            bump(leaf.field, specs.get(leaf.field, 0))
        elif isinstance(leaf, NumCmp):
            for f in len_fields(leaf.left) + len_fields(leaf.right):
                bump(f, specs.get(f, 0))
        elif isinstance(leaf, IntListPred):
            for f in len_fields(leaf.probe):
                bump(f, specs.get(f, 0))

    from ..expr import ast as east

    for rule in plan.rules:
        if rule.host and rule.program is not None:
            for node in east.walk(rule.program.root):
                if isinstance(node, east.Member) \
                        and isinstance(node.obj, east.Ident):
                    if node.obj.name == "http_request" \
                            and node.attr in specs:
                        bump(node.attr, specs[node.attr])
                    elif node.obj.name == "client" \
                            and node.attr == "country":
                        bump("country", specs.get("country", 0))

    out: list[Obligation] = []
    stored_req = dict(getattr(plan, "staging_required", {}) or {})
    stored_caps = dict(getattr(plan, "staging_caps", {}) or {})
    diffs = [f"{f}: stored {stored_req.get(f)} != recomputed {required[f]}"
             for f in specs
             if int(stored_req.get(f, -1)) != required[f]]
    if diffs:
        out.append(Obligation("staging-caps", "required", "failed",
                              "; ".join(diffs[:6])))
    else:
        out.append(Obligation(
            "staging-caps", "required", "proved",
            f"{len(specs)} field depths match the independent walker"))

    bad = []
    for f, spec in specs.items():
        cap = int(stored_caps.get(f, -1))
        need = required[f]
        if cap < min(need, int(spec)) or cap > int(spec):
            bad.append(f"{f}: cap {cap} outside [{min(need, spec)}, {spec}]")
        elif cap != quantize_stage_cap(need, int(spec)):
            bad.append(f"{f}: cap {cap} != quantize({need}, {spec})")
        elif cap != int(spec) and cap not in STAGING_RUNGS:
            bad.append(f"{f}: cap {cap} is not a staging rung")
    out.append(Obligation(
        "staging-caps", "caps", "failed" if bad else "proved",
        "; ".join(bad[:6]) if bad else
        "every cap bounds the recomputed depth and sits on a rung"))
    return out


# ---------------------------------------------------------------------------
# obligation: footprint extension


_TABLE_FIELDS = ("byte_table", "init_anchored", "init_unanchored", "opt",
                 "rep", "carry_mask", "sticky", "accept_word", "accept_mask",
                 "slot_always", "slot_empty_ok")


def _tables_equal(a: Any, b: Any) -> str | None:
    for name in _TABLE_FIELDS:
        if not (hasattr(a, name) and hasattr(b, name)):
            continue
        x = np.asarray(getattr(a, name))
        y = np.asarray(getattr(b, name))
        if x.shape != y.shape or not np.array_equal(x, y):
            return name
    return None


def certify_extension(orig: LinearPattern, ext: LinearPattern,
                      field_cap: int) -> str | None:
    """Structural certificate that `ext` is the sound bounded rewrite of
    `orig` over inputs of length <= field_cap: every unbounded repeat is
    replaced by exactly r = max(field_cap - min_len, 0) optionals of the
    SAME byte class (PLUS keeps its one required position), everything
    else — classes, order, anchors, boundaries — is untouched.  Any run
    in a field of <= field_cap bytes spends at most r bytes in one
    repeat, so match semantics are preserved exactly."""
    r = max(int(field_cap) - orig.min_len, 0)
    for flag in ("anchor_start", "anchor_end", "anchor_end_abs",
                 "boundary_start", "boundary_end", "never_match"):
        if getattr(orig, flag) != getattr(ext, flag):
            return f"flag {flag} changed"
    out = list(ext.positions)
    j = 0
    last_i = len(orig.positions) - 1
    for i, p in enumerate(orig.positions):
        if p.quant == Quant.STAR:
            want = [Pos(bytes=p.bytes, quant=Quant.OPT)] * r
        elif p.quant == Quant.PLUS:
            one = Pos(bytes=p.bytes, quant=Quant.ONE)
            opts = [Pos(bytes=p.bytes, quant=Quant.OPT)] * r
            want = (opts + [one]) if (i == last_i and orig.boundary_end) \
                else ([one] + opts)
        else:
            want = [p]
        got = out[j:j + len(want)]
        if got != want:
            return (f"position {i} ({p.quant.name}) rewrite mismatch "
                    f"(expected {len(want)} positions with r={r})")
        j += len(want)
    if j != len(out):
        return f"{len(out) - j} trailing positions not justified"
    if repat.has_unbounded_rep(ext):
        return "rewrite still has an unbounded repeat"
    return None


def check_footprint_extension(plan: RulesetPlan,
                              banks: dict) -> list[Obligation]:
    out: list[Obligation] = []
    for key, entry in sorted(plan.scan_plans.items()):
        if not entry.extended:
            continue
        field = key[len("nfa_"):]
        field_cap = int(plan.field_specs.get(field, 2048))
        patterns = banks.get(key)
        tables = plan.np_tables.get(key)
        if patterns is None or tables is None:
            out.append(Obligation("footprint-extension", key, "failed",
                                  "missing patterns/tables"))
            continue
        cands, note = [], None
        for p, lp in enumerate(patterns):
            cand = repat.extend_footprint(lp, field_cap) \
                if repat.has_unbounded_rep(lp) else lp
            if cand is None:
                note = f"slot {p}: extension impossible yet bank extended"
                break
            if cand is not lp:
                note = certify_extension(lp, cand, field_cap)
                if note is not None:
                    note = f"slot {p}: {note}"
                    break
            cands.append(cand)
        if note is None:
            rebuilt = build_bank(cands)
            from ..ops.nfa_scan import bank_to_tables

            ref = bank_to_tables(rebuilt)
            bad = _tables_equal(tables, ref)
            if bad is not None:
                note = f"shipped tables diverge from certified rebuild " \
                       f"({bad})"
            elif not bool(getattr(tables, "halo_ok", False)):
                note = "extended bank is not halo_ok"
        out.append(Obligation(
            "footprint-extension", key,
            "failed" if note else "proved",
            note or f"{len(patterns)} slot(s) certified at cap {field_cap}"))
    return out


# ---------------------------------------------------------------------------
# obligation: body-plan carry closure


def _witness_bytes(lp: LinearPattern) -> bytes:
    return bytes(min(p.bytes) for p in lp.positions
                 if p.quant in (Quant.ONE, Quant.PLUS))


def check_carry_closure(bank: NfaBank, patterns: list) -> list[str]:
    """Torn-literal closure: for every pattern's witness payload and
    EVERY seam position, scanning chunk1 then chunk2 with the carried
    state equals one contiguous scan (compiler/nfa.scan_chunk_numpy).
    This proves the carry ALGEBRA is seam-invariant on the shipped bank;
    device/numpy agreement is covered by the differential tests."""
    fails: list[str] = []
    for p, lp in enumerate(patterns):
        if lp.never_match or not lp.positions:
            continue
        wit = _witness_bytes(lp)
        pre = b"" if (lp.anchor_start or lp.boundary_start) else b"()"
        post = b"" if (lp.anchor_end or lp.anchor_end_abs
                       or lp.boundary_end) else b"()"
        payload = pre + wit + post
        if not payload:
            continue
        L = len(payload)
        data = np.frombuffer(payload, dtype=np.uint8)[None, :].copy()
        lengths = np.array([L], dtype=np.int32)
        ref = scan_numpy(bank, data, lengths)
        plain = (not lp.anchor_end and not lp.anchor_end_abs
                 and not lp.boundary_end and not lp.boundary_start
                 and lp.min_len > 0)
        if plain and not bool(ref[0, p]):
            fails.append(f"slot {p}: witness payload does not match "
                         "contiguously (closure check not exercised)")
            continue
        for k in range(1, L):
            S = scan_chunk_numpy(bank, data[:, :k], lengths)
            S = scan_chunk_numpy(bank, data[:, k:], lengths, S, t_offset=k)
            got = extract_numpy(bank, S, lengths)
            if not np.array_equal(got, ref):
                fails.append(
                    f"slot {p}: seam at byte {k} diverges from the "
                    "contiguous scan")
                break
    return fails


# ---------------------------------------------------------------------------
# entry points


def prove_plan(plan: RulesetPlan, fingerprint: str = "") -> PlanProof:
    """Discharge every ruleset-plan obligation; never raises — callers
    decide whether a failure refuses the plan (compiler/cache.py does)."""
    t0 = time.perf_counter()
    proof = PlanProof(fingerprint=fingerprint)
    banks, failures = bank_source_patterns(plan)
    if failures:
        proof.obligations.extend(failures)
    else:
        proof.obligations.append(Obligation(
            "bank-reconstruction", "*", "proved",
            f"{len(banks)} bank(s), spans tile exactly"))
    proof.obligations.extend(check_prefilter(plan, banks))
    proof.obligations.extend(check_plan_dfas(plan, banks))
    proof.obligations.extend(check_staging(plan))
    proof.obligations.extend(check_footprint_extension(plan, banks))
    proof.wall_s = time.perf_counter() - t0
    return proof


def prove_body_plan(bplan: Any) -> PlanProof:
    """Discharge the streaming body-plan obligations (engine/bodyscan)."""
    t0 = time.perf_counter()
    proof = PlanProof(fingerprint="body")
    obs = proof.obligations

    patterns: list[LinearPattern] = []
    slot_rule_ok = True
    for rule in bplan.rules:
        if rule.kind == "literal":
            alts = [repat.literal_pattern(rule.pattern.encode("latin-1"),
                                          rule.case_insensitive)]
        else:
            pat = rule.pattern
            if rule.case_insensitive and not pat.startswith("(?i)"):
                pat = "(?i)" + pat
            alts = repat.compile_regex(pat)
        patterns.extend(alts)
    if len(patterns) != len(bplan.slot_rule):
        slot_rule_ok = False
    obs.append(Obligation(
        "body-reconstruction", "rules",
        "proved" if slot_rule_ok else "failed",
        f"{len(patterns)} slots from {len(bplan.rules)} rule(s)"
        if slot_rule_ok else
        f"replay gives {len(patterns)} slots, plan has "
        f"{len(bplan.slot_rule)}"))
    if not slot_rule_ok:
        proof.wall_s = time.perf_counter() - t0
        return proof

    bank = build_bank(patterns)
    from ..ops.nfa_scan import bank_to_tables

    bad = _tables_equal(bplan.tables, bank_to_tables(bank))
    obs.append(Obligation(
        "body-tables", "bank", "failed" if bad else "proved",
        f"shipped tables diverge from rebuild ({bad})" if bad else
        f"{bank.num_patterns} slot(s), {bank.num_words} word(s)"))

    foot = max((pattern_footprint(lp) for lp in patterns
                if not lp.never_match), default=0)
    cap_ok = (int(bplan.tail_cap) == int(bplan.tables.max_footprint)
              and int(bplan.tail_cap) >= 0
              and int(bank.max_footprint) == int(bplan.tables.max_footprint)
              and foot <= max(int(bplan.tail_cap), 0) + 31)
    obs.append(Obligation(
        "body-tail-cap", "tail_cap", "proved" if cap_ok else "failed",
        f"tail_cap {bplan.tail_cap} == bank footprint "
        f"{bank.max_footprint} >= pattern bits" if cap_ok else
        f"tail_cap {bplan.tail_cap} vs tables "
        f"{bplan.tables.max_footprint} vs recomputed {bank.max_footprint}"))

    factors = [repat.necessary_factor(lp) for lp in patterns]
    all_factored = all(f is not None for f in factors)
    if bplan.lazy_ok:
        ok = (bool(getattr(bplan.tables, "halo_ok", False))
              and bplan.pf_tables is not None and all_factored
              and 0 < int(bplan.tail_cap) <= int(bplan.window))
        obs.append(Obligation(
            "body-lazy-gate", "lazy_ok", "proved" if ok else "failed",
            "halo_ok, factors present, 0 < tail_cap <= window" if ok else
            "lazy_ok=True without its preconditions"))
        bad_factors = []
        for lp, f in zip(patterns, factors):
            if f is None:
                continue
            note = check_factor_necessity(lp, f)
            if note not in (None, "<capped>"):
                bad_factors.append(note)
        obs.append(Obligation(
            "body-factor-necessity", "pf",
            "failed" if bad_factors else "proved",
            "; ".join(bad_factors[:4]) if bad_factors else
            f"{sum(1 for f in factors if f is not None)} factor(s) proved"))
    else:
        obs.append(Obligation("body-lazy-gate", "lazy_ok", "skipped",
                              "lazy path disabled for this plan"))

    if bplan.dfa_tables is not None:
        if not bool(getattr(bplan.dfa_tables, "exact", False)):
            obs.append(Obligation(
                "body-dfa", "dfa", "failed",
                "body DFA shipped without exact=True (the streaming "
                "scanner has no NFA recheck)"))
        else:
            fails = check_dfa_containment(patterns, bplan.dfa_tables)
            obs.append(Obligation(
                "body-dfa", "dfa", "failed" if fails else "proved",
                "; ".join(fails[:4]) if fails else
                f"exact over {int(bplan.dfa_tables.num_states)} states"))

    fails = check_carry_closure(bank, patterns)
    obs.append(Obligation(
        "body-carry-closure", "seams", "failed" if fails else "proved",
        "; ".join(fails[:4]) if fails else
        "every seam through every witness equals the contiguous scan"))

    proof.wall_s = time.perf_counter() - t0
    return proof


def require(proof: PlanProof) -> PlanProof:
    """Raise ObligationError when the proof has failures."""
    if not proof.ok:
        raise ObligationError(proof)
    return proof
