"""Bit-parallel NFA banks: packing linear patterns into uint32 lanes.

An `NfaBank` holds every contains/regex predicate that scans one request
field (path, url, host, user_agent, ...). Patterns are packed into uint32
words — one guard bit + one bit per position, each pattern confined to a
single word — and executed as extended Shift-And (Glushkov over linear
patterns) with pure bitwise ops:

    inj  = INIT_unanchored | (t == 0 ? INIT_anchored : 0)
    adv  = (S << 1) | inj
    adv |= ((adv & OPT) + OPT) ^ OPT        # skip optional runs (carry trick)
    pre  = adv | (S & REP)                  # self-loops for x* / x+
    S'   = pre & B[c]                       # byte-class transition
    float_matches |= S' & LAST_FLOAT        # accept for non-$ patterns
    ...after the scan: end_matches = S_final & LAST_END   # $ patterns

The optional-skip identity: within a run of consecutive OPT bits, adding
(adv & OPT) to OPT carries through the run; XOR with OPT recovers every
position from the first active bit through one past the run's end —
exactly the Glushkov epsilon-skip closure for linear patterns.

This module builds the (numpy) tables; ops/nfa_scan.py executes them in
JAX; `simulate` is the pure-Python oracle used by differential tests
(pattern semantics are verified three ways: Python `re` (bytes mode) ==
`simulate` == the bit-parallel scan).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .repat import LinearPattern, Pos, Quant, Unsupported

WORD_BITS = 32


def _skippable(p: Pos) -> bool:
    return p.quant in (Quant.OPT, Quant.STAR)


def _repeatable(p: Pos) -> bool:
    return p.quant in (Quant.STAR, Quant.PLUS)


def _is_word(c: int) -> bool:
    from .repat import is_word_byte

    return is_word_byte(c)


def simulate(lp: LinearPattern, data: bytes) -> bool:
    """Pure-Python Glushkov simulation of one linear pattern (oracle).

    `$` semantics follow Python `re` in bytes mode (the interpreter's
    engine, expr/values.py): it accepts at the end of input AND just
    before one trailing newline. Leading/trailing \\b gate injection and
    delay acceptance by one byte (confirmed by the next byte's word-ness
    or end of input).
    """
    if lp.never_match:
        return False
    m = len(lp.positions)
    if m == 0 or lp.min_len == 0:
        if not (lp.anchor_start and lp.anchor_end):
            return True
        # ^...$ with nothing required: empty input, or empty before a
        # lone trailing newline, or fall through to the NFA (m>0).
        if len(data) == 0 or data == b"\n":
            return True
        if m == 0:
            return False
    first_word = _is_word(next(iter(lp.positions[0].bytes))) if m else False
    last_word = _is_word(next(iter(lp.positions[-1].bytes))) if m else False
    if lp.anchor_end and lp.boundary_end and not last_word:
        return False  # boundary can never hold at end-of-input
    last_set = _last_set(lp)
    active: set[int] = set()
    matched = False
    pend = False  # boundary_end accept awaiting confirmation
    prev_word = False  # start of input counts as non-word
    ends_nl = len(data) > 0 and data[-1] == 0x0A
    for t, c in enumerate(data):
        cur_word = _is_word(c)
        if lp.boundary_end and not lp.anchor_end and pend and \
                cur_word != last_word:
            matched = True
        inject = (t == 0) or not lp.anchor_start
        if lp.boundary_start and inject:
            inject = prev_word != first_word
        nxt: set[int] = set()
        candidates: set[int] = set()
        if inject:
            candidates |= _closure_from(lp, 0)
        for i in active:
            if _repeatable(lp.positions[i]):
                candidates.add(i)
            if i + 1 < m:
                candidates |= _closure_from(lp, i + 1)
        for i in candidates:
            if c in lp.positions[i].bytes:
                nxt.add(i)
        active = nxt
        hit = bool(active & last_set)
        if lp.boundary_end:
            pend = hit
        elif not lp.anchor_end and hit:
            matched = True
        if lp.anchor_end and ends_nl and t == len(data) - 2 and hit:
            matched = True  # accept just before the trailing newline
        prev_word = cur_word
    if lp.boundary_end and not lp.anchor_end:
        # End of input confirms a pending accept when the last consumed
        # char is a word char (EOS is the non-word side).
        return matched or (pend and last_word)
    if lp.anchor_end:
        return matched or bool(active & last_set)
    return matched


def _closure_from(lp: LinearPattern, start: int) -> set[int]:
    """Positions reachable as 'next consumed' entering at `start`:
    start itself plus everything past a run of skippable positions."""
    out = set()
    i = start
    m = len(lp.positions)
    while i < m:
        out.add(i)
        if _skippable(lp.positions[i]):
            i += 1
        else:
            break
    return out


def _last_set(lp: LinearPattern) -> set[int]:
    """Accept positions: i such that every later position is skippable."""
    out = set()
    for i in range(len(lp.positions) - 1, -1, -1):
        out.add(i)
        if not _skippable(lp.positions[i]):
            break
    return out


@dataclass(frozen=True)
class PatternSlot:
    """Where one input pattern lives in the bank + accept metadata.

    With sticky-accept compilation every accept is read from the FINAL
    scan state: `hit = (S_final[word] & accept_mask) != 0`, plus the
    always/empty flags. There is no float/end distinction at scan time —
    `$`, trailing newlines, and \\b variants were compiled into extra
    positions/alternatives (see _expand_scan_patterns).
    """

    word: int
    accept_mask: int
    always_match: bool
    empty_ok: bool  # additionally accept empty input (lengths == 0)


@dataclass
class NfaBank:
    """Packed bit-parallel tables for one field's pattern group.

    The scan algebra is minimal — a single carried state word vector:

        inj  = t == 0 ? init_anchored | init_unanchored : init_unanchored
        adv  = (S << 1) | inj
        adv |= ((adv & OPT) + OPT) ^ OPT     # skip optional runs
        S'   = (adv | (S & REP)) & B[c]      # self-loops + byte classes

    Accept state is *inside* S: each floating subpattern has a sticky
    bit (byte class = ALL, REP self-loop) fed by its last position, so a
    match anywhere survives to the end of the scan; `$` compiles into an
    extra accept position (and an optional-\\n alternative for Python
    re's trailing-newline semantics); \\b compiles into prepended/
    appended word-class positions and/or anchored alternatives. One
    HBM-resident carry instead of four makes the lax.scan loop ~3x
    cheaper (each carry round-trips HBM per step under XLA).
    """

    num_words: int = 0
    byte_table: np.ndarray = field(
        default_factory=lambda: np.zeros((256, 0), dtype=np.uint32)
    )  # [256, W]
    init_anchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected at t==0 only
    init_unanchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected every step
    opt: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    rep: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    slots: list[PatternSlot] = field(default_factory=list)

    @property
    def num_patterns(self) -> int:
        return len(self.slots)


@dataclass(frozen=True)
class _ScanPattern:
    """One compiled alternative: positions + static accept positions."""

    positions: tuple[Pos, ...]
    accept: frozenset[int]  # relative indices accepting at final state
    sticky: bool  # add a sticky accept bit after the last position
    anchored: bool


from .repat import _WORD as _WORDSET  # noqa: E402

_NONWORD = frozenset(range(256)) - _WORDSET
_NEWLINE = frozenset([0x0A])


def _expand_scan_patterns(lp: LinearPattern) -> list[_ScanPattern]:
    """Compile anchors/boundaries into plain scan alternatives.

    `X$` -> positions X + required '\n' with accepts at last_set(X) (abs
    end) and at the \n position (end just before a trailing newline).
    Trailing \b -> an appended opposite-word-class position (+ the
    absolute-end accept when the last class is word). Leading \b -> a
    prepended opposite-word-class required position, plus an anchored
    alternative for matches at position 0.
    """
    from .repat import Quant, is_word_byte

    base = tuple(lp.positions)
    m = len(base)
    base_last = frozenset(_last_set(lp))

    if lp.anchor_end and lp.boundary_end and m and not is_word_byte(
            next(iter(base[-1].bytes))):
        # \b$ with a non-word last class: the boundary can never hold at
        # end-of-input (simulate() has the same early-out).
        return []

    variants: list[tuple[tuple[Pos, ...], frozenset[int], bool]] = []
    if lp.anchor_end:
        pos = base + (Pos(bytes=_NEWLINE),)
        variants.append((pos, base_last | {m}, False))
    elif lp.boundary_end:
        last_word = is_word_byte(next(iter(base[-1].bytes)))
        if last_word:
            pos = base + (Pos(bytes=_NONWORD),)
            variants.append((pos, base_last | {m}, True))
        else:
            pos = base + (Pos(bytes=_WORDSET),)
            variants.append((pos, frozenset({m}), True))
    else:
        variants.append((base, base_last, True))

    out: list[_ScanPattern] = []
    for pos, accept, sticky in variants:
        if lp.boundary_start:
            first_word = is_word_byte(next(iter(base[0].bytes)))
            if not lp.anchor_start:
                prefix_cls = _NONWORD if first_word else _WORDSET
                shifted = frozenset(i + 1 for i in accept)
                out.append(_ScanPattern(
                    positions=(Pos(bytes=prefix_cls),) + pos,
                    accept=shifted, sticky=sticky, anchored=False))
            if first_word:
                # Boundary holds at position 0 (start is the non-word
                # side) -> anchored alternative. Non-word first class can
                # never have a boundary at position 0.
                out.append(_ScanPattern(positions=pos, accept=accept,
                                        sticky=sticky, anchored=True))
        else:
            out.append(_ScanPattern(positions=pos, accept=accept,
                                    sticky=sticky,
                                    anchored=lp.anchor_start))
    return out


def scan_bits_needed(lp: LinearPattern) -> int:
    """Bits one input pattern occupies after expansion (guards + sticky
    included). Must be <= WORD_BITS for device residency."""
    if lp.never_match:
        return 0
    if lp.min_len == 0 and not (lp.anchor_start and lp.anchor_end):
        return 0  # always-match: no device state
    total = 0
    for sp in _expand_scan_patterns(lp):
        total += 1 + len(sp.positions) + (1 if sp.sticky else 0)
    return total


def build_bank(patterns: list[LinearPattern]) -> NfaBank:
    """Pack linear patterns into an NfaBank (first-fit into uint32 words).

    All expanded alternatives of one input pattern are packed contiguously
    in a single word so each pattern keeps one (word, accept_mask) slot.
    """
    from .repat import Unsupported

    bank = NfaBank()
    word_used: list[int] = []
    byte_rows: list[dict[int, int]] = []
    init_a: list[int] = []
    init_u: list[int] = []
    opt: list[int] = []
    rep: list[int] = []

    for lp in patterns:
        m = len(lp.positions)
        always = lp.min_len == 0 and not (lp.anchor_start and lp.anchor_end)
        empty_ok = lp.min_len == 0 and lp.anchor_start and lp.anchor_end
        if lp.never_match:
            bank.slots.append(PatternSlot(word=0, accept_mask=0,
                                          always_match=False, empty_ok=False))
            continue
        if m == 0 and not (lp.anchor_start and lp.anchor_end):
            bank.slots.append(PatternSlot(word=0, accept_mask=0,
                                          always_match=True, empty_ok=False))
            continue
        if always:
            bank.slots.append(PatternSlot(word=0, accept_mask=0,
                                          always_match=True, empty_ok=False))
            continue

        subs = _expand_scan_patterns(lp)
        need = sum(1 + len(s.positions) + (1 if s.sticky else 0)
                   for s in subs)
        if not subs or need == 0:
            # e.g. ^\b with non-word first class only: unsatisfiable.
            bank.slots.append(PatternSlot(word=0, accept_mask=0,
                                          always_match=False,
                                          empty_ok=empty_ok))
            continue
        if need > WORD_BITS:
            raise Unsupported(f"pattern needs {need} bits > {WORD_BITS}")
        w = -1
        for idx, used in enumerate(word_used):
            if used + need <= WORD_BITS:
                w = idx
                break
        if w == -1:
            word_used.append(0)
            byte_rows.append({})
            init_a.append(0)
            init_u.append(0)
            opt.append(0)
            rep.append(0)
            w = len(word_used) - 1

        accept_mask = 0
        for sub in subs:
            base = word_used[w] + 1  # skip the guard bit
            bit = lambda i: 1 << (base + i)  # noqa: E731
            for i, pos in enumerate(sub.positions):
                for b in pos.bytes:
                    byte_rows[w][b] = byte_rows[w].get(b, 0) | bit(i)
                if _skippable(pos):
                    opt[w] |= bit(i)
                if _repeatable(pos):
                    rep[w] |= bit(i)
            if sub.anchored:
                init_a[w] |= bit(0)
            else:
                init_u[w] |= bit(0)
            for i in sub.accept:
                accept_mask |= bit(i)
            n = len(sub.positions)
            if sub.sticky:
                # Sticky accept bit: matches any byte, self-loops, fed by
                # the last position's shift/opt-propagation.
                for b in range(256):
                    byte_rows[w][b] = byte_rows[w].get(b, 0) | bit(n)
                rep[w] |= bit(n)
                accept_mask |= bit(n)
                n += 1
            word_used[w] += 1 + n

        bank.slots.append(PatternSlot(word=w, accept_mask=accept_mask,
                                      always_match=False, empty_ok=empty_ok))

    W = len(word_used)
    bank.num_words = W
    table = np.zeros((256, W), dtype=np.uint32)
    for w in range(W):
        for b, mask in byte_rows[w].items():
            table[b, w] = mask
    bank.byte_table = table
    bank.init_anchored = np.array(init_a, dtype=np.uint32)
    bank.init_unanchored = np.array(init_u, dtype=np.uint32)
    bank.opt = np.array(opt, dtype=np.uint32)
    bank.rep = np.array(rep, dtype=np.uint32)
    return bank


def scan_numpy(bank: NfaBank, data: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Reference bitwise scan in numpy (same algebra as the JAX op).

    data: [B, L] uint8, lengths: [B] -> matched [B, P] bool.
    """
    B, L = data.shape
    W = bank.num_words
    S = np.zeros((B, W), dtype=np.uint32)
    for t in range(L):
        c = data[:, t].astype(np.int64)
        bc = bank.byte_table[c]  # [B, W]
        inj = bank.init_unanchored[None, :]
        if t == 0:
            inj = inj | bank.init_anchored[None, :]
        adv = ((S << np.uint32(1)) | inj).astype(np.uint32)
        adv |= ((adv & bank.opt) + bank.opt) ^ bank.opt
        S_new = ((adv | (S & bank.rep)) & bc).astype(np.uint32)
        S = np.where((t < lengths)[:, None], S_new, S)
    out = np.zeros((B, bank.num_patterns), dtype=bool)
    empty = lengths == 0
    for p, slot in enumerate(bank.slots):
        if slot.always_match:
            out[:, p] = True
            continue
        hit = np.zeros(B, dtype=bool)
        if W and slot.accept_mask:
            hit = (S[:, slot.word] & np.uint32(slot.accept_mask)) != 0
        if slot.empty_ok:
            hit |= empty
        out[:, p] = hit
    return out
