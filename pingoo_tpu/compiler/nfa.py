"""Bit-parallel NFA banks: packing linear patterns into uint32 lanes.

An `NfaBank` holds every contains/regex predicate that scans one request
field (path, url, host, user_agent, ...). Patterns are packed into uint32
words — one guard bit + one bit per position — and executed as extended
Shift-And (Glushkov over linear patterns) with pure bitwise ops:

    inj  = INIT_unanchored | (t == 0 ? INIT_anchored : 0)
    adv  = (S << 1) | inj | word_carry(S)   # bit31 -> bit0 of next word
    adv |= ((adv & OPT) + OPT) ^ OPT        # skip optional runs (carry trick)
    pre  = adv | (S & REP)                  # self-loops for x* / x+
    S'   = pre & B[c]                       # byte-class transition

The optional-skip identity: within a run of consecutive OPT bits, adding
(adv & OPT) to OPT carries through the run; XOR with OPT recovers every
position from the first active bit through one past the run's end —
exactly the Glushkov epsilon-skip closure for linear patterns.

Multi-word patterns (> ~31 positions after expansion — the OWASP-CRS
long literals and bounded-repeat classes): a pattern spanning k uint32
words gets a DEDICATED run of consecutive words. Advancement crosses
word boundaries through `carry_mask` (bit31 of word w feeds bit0 of
word w+1 where enabled), and the optional-skip closure crosses through
its add-carry: a run reaching bit31 overflows the uint32 add, detected
as `sum < OPT`, and re-injected at bit0 of the next word before another
propagation pass. The number of passes is static per bank
(1 + max word boundaries any optional run crosses).

This module builds the (numpy) tables; ops/nfa_scan.py executes them in
JAX; `simulate` is the pure-Python oracle used by differential tests
(pattern semantics are verified three ways: Python `re` (bytes mode) ==
`simulate` == the bit-parallel scan).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .repat import LinearPattern, Pos, Quant, Unsupported

WORD_BITS = 32
# Device-residency cap for one pattern's expanded footprint (guards +
# positions + sticky bits across all alternatives). 128 bits = a 4-word
# span; anything larger is Unsupported -> host-interpreted rule.
MAX_SCAN_BITS = 128
# Cap on ONE RULE's total footprint across all its alternatives (wide
# alternations split across slots): 24 words worth of state. Keeps a
# single pathological rule from doubling the whole bank's lane count.
MAX_RULE_SCAN_BITS = 768


def _skippable(p: Pos) -> bool:
    return p.quant in (Quant.OPT, Quant.STAR)


def _repeatable(p: Pos) -> bool:
    return p.quant in (Quant.STAR, Quant.PLUS)


def _is_word(c: int) -> bool:
    from .repat import is_word_byte

    return is_word_byte(c)


def simulate(lp: LinearPattern, data: bytes) -> bool:
    """Pure-Python Glushkov simulation of one linear pattern (oracle).

    `$` semantics follow Python `re` in bytes mode (the interpreter's
    engine, expr/values.py): it accepts at the end of input AND just
    before one trailing newline. Leading/trailing \\b gate injection and
    delay acceptance by one byte (confirmed by the next byte's word-ness
    or end of input).
    """
    if lp.never_match:
        return False
    m = len(lp.positions)
    if m == 0 or lp.min_len == 0:
        if not (lp.anchor_start and (lp.anchor_end or lp.anchor_end_abs)):
            return True
        # ^...$ with nothing required: empty input, or (non-abs $ only)
        # empty before a lone trailing newline, or fall through to the
        # NFA (m>0).
        if len(data) == 0 or (data == b"\n" and not lp.anchor_end_abs):
            return True
        if m == 0:
            return False
    first_word = _is_word(next(iter(lp.positions[0].bytes))) if m else False
    last_word = _is_word(next(iter(lp.positions[-1].bytes))) if m else False
    if (lp.anchor_end or lp.anchor_end_abs) and lp.boundary_end \
            and not last_word:
        return False  # boundary can never hold at end-of-input
    last_set = _last_set(lp)
    active: set[int] = set()
    matched = False
    pend = False  # boundary_end accept awaiting confirmation
    prev_word = False  # start of input counts as non-word
    ends_nl = len(data) > 0 and data[-1] == 0x0A
    for t, c in enumerate(data):
        cur_word = _is_word(c)
        if lp.boundary_end and not (lp.anchor_end or lp.anchor_end_abs) \
                and pend and cur_word != last_word:
            matched = True
        inject = (t == 0) or not lp.anchor_start
        if lp.boundary_start and inject:
            inject = prev_word != first_word
        nxt: set[int] = set()
        candidates: set[int] = set()
        if inject:
            candidates |= _closure_from(lp, 0)
        for i in active:
            if _repeatable(lp.positions[i]):
                candidates.add(i)
            if i + 1 < m:
                candidates |= _closure_from(lp, i + 1)
        for i in candidates:
            if c in lp.positions[i].bytes:
                nxt.add(i)
        active = nxt
        hit = bool(active & last_set)
        if lp.boundary_end:
            pend = hit
        elif not (lp.anchor_end or lp.anchor_end_abs) and hit:
            matched = True
        if lp.anchor_end and ends_nl and t == len(data) - 2 and hit:
            matched = True  # accept just before the trailing newline
        prev_word = cur_word
    if lp.boundary_end and not lp.anchor_end:
        # End of input confirms a pending accept when the last consumed
        # char is a word char (EOS is the non-word side). For \b\Z the
        # fixed `matched` above stays False, so only the final-position
        # pend (+ word-ness, guaranteed by the early-out) accepts.
        return matched or (pend and last_word)
    if lp.anchor_end_abs:
        # Absolute end: accept only from the final state (no trailing-\n
        # tolerance, so `matched` never fires for abs patterns).
        return bool(active & last_set)
    if lp.anchor_end:
        return matched or bool(active & last_set)
    return matched


def _closure_from(lp: LinearPattern, start: int) -> set[int]:
    """Positions reachable as 'next consumed' entering at `start`:
    start itself plus everything past a run of skippable positions."""
    out = set()
    i = start
    m = len(lp.positions)
    while i < m:
        out.add(i)
        if _skippable(lp.positions[i]):
            i += 1
        else:
            break
    return out


def _last_set(lp: LinearPattern) -> set[int]:
    """Accept positions: i such that every later position is skippable."""
    out = set()
    for i in range(len(lp.positions) - 1, -1, -1):
        out.add(i)
        if not _skippable(lp.positions[i]):
            break
    return out


@dataclass(frozen=True)
class PatternSlot:
    """Where one input pattern lives in the bank + accept metadata.

    With sticky-accept compilation every accept is read from the FINAL
    scan state: `hit = any((S_final[word] & mask) != 0 for word, mask in
    accepts)`, plus the always/empty flags. There is no float/end
    distinction at scan time — `$`, trailing newlines, and \\b variants
    were compiled into extra positions/alternatives (see
    _expand_scan_patterns). Single-word patterns have exactly one
    (word, mask) pair; multi-word patterns may accept in several words
    (one pair per word their accept positions touch).
    """

    accepts: tuple[tuple[int, int], ...]  # (word, accept_mask) pairs
    always_match: bool
    empty_ok: bool  # additionally accept empty input (lengths == 0)


@dataclass
class NfaBank:
    """Packed bit-parallel tables for one field's pattern group.

    The scan algebra is minimal — a single carried state word vector:

        inj  = t == 0 ? init_anchored | init_unanchored : init_unanchored
        adv  = (S << 1) | inj
        adv |= ((adv & OPT) + OPT) ^ OPT     # skip optional runs
        S'   = (adv | (S & REP)) & B[c]      # self-loops + byte classes

    Accept state is *inside* S: each floating subpattern has a sticky
    bit (byte class = ALL, REP self-loop) fed by its last position, so a
    match anywhere survives to the end of the scan; `$` compiles into an
    extra accept position (and an optional-\\n alternative for Python
    re's trailing-newline semantics); \\b compiles into prepended/
    appended word-class positions and/or anchored alternatives. One
    HBM-resident carry instead of four makes the lax.scan loop ~3x
    cheaper (each carry round-trips HBM per step under XLA).
    """

    num_words: int = 0
    byte_table: np.ndarray = field(
        default_factory=lambda: np.zeros((256, 0), dtype=np.uint32)
    )  # [256, W]
    init_anchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected at t==0 only
    init_unanchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected every step
    opt: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    rep: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    # carry_mask[w] == 1 -> word w continues word w-1's pattern: bit31 of
    # w-1 advances into bit0 of w, and opt-closure escapes re-inject there.
    carry_mask: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32))
    # Bits that are sticky ACCEPT accumulators (self-looping on every
    # byte). rep & ~sticky == 0 means the automaton has bounded memory
    # (state at t depends only on the last `max_footprint` bytes), which
    # enables the halo-parallel sequence scan (parallel/ring.py).
    sticky_mask: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32))
    # Static number of opt-propagation passes the scan needs
    # (1 + max word boundaries any optional run crosses).
    prop_passes: int = 1
    # Largest single-pattern footprint in bits (>= its byte memory).
    max_footprint: int = 0
    # Per-word: True for words allocated to a multi-word span (single-
    # word patterns may still share a span's LAST word's free tail).
    dedicated: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=bool))
    slots: list[PatternSlot] = field(default_factory=list)

    @property
    def num_patterns(self) -> int:
        return len(self.slots)

    @property
    def has_carry(self) -> bool:
        return bool(self.carry_mask.any())


@dataclass(frozen=True)
class _ScanPattern:
    """One compiled alternative: positions + static accept positions."""

    positions: tuple[Pos, ...]
    accept: frozenset[int]  # relative indices accepting at final state
    sticky: bool  # add a sticky accept bit after the last position
    anchored: bool


from .repat import _WORD as _WORDSET  # noqa: E402

_NONWORD = frozenset(range(256)) - _WORDSET
_NEWLINE = frozenset([0x0A])


def _expand_scan_patterns(lp: LinearPattern) -> list[_ScanPattern]:
    """Compile anchors/boundaries into plain scan alternatives.

    `X$` -> positions X + required '\n' with accepts at last_set(X) (abs
    end) and at the \n position (end just before a trailing newline).
    Trailing \b -> an appended opposite-word-class position (+ the
    absolute-end accept when the last class is word). Leading \b -> a
    prepended opposite-word-class required position, plus an anchored
    alternative for matches at position 0.
    """
    from .repat import Quant, is_word_byte

    base = tuple(lp.positions)
    m = len(base)
    base_last = frozenset(_last_set(lp))

    if (lp.anchor_end or lp.anchor_end_abs) and lp.boundary_end and m \
            and not is_word_byte(next(iter(base[-1].bytes))):
        # \b$ / \b\Z with a non-word last class: the boundary can never
        # hold at end-of-input (simulate() has the same early-out).
        return []

    variants: list[tuple[tuple[Pos, ...], frozenset[int], bool]] = []
    if lp.anchor_end_abs:
        # Absolute end (\Z / mid-$ lowering): accept only from the final
        # scan state — no appended-\n alternative, no sticky bit.
        variants.append((base, base_last, False))
    elif lp.anchor_end:
        pos = base + (Pos(bytes=_NEWLINE),)
        variants.append((pos, base_last | {m}, False))
    elif lp.boundary_end:
        last_word = is_word_byte(next(iter(base[-1].bytes)))
        if last_word:
            pos = base + (Pos(bytes=_NONWORD),)
            variants.append((pos, base_last | {m}, True))
        else:
            pos = base + (Pos(bytes=_WORDSET),)
            variants.append((pos, frozenset({m}), True))
    else:
        variants.append((base, base_last, True))

    out: list[_ScanPattern] = []
    for pos, accept, sticky in variants:
        if lp.boundary_start:
            first_word = is_word_byte(next(iter(base[0].bytes)))
            if not lp.anchor_start:
                prefix_cls = _NONWORD if first_word else _WORDSET
                shifted = frozenset(i + 1 for i in accept)
                out.append(_ScanPattern(
                    positions=(Pos(bytes=prefix_cls),) + pos,
                    accept=shifted, sticky=sticky, anchored=False))
            if first_word:
                # Boundary holds at position 0 (start is the non-word
                # side) -> anchored alternative. Non-word first class can
                # never have a boundary at position 0.
                out.append(_ScanPattern(positions=pos, accept=accept,
                                        sticky=sticky, anchored=True))
        else:
            out.append(_ScanPattern(positions=pos, accept=accept,
                                    sticky=sticky,
                                    anchored=lp.anchor_start))
    return out


def scan_bits_needed(lp: LinearPattern) -> int:
    """Bits one input pattern occupies after expansion (guards + sticky
    included). Must be <= MAX_SCAN_BITS for device residency."""
    if lp.never_match:
        return 0
    if lp.min_len == 0 and not (
            lp.anchor_start and (lp.anchor_end or lp.anchor_end_abs)):
        return 0  # always-match: no device state
    total = 0
    for sp in _expand_scan_patterns(lp):
        total += 1 + len(sp.positions) + (1 if sp.sticky else 0)
    return total


def pattern_footprint(lp: LinearPattern) -> int:
    """Largest single-alternative footprint (guard + positions + sticky)
    after expansion — an upper bound on the byte memory the halo scans
    must warm up for this pattern. 0 for never/always patterns (they
    carry no device state)."""
    if lp.never_match:
        return 0
    ends = lp.anchor_end or lp.anchor_end_abs
    if lp.min_len == 0 and not (lp.anchor_start and ends):
        return 0
    subs = _expand_scan_patterns(lp)
    if not subs:
        return 0
    return max(2 + len(s.positions) + (1 if s.sticky else 0) for s in subs)


class _BankBuilder:
    """Mutable word-table state shared by both packing paths."""

    def __init__(self) -> None:
        self.used: list[int] = []
        self.byte_rows: list[dict[int, int]] = []
        self.init_a: list[int] = []
        self.init_u: list[int] = []
        self.opt: list[int] = []
        self.rep: list[int] = []
        self.sticky: list[int] = []
        self.carry: list[bool] = []
        self.dedicated: list[bool] = []
        self.max_passes = 1
        self.max_footprint = 0

    def add_word(self, carry: bool, dedicated: bool) -> int:
        self.used.append(0)
        self.byte_rows.append({})
        self.init_a.append(0)
        self.init_u.append(0)
        self.opt.append(0)
        self.rep.append(0)
        self.sticky.append(0)
        self.carry.append(carry)
        self.dedicated.append(dedicated)
        return len(self.used) - 1

    # -- single-word path (first-fit sharing, the common case) ---------------

    def pack_shared(self, subs: list[_ScanPattern], need: int) -> PatternSlot:
        # First-fit over shared words AND the free tails of dedicated
        # span words: a span's final word rarely ends at bit 31, and the
        # tail bits above it are safe to share — the guard bit absorbs
        # the shift out of the span's top position, and any escape out
        # of the tail's bit 31 only lands where carry is enabled, which
        # the word AFTER a span's last word never is. The load-bearing
        # invariant: a non-final span word is always exactly full
        # (pack_span's place() only opens a new word at used == 32), so
        # any dedicated word with free bits IS its span's last word —
        # asserted below so a packing refactor that breaks it fails
        # loudly instead of corrupting shared patterns.
        w = -1
        for idx, used in enumerate(self.used):
            if used + need <= WORD_BITS:
                if self.dedicated[idx]:
                    assert not (idx + 1 < len(self.carry)
                                and self.carry[idx + 1]), \
                        "tail-sharing a non-final span word"
                w = idx
                break
        if w == -1:
            w = self.add_word(carry=False, dedicated=False)
        accept_mask = 0
        for sub in subs:
            base = self.used[w] + 1  # skip the guard bit
            bit = lambda i: 1 << (base + i)  # noqa: E731
            for i, pos in enumerate(sub.positions):
                for b in pos.bytes:
                    self.byte_rows[w][b] = self.byte_rows[w].get(b, 0) | bit(i)
                if _skippable(pos):
                    self.opt[w] |= bit(i)
                if _repeatable(pos):
                    self.rep[w] |= bit(i)
            if sub.anchored:
                self.init_a[w] |= bit(0)
            else:
                self.init_u[w] |= bit(0)
            for i in sub.accept:
                accept_mask |= bit(i)
            n = len(sub.positions)
            if sub.sticky:
                # Sticky accept bit: matches any byte, self-loops, fed by
                # the last position's shift/opt-propagation.
                for b in range(256):
                    self.byte_rows[w][b] = self.byte_rows[w].get(b, 0) | bit(n)
                self.rep[w] |= bit(n)
                self.sticky[w] |= bit(n)
                accept_mask |= bit(n)
                n += 1
            self.used[w] += 1 + n
            self.max_footprint = max(self.max_footprint, 1 + n)
        return PatternSlot(accepts=((w, accept_mask),),
                           always_match=False, empty_ok=False)

    # -- multi-word path (dedicated span, cross-word carry) ------------------

    def pack_span(self, subs: list[_ScanPattern]) -> PatternSlot:
        first_w = self.add_word(carry=False, dedicated=True)
        cur = [first_w]  # boxed current word

        def gbit(w: int, b: int) -> int:
            return (w - first_w) * WORD_BITS + b

        def place() -> tuple[int, int]:
            if self.used[cur[0]] == WORD_BITS:
                cur[0] = self.add_word(carry=True, dedicated=True)
            b = self.used[cur[0]]
            self.used[cur[0]] += 1
            return cur[0], b

        accepts: dict[int, int] = {}
        for sub in subs:
            place()  # guard bit: absorbs shift-in from the previous region
            run_start: int | None = None  # global bit of current opt run

            def close_run(end_g: int) -> None:
                nonlocal run_start
                if run_start is not None:
                    # The epsilon closure from an active bit at run_start
                    # reaches end_g (one past the run); each word boundary
                    # in between needs one extra propagation pass.
                    crossings = end_g // WORD_BITS - run_start // WORD_BITS
                    self.max_passes = max(self.max_passes, 1 + crossings)
                    run_start = None

            placed: list[tuple[int, int]] = []
            first = True
            for pos in sub.positions:
                w, b = place()
                for byte in pos.bytes:
                    self.byte_rows[w][byte] = (
                        self.byte_rows[w].get(byte, 0) | (1 << b))
                if _skippable(pos):
                    self.opt[w] |= 1 << b
                    if run_start is None:
                        run_start = gbit(w, b)
                else:
                    close_run(gbit(w, b))
                if _repeatable(pos):
                    self.rep[w] |= 1 << b
                if first:
                    if sub.anchored:
                        self.init_a[w] |= 1 << b
                    else:
                        self.init_u[w] |= 1 << b
                    first = False
                placed.append((w, b))
            # A trailing optional run's closure must still reach one past
            # the last position (the sticky bit, when present).
            close_run(gbit(*placed[-1]) + 1)
            if sub.sticky:
                w, b = place()
                for byte in range(256):
                    self.byte_rows[w][byte] = (
                        self.byte_rows[w].get(byte, 0) | (1 << b))
                self.rep[w] |= 1 << b
                self.sticky[w] |= 1 << b
                accepts[w] = accepts.get(w, 0) | (1 << b)
            for i in sub.accept:
                w, b = placed[i]
                accepts[w] = accepts.get(w, 0) | (1 << b)
            self.max_footprint = max(
                self.max_footprint,
                2 + len(sub.positions) + (1 if sub.sticky else 0))
        return PatternSlot(
            accepts=tuple(sorted(accepts.items())),
            always_match=False, empty_ok=False)


def build_bank(patterns: list[LinearPattern]) -> NfaBank:
    """Pack linear patterns into an NfaBank.

    Patterns fitting one uint32 word (<= 32 bits after expansion) share
    words first-fit, all alternatives contiguous in the same word.
    Larger patterns (up to MAX_SCAN_BITS) get a dedicated span of
    consecutive words with cross-word carry (see module docstring).
    """
    from dataclasses import replace

    from .repat import Unsupported

    bank = NfaBank()
    builder = _BankBuilder()

    for lp in patterns:
        m = len(lp.positions)
        ends = lp.anchor_end or lp.anchor_end_abs
        always = lp.min_len == 0 and not (lp.anchor_start and ends)
        empty_ok = lp.min_len == 0 and lp.anchor_start and ends
        no_match = PatternSlot(accepts=(), always_match=False, empty_ok=False)
        if lp.never_match:
            bank.slots.append(no_match)
            continue
        if always or (m == 0 and not (lp.anchor_start and lp.anchor_end)):
            bank.slots.append(replace(no_match, always_match=True))
            continue

        subs = _expand_scan_patterns(lp)
        need = sum(1 + len(s.positions) + (1 if s.sticky else 0)
                   for s in subs)
        if not subs or need == 0:
            # e.g. ^\b with non-word first class only: unsatisfiable.
            bank.slots.append(replace(no_match, empty_ok=empty_ok))
            continue
        if need > MAX_SCAN_BITS:
            raise Unsupported(f"pattern needs {need} bits > {MAX_SCAN_BITS}")
        if need <= WORD_BITS:
            slot = builder.pack_shared(subs, need)
        else:
            slot = builder.pack_span(subs)
        bank.slots.append(replace(slot, empty_ok=empty_ok))

    W = len(builder.used)
    bank.num_words = W
    table = np.zeros((256, W), dtype=np.uint32)
    for w in range(W):
        for b, mask in builder.byte_rows[w].items():
            table[b, w] = mask
    bank.byte_table = table
    bank.init_anchored = np.array(builder.init_a, dtype=np.uint32)
    bank.init_unanchored = np.array(builder.init_u, dtype=np.uint32)
    bank.opt = np.array(builder.opt, dtype=np.uint32)
    bank.rep = np.array(builder.rep, dtype=np.uint32)
    bank.carry_mask = np.array(builder.carry, dtype=np.uint32)
    bank.sticky_mask = np.array(builder.sticky, dtype=np.uint32)
    bank.prop_passes = builder.max_passes
    bank.max_footprint = builder.max_footprint
    bank.dedicated = np.array(builder.dedicated, dtype=bool)
    return bank


# ---------------------------------------------------------------------------
# Bitsplit-DFA lowering (ISSUE 8): subset-construct a bank's position
# NFA into byte-indexed transition tables so the scan becomes one
# [S, C]-row gather per byte instead of the dependent one-hot matmul
# chain. Optional approximate state merging (quotient by bounded-depth
# bisimulation signatures) shrinks the NFA *before* determinization;
# merging only ever ADDS behavior (byte classes, successors, accepts
# are unioned), so an approximate DFA over-approximates every slot:
# candidates ⊇ matches, and the engine rechecks candidates against the
# exact NFA (engine/verdict.py), mirroring prefilter prune-only
# soundness. docs/DFA.md documents the pipeline.
# ---------------------------------------------------------------------------

DFA_STATE_BUDGET = 4096  # default PINGOO_DFA_STATES (clamped <= 65536)
DFA_MERGE_DEPTHS = (8, 4, 2)  # default PINGOO_DFA_MERGE ladder


@dataclass
class DfaBank:
    """Byte-indexed DFA tables for one field's pattern group.

    Execution (ops/bitsplit_dfa.py):

        H    |= step_accept[state]          # while t < len (sticky fire)
        state = trans[state, byte_cls[c]]   # while t < len
        ...
        H |= end_accept[state_final]        # absolute-end accepts
        hit[p] = (H[slot p's word] & slot mask) | always | (empty_ok & len==0)

    State 0 is the dedicated start state (it alone carries the t==0
    anchored injection) and is never a transition target; the empty
    subset is interned separately as the dead/idle state. Sticky accept
    accumulators are factored OUT of the subset state (they would
    otherwise multiply reachable subsets by 2^latched) and fired into
    the H accumulator via `step_accept` instead — `step_accept[Q]` is
    the slot mask whose sticky bit the NEXT consumed byte would light
    from subset Q, which is byte-independent because sticky bits match
    every byte.
    """

    trans: np.ndarray        # [S, C] int32 (state, byte class) -> state
    byte_cls: np.ndarray     # [256] int32 byte -> class id
    step_accept: np.ndarray  # [S, Wh] uint32 sticky fire, read pre-step
    end_accept: np.ndarray   # [S, Wh] uint32 read at the final state
    slot_always: np.ndarray  # [P] bool
    slot_empty_ok: np.ndarray  # [P] bool
    num_states: int = 0
    num_classes: int = 0
    num_slots: int = 0
    num_words: int = 0       # Wh = ceil(P / 32) accept words
    exact: bool = True       # False -> over-approximation (recheck hits)
    merge_depth: int = 0     # signature depth that produced the tables


class _PosNfa:
    """Flattened position NFA over one bank's expanded alternatives.

    Non-sticky positions only; `succ[q]` / `inj_*` are bitmask ints over
    positions, `fire*` / `end[q]` are bitmask ints over pattern slots.
    """

    def __init__(self) -> None:
        self.bytes: list[frozenset[int]] = []
        self.rep: list[bool] = []
        self.succ: list[int] = []   # successors of q (shift + opt closure)
        self.fire: list[int] = []   # slots whose sticky bit succ(q) feeds
        self.end: list[int] = []    # slots accepting when q is active at end
        self.inj_u = 0              # injected every step
        self.inj_a = 0              # injected at t == 0 only
        self.fire_u = 0             # sticky slots fed by per-step injection
        self.fire_a = 0


def _add_sub(nfa: _PosNfa, sub: _ScanPattern, slot: int) -> None:
    n = len(sub.positions)
    slot_bit = 1 << slot
    base = len(nfa.bytes)

    def closure(start: int) -> tuple[int, int]:
        """(position mask, sticky-slot mask) reachable entering `start`:
        start plus everything past a run of skippable positions; walking
        past the last position reaches the sticky accumulator."""
        mask = 0
        i = start
        while i < n:
            mask |= 1 << (base + i)
            if not _skippable(sub.positions[i]):
                return mask, 0
            i += 1
        return mask, (slot_bit if sub.sticky else 0)

    for i, pos in enumerate(sub.positions):
        nfa.bytes.append(pos.bytes)
        nfa.rep.append(_repeatable(pos))
        smask, sfire = closure(i + 1)
        if _repeatable(pos):
            smask |= 1 << (base + i)
        nfa.succ.append(smask)
        nfa.fire.append(sfire)
        nfa.end.append(slot_bit if i in sub.accept else 0)
    imask, ifire = closure(0)
    if sub.anchored:
        nfa.inj_a |= imask
        nfa.fire_a |= ifire
    else:
        nfa.inj_u |= imask
        nfa.fire_u |= ifire


def _bank_position_nfa(
    patterns: list[LinearPattern],
) -> tuple[_PosNfa, np.ndarray, np.ndarray]:
    """Build the global position NFA + slot flag lanes for one bank.

    Slot classification replicates build_bank() bit for bit so DFA slot
    indices line up with the NfaBank's PatternSlot list.
    """
    P = len(patterns)
    slot_always = np.zeros(P, dtype=bool)
    slot_empty_ok = np.zeros(P, dtype=bool)
    nfa = _PosNfa()
    for p, lp in enumerate(patterns):
        m = len(lp.positions)
        ends = lp.anchor_end or lp.anchor_end_abs
        always = lp.min_len == 0 and not (lp.anchor_start and ends)
        empty_ok = lp.min_len == 0 and lp.anchor_start and ends
        if lp.never_match:
            continue
        if always or (m == 0 and not (lp.anchor_start and lp.anchor_end)):
            slot_always[p] = True
            continue
        subs = _expand_scan_patterns(lp)
        need = sum(1 + len(s.positions) + (1 if s.sticky else 0)
                   for s in subs)
        slot_empty_ok[p] = empty_ok
        if not subs or need == 0:
            continue
        for sub in subs:
            _add_sub(nfa, sub, p)
    return nfa, slot_always, slot_empty_ok


def _bits(mask: int):
    while mask:
        lsb = mask & -mask
        yield lsb.bit_length() - 1
        mask ^= lsb


def _merge_positions(nfa: _PosNfa, depth: int) -> tuple[_PosNfa, bool]:
    """Quotient the position NFA by depth-`depth` signature classes.

    Two positions share a class when their local attributes (self-loop,
    accept/sticky slot masks, injection membership — NOT the byte set,
    which is what gets over-approximated) agree and their successor
    CLASS sets agree through `depth` refinement rounds — a bounded-depth
    bisimulation. Distinctions propagate backward from accepting
    positions, so depth k keeps roughly the last k positions before
    each accept exact and merges (byte-unions) everything upstream: the
    suffix-window approximation of the approximate-NFA blueprint. The
    quotient unions every attribute over each class, so it simulates
    the original: any accepting run maps to an accepting run of the
    quotient, i.e. the merged automaton over-approximates every slot.
    Returns (quotient, merged?) where merged is False when the
    partition is trivial (exact)."""
    N = len(nfa.bytes)
    sigs: list = [
        (nfa.rep[q], nfa.end[q], nfa.fire[q],
         bool((nfa.inj_u >> q) & 1), bool((nfa.inj_a >> q) & 1))
        for q in range(N)
    ]
    canon: dict = {}
    ids = [canon.setdefault(s, len(canon)) for s in sigs]
    for _ in range(depth):
        canon = {}
        nxt = [
            canon.setdefault(
                (ids[q], frozenset(ids[i] for i in _bits(nfa.succ[q]))),
                len(canon))
            for q in range(N)
        ]
        if nxt == ids:
            break
        ids = nxt
    K = len(set(ids))
    if K == N:
        return nfa, False
    # Renumber classes densely in first-member order (deterministic).
    remap: dict[int, int] = {}
    for q in range(N):
        remap.setdefault(ids[q], len(remap))
    ids = [remap[i] for i in ids]

    def map_mask(mask: int) -> int:
        out = 0
        for q in _bits(mask):
            out |= 1 << ids[q]
        return out

    q_nfa = _PosNfa()
    q_nfa.bytes = [frozenset() for _ in range(K)]
    q_nfa.rep = [False] * K
    q_nfa.succ = [0] * K
    q_nfa.fire = [0] * K
    q_nfa.end = [0] * K
    for q in range(N):
        k = ids[q]
        q_nfa.bytes[k] = q_nfa.bytes[k] | nfa.bytes[q]
        q_nfa.rep[k] = q_nfa.rep[k] or nfa.rep[q]
        q_nfa.succ[k] |= map_mask(nfa.succ[q])
        q_nfa.fire[k] |= nfa.fire[q]
        q_nfa.end[k] |= nfa.end[q]
    q_nfa.inj_u = map_mask(nfa.inj_u)
    q_nfa.inj_a = map_mask(nfa.inj_a)
    q_nfa.fire_u = nfa.fire_u
    q_nfa.fire_a = nfa.fire_a
    return q_nfa, True


def _slot_words(mask: int, Wh: int) -> list[int]:
    return [(mask >> (32 * w)) & 0xFFFFFFFF for w in range(Wh)]


def _determinize(
    nfa: _PosNfa, num_slots: int, budget: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    """Budget-bounded subset construction -> (trans, byte_cls,
    step_accept, end_accept) or None when the subset count exceeds
    `budget`."""
    N = len(nfa.bytes)
    # Byte -> class compression over position membership columns (the
    # ops/nfa_scan class_compress idiom, on bitmask ints).
    col = [0] * 256
    for q, bs in enumerate(nfa.bytes):
        bit = 1 << q
        for b in bs:
            col[b] |= bit
    cls_of: dict[int, int] = {}
    cls_masks: list[int] = []
    byte_cls = np.zeros(256, dtype=np.int32)
    for b in range(256):
        cid = cls_of.get(col[b])
        if cid is None:
            cid = len(cls_masks)
            cls_of[col[b]] = cid
            cls_masks.append(col[b])
        byte_cls[b] = cid
    C = len(cls_masks)

    # masks[0] is the start state (empty subset + anchored injection);
    # interned subsets start at id 1, so a re-reached empty subset gets
    # its own dead/idle id and never resurrects the t==0 injection.
    masks: list[int] = [0]
    ids: dict[int, int] = {}
    trans_rows: list[list[int]] = []
    fires: list[int] = []
    ends: list[int] = []

    def intern(mask: int) -> int:
        sid = ids.get(mask)
        if sid is None:
            sid = len(masks)
            ids[mask] = sid
            masks.append(mask)
        return sid

    sid = 0
    while sid < len(masks):
        if sid == 0:
            cand = nfa.inj_u | nfa.inj_a
            fire = nfa.fire_u | nfa.fire_a
            end = 0
        else:
            cand = nfa.inj_u
            fire = nfa.fire_u
            end = 0
            for q in _bits(masks[sid]):
                cand |= nfa.succ[q]
                fire |= nfa.fire[q]
                end |= nfa.end[q]
        # One AND per class against the per-state candidate mask: the
        # construction is O(S * (|Q| + C)), not O(S * C * |Q|).
        row = [intern(cand & cm) for cm in cls_masks]
        if len(masks) > budget:
            return None
        trans_rows.append(row)
        fires.append(fire)
        ends.append(end)
        sid += 1

    S = len(masks)
    Wh = max(1, -(-num_slots // 32))
    trans = np.asarray(trans_rows, dtype=np.int32).reshape(S, C)
    step_accept = np.asarray(
        [_slot_words(f, Wh) for f in fires], dtype=np.uint32)
    end_accept = np.asarray(
        [_slot_words(e, Wh) for e in ends], dtype=np.uint32)
    return trans, byte_cls, step_accept, end_accept


def _dfa_state_budget(state_budget: int | None) -> int:
    import os

    if state_budget is None:
        try:
            state_budget = int(
                os.environ.get("PINGOO_DFA_STATES", DFA_STATE_BUDGET))
        except ValueError:
            state_budget = DFA_STATE_BUDGET
    # 65536 keeps state ids exact through the Pallas f32 one-hot path.
    return max(2, min(int(state_budget), 65536))


def _dfa_merge_depths(merge_depths) -> tuple[int, ...]:
    import os

    if merge_depths is None:
        env = os.environ.get("PINGOO_DFA_MERGE")
        if env is None:
            return DFA_MERGE_DEPTHS
        try:
            return tuple(int(x) for x in env.split(",") if x.strip())
        except ValueError:
            return DFA_MERGE_DEPTHS
    return tuple(merge_depths)


def lower_bank_to_dfa(
    patterns: list[LinearPattern],
    state_budget: int | None = None,
    merge_depths: tuple[int, ...] | None = None,
) -> DfaBank | None:
    """Lower one bank's patterns to a bitsplit DFA, or None on blow-up.

    Tries the exact subset construction first; when it exceeds the
    state budget, retries after approximate merging at each depth in
    `merge_depths` (finer first — deeper signatures merge less). Every
    failure falls through; None means the caller keeps the NFA tables.
    """
    budget = _dfa_state_budget(state_budget)
    depths = _dfa_merge_depths(merge_depths)
    nfa, slot_always, slot_empty_ok = _bank_position_nfa(patterns)
    if not nfa.bytes:
        return None  # no device-state patterns: nothing to lower
    P = len(patterns)
    attempts: list[tuple[_PosNfa, bool, int]] = [(nfa, True, 0)]
    for d in depths:
        merged, did = _merge_positions(nfa, d)
        if did:
            attempts.append((merged, False, d))
    for cand_nfa, exact, depth in attempts:
        res = _determinize(cand_nfa, P, budget)
        if res is None:
            continue
        trans, byte_cls, step_accept, end_accept = res
        return DfaBank(
            trans=trans, byte_cls=byte_cls, step_accept=step_accept,
            end_accept=end_accept, slot_always=slot_always,
            slot_empty_ok=slot_empty_ok, num_states=trans.shape[0],
            num_classes=trans.shape[1], num_slots=P,
            num_words=step_accept.shape[1], exact=exact,
            merge_depth=depth)
    return None


def scan_chunk_numpy(bank: NfaBank, data: np.ndarray, lengths: np.ndarray,
                     state: np.ndarray | None = None,
                     t_offset: int = 0) -> np.ndarray:
    """Chunk-carry reference scan: resume the bitwise algebra from a
    carried state word vector.

    `lengths` are GLOBAL row lengths and `t_offset` is the global
    position of data[:, 0]; the anchored injection fires only at global
    t == 0, so feeding a row through consecutive chunks while threading
    `state` must equal one contiguous scan. That seam-invariance is what
    the torn-literal obligation (compiler/obligations.py, `make prove`)
    checks for every compiled body/plan bank.
    """
    B, L = data.shape
    W = bank.num_words
    has_carry = bank.has_carry
    carry_mask = bank.carry_mask
    opt = bank.opt
    if state is None:
        S = np.zeros((B, W), dtype=np.uint32)
    else:
        S = state.astype(np.uint32).copy()
    for tl in range(L):
        t = t_offset + tl
        c = data[:, tl].astype(np.int64)
        bc = bank.byte_table[c]  # [B, W]
        inj = bank.init_unanchored[None, :]
        if t == 0:
            inj = inj | bank.init_anchored[None, :]
        adv = ((S << np.uint32(1)) | inj).astype(np.uint32)
        if has_carry:
            # bit31 of word w-1 advances into bit0 of word w.
            carry = np.zeros_like(S)
            carry[:, 1:] = (S[:, :-1] >> np.uint32(31)) & np.uint32(1)
            adv |= carry & carry_mask
        for p in range(bank.prop_passes):
            x = ((adv & opt) + opt).astype(np.uint32)  # wraps on escape
            adv |= x ^ opt
            if has_carry and p + 1 < bank.prop_passes:
                # Closure escaped past bit31 (add overflow) -> re-inject
                # at bit0 of the next span word and propagate again.
                esc = (x < opt).astype(np.uint32)
                esc_in = np.zeros_like(S)
                esc_in[:, 1:] = esc[:, :-1]
                adv |= esc_in & carry_mask
        S_new = ((adv | (S & bank.rep)) & bc).astype(np.uint32)
        S = np.where((t < lengths)[:, None], S_new, S)
    return S


def extract_numpy(bank: NfaBank, state: np.ndarray,
                  lengths: np.ndarray) -> np.ndarray:
    """Slot extraction from a final scan state: [B, W] -> [B, P] bool."""
    B = state.shape[0]
    W = bank.num_words
    out = np.zeros((B, bank.num_patterns), dtype=bool)
    empty = lengths == 0
    for p, slot in enumerate(bank.slots):
        if slot.always_match:
            out[:, p] = True
            continue
        hit = np.zeros(B, dtype=bool)
        for w, mask in slot.accepts:
            if W and mask:
                hit |= (state[:, w] & np.uint32(mask)) != 0
        if slot.empty_ok:
            hit |= empty
        out[:, p] = hit
    return out


def scan_numpy(bank: NfaBank, data: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Reference bitwise scan in numpy (same algebra as the JAX op).

    data: [B, L] uint8, lengths: [B] -> matched [B, P] bool.
    """
    return extract_numpy(
        bank, scan_chunk_numpy(bank, data, lengths), lengths)
