"""Bit-parallel NFA banks: packing linear patterns into uint32 lanes.

An `NfaBank` holds every contains/regex predicate that scans one request
field (path, url, host, user_agent, ...). Patterns are packed into uint32
words — one guard bit + one bit per position, each pattern confined to a
single word — and executed as extended Shift-And (Glushkov over linear
patterns) with pure bitwise ops:

    inj  = INIT_unanchored | (t == 0 ? INIT_anchored : 0)
    adv  = (S << 1) | inj
    adv |= ((adv & OPT) + OPT) ^ OPT        # skip optional runs (carry trick)
    pre  = adv | (S & REP)                  # self-loops for x* / x+
    S'   = pre & B[c]                       # byte-class transition
    float_matches |= S' & LAST_FLOAT        # accept for non-$ patterns
    ...after the scan: end_matches = S_final & LAST_END   # $ patterns

The optional-skip identity: within a run of consecutive OPT bits, adding
(adv & OPT) to OPT carries through the run; XOR with OPT recovers every
position from the first active bit through one past the run's end —
exactly the Glushkov epsilon-skip closure for linear patterns.

This module builds the (numpy) tables; ops/nfa_scan.py executes them in
JAX; `simulate` is the pure-Python oracle used by differential tests
(pattern semantics are verified three ways: Python `re` (bytes mode) ==
`simulate` == the bit-parallel scan).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .repat import LinearPattern, Pos, Quant, Unsupported

WORD_BITS = 32


def _skippable(p: Pos) -> bool:
    return p.quant in (Quant.OPT, Quant.STAR)


def _repeatable(p: Pos) -> bool:
    return p.quant in (Quant.STAR, Quant.PLUS)


def simulate(lp: LinearPattern, data: bytes) -> bool:
    """Pure-Python Glushkov simulation of one linear pattern (oracle).

    `$` semantics follow Python `re` in bytes mode (the interpreter's
    engine, expr/values.py): it accepts at the end of input AND just
    before one trailing newline.
    """
    m = len(lp.positions)
    if m == 0 or lp.min_len == 0:
        if not (lp.anchor_start and lp.anchor_end):
            return True
        # ^...$ with nothing required: empty input, or empty before a
        # lone trailing newline, or fall through to the NFA (m>0).
        if len(data) == 0 or data == b"\n":
            return True
        if m == 0:
            return False
    last_set = _last_set(lp)
    active: set[int] = set()
    matched = False
    ends_nl = len(data) > 0 and data[-1] == 0x0A
    for t, c in enumerate(data):
        inject = (t == 0) or not lp.anchor_start
        nxt: set[int] = set()
        candidates: set[int] = set()
        if inject:
            candidates |= _closure_from(lp, 0)
        for i in active:
            if _repeatable(lp.positions[i]):
                candidates.add(i)
            if i + 1 < m:
                candidates |= _closure_from(lp, i + 1)
        for i in candidates:
            if c in lp.positions[i].bytes:
                nxt.add(i)
        active = nxt
        if not lp.anchor_end and active & last_set:
            matched = True
        if lp.anchor_end and ends_nl and t == len(data) - 2 and active & last_set:
            matched = True  # accept just before the trailing newline
    if lp.anchor_end:
        return matched or bool(active & last_set)
    return matched


def _closure_from(lp: LinearPattern, start: int) -> set[int]:
    """Positions reachable as 'next consumed' entering at `start`:
    start itself plus everything past a run of skippable positions."""
    out = set()
    i = start
    m = len(lp.positions)
    while i < m:
        out.add(i)
        if _skippable(lp.positions[i]):
            i += 1
        else:
            break
    return out


def _last_set(lp: LinearPattern) -> set[int]:
    """Accept positions: i such that every later position is skippable."""
    out = set()
    for i in range(len(lp.positions) - 1, -1, -1):
        out.add(i)
        if not _skippable(lp.positions[i]):
            break
    return out


@dataclass(frozen=True)
class PatternSlot:
    """Where one pattern lives in the bank + its accept metadata."""

    word: int
    accept_mask: int  # last-set bits
    end_anchored: bool
    always_match: bool  # min_len == 0 and not (^ and $)
    empty_ok: bool  # ^...$ with min_len == 0: matches empty input


@dataclass
class NfaBank:
    """Packed bit-parallel tables for one field's pattern group."""

    num_words: int = 0
    byte_table: np.ndarray = field(
        default_factory=lambda: np.zeros((256, 0), dtype=np.uint32)
    )  # [256, W]
    init_anchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected at t==0 only
    init_unanchored: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # [W] injected every step
    opt: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    rep: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint32))
    last_float: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # accept bits of patterns without $
    last_end: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint32)
    )  # accept bits of $-anchored patterns
    slots: list[PatternSlot] = field(default_factory=list)

    @property
    def num_patterns(self) -> int:
        return len(self.slots)


def build_bank(patterns: list[LinearPattern]) -> NfaBank:
    """Pack linear patterns into an NfaBank (first-fit into uint32 words)."""
    bank = NfaBank()
    word_used: list[int] = []  # bits used per word

    byte_rows: list[dict[int, int]] = []  # per word: byte -> mask
    init_a: list[int] = []
    init_u: list[int] = []
    opt: list[int] = []
    rep: list[int] = []
    last_f: list[int] = []
    last_e: list[int] = []

    for lp in patterns:
        m = len(lp.positions)
        always = lp.min_len == 0 and not (lp.anchor_start and lp.anchor_end)
        empty_ok = lp.min_len == 0 and lp.anchor_start and lp.anchor_end
        if m == 0 or always:
            # Constant or empty-only patterns carry no device state: "" or
            # "a*" unanchored match everything (always); "^$" matches only
            # empty input (empty_ok with accept_mask 0).
            bank.slots.append(
                PatternSlot(word=0, accept_mask=0, end_anchored=lp.anchor_end,
                            always_match=always, empty_ok=empty_ok)
            )
            continue
        need = m + 1  # one guard bit
        if need > WORD_BITS:
            raise Unsupported(f"pattern needs {need} bits > {WORD_BITS}")
        # First-fit placement.
        w = -1
        for idx, used in enumerate(word_used):
            if used + need <= WORD_BITS:
                w = idx
                break
        if w == -1:
            word_used.append(0)
            byte_rows.append({})
            init_a.append(0)
            init_u.append(0)
            opt.append(0)
            rep.append(0)
            last_f.append(0)
            last_e.append(0)
            w = len(word_used) - 1
        base = word_used[w] + 1  # skip guard bit at word_used[w]
        word_used[w] += need

        bit = lambda i: 1 << (base + i)  # noqa: E731
        for i, pos in enumerate(lp.positions):
            for b in pos.bytes:
                byte_rows[w][b] = byte_rows[w].get(b, 0) | bit(i)
            if _skippable(pos):
                opt[w] |= bit(i)
            if _repeatable(pos):
                rep[w] |= bit(i)
        if lp.anchor_start:
            init_a[w] |= bit(0)
        else:
            init_u[w] |= bit(0)
        accept_mask = 0
        for i in _last_set(lp):
            accept_mask |= bit(i)
        if lp.anchor_end:
            last_e[w] |= accept_mask
        else:
            last_f[w] |= accept_mask
        bank.slots.append(
            PatternSlot(word=w, accept_mask=accept_mask,
                        end_anchored=lp.anchor_end, always_match=False,
                        empty_ok=empty_ok)
        )

    W = len(word_used)
    bank.num_words = W
    table = np.zeros((256, W), dtype=np.uint32)
    for w in range(W):
        for b, mask in byte_rows[w].items():
            table[b, w] = mask
    bank.byte_table = table
    bank.init_anchored = np.array(init_a, dtype=np.uint32)
    bank.init_unanchored = np.array(init_u, dtype=np.uint32)
    bank.opt = np.array(opt, dtype=np.uint32)
    bank.rep = np.array(rep, dtype=np.uint32)
    bank.last_float = np.array(last_f, dtype=np.uint32)
    bank.last_end = np.array(last_e, dtype=np.uint32)
    return bank


def scan_numpy(bank: NfaBank, data: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Reference bitwise scan in numpy (same algebra as the JAX op).

    data: [B, L] uint8, lengths: [B] -> matched [B, P] bool.
    """
    B, L = data.shape
    W = bank.num_words
    S = np.zeros((B, W), dtype=np.uint32)
    float_acc = np.zeros((B, W), dtype=np.uint32)
    end_acc = np.zeros((B, W), dtype=np.uint32)
    # `$` accepts at end of input or just before one trailing newline
    # (Python-re semantics; see simulate()).
    ends_nl = np.zeros(B, dtype=bool)
    if L > 0:
        last_byte = data[np.arange(B), np.maximum(lengths - 1, 0)]
        ends_nl = (lengths > 0) & (last_byte == 0x0A)
    for t in range(L):
        c = data[:, t].astype(np.int64)
        bc = bank.byte_table[c]  # [B, W]
        inj = bank.init_unanchored[None, :]
        if t == 0:
            inj = inj | bank.init_anchored[None, :]
        adv = ((S << np.uint32(1)) | inj).astype(np.uint32)
        adv |= ((adv & bank.opt) + bank.opt) ^ bank.opt
        pre = adv | (S & bank.rep)
        S_new = (pre & bc).astype(np.uint32)
        active = (t < lengths)[:, None]
        S = np.where(active, S_new, S)
        float_acc |= np.where(active, S_new & bank.last_float, 0).astype(np.uint32)
        before_nl = (ends_nl & (t == lengths - 2))[:, None]
        end_acc |= np.where(before_nl, S_new & bank.last_end, 0).astype(np.uint32)
    end_acc |= S & bank.last_end
    out = np.zeros((B, bank.num_patterns), dtype=bool)
    empty_like = (lengths == 0) | (ends_nl & (lengths == 1))
    for p, slot in enumerate(bank.slots):
        if slot.always_match:
            out[:, p] = True
            continue
        if slot.end_anchored:
            if bank.num_words == 0:
                hit = np.zeros(B, dtype=bool)
            else:
                hit = (end_acc[:, slot.word] & np.uint32(slot.accept_mask)) != 0
            if slot.empty_ok:
                hit = hit | empty_like
        else:
            hit = (float_acc[:, slot.word] & np.uint32(slot.accept_mask)) != 0
        out[:, p] = hit
    return out
