"""Regex subset -> linear NFA pattern programs for bit-parallel execution.

The TPU verdict engine executes regex/contains predicates as extended
Shift-And (bit-parallel Glushkov over *linear* patterns): a pattern is a
sequence of byte-class positions, each with a quantifier ONE / OPT (x?) /
STAR (x*) / PLUS (x+), plus start/end anchors. This covers the WAF staples
(literals, classes, ., \\d\\w\\s, quantifiers, bounded repeats, small
alternations) with pure uint32 VPU ops on device; anything outside the
subset (nested quantified groups, backrefs, lookaround, wide expansions)
is reported Unsupported and the owning rule falls back to host
interpretation — mirroring the fail-safe split in SURVEY.md §7 "Hard
parts" ("fallback to host for pathological patterns").

Byte semantics: patterns compile against UTF-8 bytes, consistent with the
interpreter's bytes-mode `re` (expr/values.py Regex) and with the byte
tensors the engine scans. `.` matches any byte except \\n. The ASCII-only
perl classes match Rust regex's (?-u) / RE2 bytes behavior.

Alternation handling: a top-level alternation compiles to multiple linear
patterns OR-ed at the predicate level; group alternations of single
chars/classes merge into one byte class; short multi-char group
alternations expand by cross product (capped).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable

# Positions per linear pattern. Multi-word packing (compiler/nfa.py
# pack_span) spreads one pattern over up to MAX_SCAN_BITS/32 uint32
# words with cross-word carry, so patterns are no longer capped at one
# word; the binding limit is nfa.MAX_SCAN_BITS on the EXPANDED footprint
# (checked at lowering), this is just a sanity bound before expansion.
MAX_POSITIONS = 126  # 1 guard + 126 positions + 1 sticky = 128 bits
MAX_CROSS_PRODUCT = 48  # cap on alternation expansion (alternatives/rule)
MAX_REPEAT_EXPANSION = 96


class Unsupported(Exception):
    """Pattern is outside the bit-parallel subset -> host fallback."""


class Quant(enum.Enum):
    ONE = "one"
    OPT = "opt"  # x?
    STAR = "star"  # x*
    PLUS = "plus"  # x+


@dataclass(frozen=True)
class Pos:
    """One pattern position: a byte class + quantifier."""

    bytes: frozenset[int]
    quant: Quant = Quant.ONE


@dataclass
class LinearPattern:
    """A linear NFA: positions consumed left to right.

    boundary_start/_end implement leading/trailing \\b (the CRS staple
    `\\bunion\\b`): a leading \\b admits a match only when the byte before
    the first consumed position has the opposite word-ness of that
    position's class; a trailing \\b requires the byte after the last
    consumed position (or end of input) to flip word-ness. Mid-pattern
    \\b stays Unsupported (host fallback).
    """

    positions: list[Pos] = field(default_factory=list)
    anchor_start: bool = False
    anchor_end: bool = False
    # Absolute end-of-input anchor (\z / \Z, and the lowering of a
    # mid-pattern $ whose suffix consumed the trailing newline): accepts
    # at the final byte only, WITHOUT $'s before-trailing-\n tolerance.
    anchor_end_abs: bool = False
    boundary_start: bool = False
    boundary_end: bool = False
    never_match: bool = False  # statically unsatisfiable (e.g. a\bb)

    @property
    def min_len(self) -> int:
        return sum(1 for p in self.positions if p.quant in (Quant.ONE, Quant.PLUS))

    @property
    def matches_empty(self) -> bool:
        return self.min_len == 0


def literal_pattern(text: bytes, case_insensitive: bool = False) -> LinearPattern:
    """A plain substring pattern (for `contains`/`starts_with`/... lowering)."""
    positions = []
    for b in text:
        positions.append(Pos(bytes=_fold_byte(b) if case_insensitive else frozenset([b])))
    if len(positions) > MAX_POSITIONS:
        raise Unsupported(f"literal longer than {MAX_POSITIONS} bytes")
    return LinearPattern(positions=positions)


def compile_regex(pattern: str) -> list[LinearPattern]:
    """Compile a regex into alternative linear patterns (match = any).

    Raises Unsupported for constructs outside the subset.
    """
    try:
        data = pattern.encode("latin-1")  # canonical byte view (expr/values.py)
    except UnicodeEncodeError:
        raise Unsupported("pattern contains non-byte characters")
    ci = False
    # Leading inline flags: (?i) / (?s) / (?i:...) not handled beyond (?i)(?s).
    while True:
        if data.startswith(b"(?i)"):
            ci = True
            data = data[4:]
        elif data.startswith(b"(?s)"):
            # We treat `.` as not matching \n; (?s) changes that.
            raise Unsupported("(?s) dotall flag")
        elif data.startswith(b"(?is)") or data.startswith(b"(?si)"):
            raise Unsupported("(?s) dotall flag")
        else:
            break
    parser = _Parser(data, ci)
    alts = parser.parse_alternation(top=True)
    if parser.i < len(parser.data):
        raise Unsupported(f"unexpected {chr(parser.data[parser.i])!r}")
    expanded: list[list[_Item]] = []
    for alt in alts:
        expanded.extend(_expand_alts(alt, at_start=True))
    if len(expanded) > MAX_CROSS_PRODUCT:
        raise Unsupported("too many alternation branches")
    # Anchor/boundary lowering pre-passes (each may fan one alternative
    # out into several, or statically eliminate it):
    #   mid-pattern $  -> end-anchored alternatives (see _lower_mid_dollar)
    #   \b next to an optional position -> case-split on its presence
    final: list[list[_Item]] = []
    for items in expanded:
        for v in _lower_mid_dollar(items):
            final.extend(_split_boundary_optionals(v))
    if len(final) > MAX_CROSS_PRODUCT:
        raise Unsupported("too many alternation branches")
    out = []
    for alt in final:
        lp = _to_linear(alt)
        if len(lp.positions) > MAX_POSITIONS:
            raise Unsupported(f"pattern expands to >{MAX_POSITIONS} positions")
        out.append(lp)
    if not out:
        # Every alternative was statically unsatisfiable.
        out.append(LinearPattern(never_match=True))
    return out


def _expand_alts(items: list[_Item],
                 at_start: bool = False) -> list[list[_Item]]:
    """Cross-product expansion of group alternations into flat sequences.

    `at_start` is True when nothing in the overall pattern can precede
    `items` (compile_regex's top-level call; propagated through groups
    while the accumulated prefix is still empty). It licenses the repeat
    truncation below.
    """
    seqs: list[list[_Item]] = [[]]
    for item in items:
        start_here = at_start and all(len(s) == 0 for s in seqs)
        if item.alts is not None:
            branches: list[list[_Item]] = []
            for alt in item.alts:
                branches.extend(_expand_alts(alt, start_here))
            new_seqs = []
            for seq in seqs:
                for branch in branches:
                    new_seqs.append(seq + branch)
            seqs = new_seqs
        elif item.seq is not None and (item.min_rep, item.max_rep) == (1, 1):
            inner = _expand_alts(item.seq, start_here)
            new_seqs = []
            for seq in seqs:
                for branch in inner:
                    new_seqs.append(seq + branch)
            seqs = new_seqs
        elif item.seq is not None:
            # Quantified multi-position group Y{lo,hi} -> alternation of
            # exact repetition counts. With NOTHING before it in an
            # unanchored search pattern, Y{lo,hi}X is match-equivalent to
            # Y{lo}X (any occurrence of Y{k}X, k >= lo, contains a
            # Y{lo}X occurrence over its last lo repetitions), so the
            # fan-out collapses to one branch — the lowering that keeps
            # CRS-style `(\.\./){3,12}etc/...` on device.
            lo, hi = item.min_rep, item.max_rep
            if start_here:
                hi = lo
            if hi == -1:
                raise Unsupported("unbounded repeat of multi-char group")
            if hi - lo + 1 > MAX_CROSS_PRODUCT or hi > MAX_REPEAT_EXPANSION:
                raise Unsupported("repeat expansion too large")
            branches = []
            for k in range(lo, hi + 1):
                branches.extend(_expand_alts(list(item.seq) * k, start_here))
            new_seqs = []
            for seq in seqs:
                for branch in branches:
                    new_seqs.append(seq + branch)
            seqs = new_seqs
        else:
            seqs = [seq + [item] for seq in seqs]
        if len(seqs) > MAX_CROSS_PRODUCT:
            raise Unsupported("too many alternation branches")
    return seqs


def _item_nullable(item: "_Item") -> bool:
    """Can this position item consume zero bytes?"""
    if item.pos is None:
        return False
    if (item.min_rep, item.max_rep) == (1, 1):
        return item.pos.quant in (Quant.OPT, Quant.STAR)
    return item.min_rep == 0


def _item_can_consume_one(item: "_Item") -> bool:
    """Can this position item consume exactly one byte?"""
    if item.pos is None:
        return False
    if (item.min_rep, item.max_rep) == (1, 1):
        return True  # ONE/OPT/STAR/PLUS all admit a single repetition
    return item.min_rep <= 1 and (item.max_rep == -1 or item.max_rep >= 1)


def _lower_mid_dollar(items: list["_Item"]) -> list[list["_Item"]]:
    """Lower a mid-pattern `$` into end-anchored alternatives.

    `$` asserts (Python-re bytes semantics, the parity oracle) that the
    current position is end-of-input or just before one trailing '\\n'.
    For X $ Y that leaves exactly two ways Y can succeed:

      * at end-of-input — Y must match empty        -> alternative X$
      * before the trailing newline — Y must consume exactly that '\\n'
        (and nothing else)                          -> alternative X'\\n'
        anchored at ABSOLUTE end (no further \\n tolerance: a$\\n must
        not match "a\\n\\n")

    Returns [] when neither applies (the pattern is unsatisfiable) and
    [items] unchanged when there is no mid-pattern $ or the suffix has
    shapes we leave to host fallback.
    """
    idx = None
    for i, it in enumerate(items):
        if it.anchor == "$" and i != len(items) - 1:
            idx = i
            break
    if idx is None:
        return [items]
    x_items = items[:idx]
    y_items = items[idx + 1:]
    if any(it.anchor in ("^", "b", "A", "Z") for it in y_items):
        return [items]  # _to_linear reports these Unsupported
    y_pos = [it for it in y_items if it.pos is not None]
    alts: list[list[_Item]] = []
    if all(_item_nullable(it) for it in y_pos):
        # Further $ items in Y hold trivially at either end position.
        alts.append(x_items + [_Item(anchor="$")])
    else:
        for j, it in enumerate(y_items):
            if it.pos is None or 0x0A not in it.pos.bytes or \
                    not _item_can_consume_one(it):
                continue
            rest = [k for k in y_items[:j] + y_items[j + 1:]
                    if k.pos is not None]
            if all(_item_nullable(k) for k in rest):
                alts.append(x_items +
                            [_Item(pos=Pos(bytes=frozenset([0x0A]))),
                             _Item(anchor="Z")])
                break
    return alts


def _leading_edge_optional(item: "_Item") -> bool:
    # An item's first expanded position is optional exactly when the
    # item can consume zero bytes.
    return _item_nullable(item)


def _trailing_edge_optional(item: "_Item") -> bool:
    if (item.min_rep, item.max_rep) == (1, 1):
        return item.pos.quant in (Quant.OPT, Quant.STAR)
    return item.max_rep != -1 and item.max_rep > item.min_rep


def _split_leading(item: "_Item") -> list[list["_Item"]]:
    """Case-split an optional-leading-edge item: absent | present."""
    if (item.min_rep, item.max_rep) == (1, 1):
        q = Quant.ONE if item.pos.quant == Quant.OPT else Quant.PLUS
        return [[], [_Item(pos=Pos(bytes=item.pos.bytes, quant=q))]]
    # {0,hi} -> absent | {1,hi}
    return [[], [_Item(pos=item.pos, min_rep=1, max_rep=item.max_rep)]]


def _split_trailing(item: "_Item") -> list[list["_Item"]]:
    """Case-split an optional-trailing-edge item into exact counts."""
    if (item.min_rep, item.max_rep) == (1, 1):
        q = Quant.ONE if item.pos.quant == Quant.OPT else Quant.PLUS
        return [[], [_Item(pos=Pos(bytes=item.pos.bytes, quant=q))]]
    return [([_Item(pos=item.pos, min_rep=k, max_rep=k)] if k else [])
            for k in range(item.min_rep, item.max_rep + 1)]


def _split_boundary_optionals(items: list["_Item"]) -> list[list["_Item"]]:
    """Case-split positions with an optional edge adjacent to a \\b.

    A \\b's truth depends on the word-ness of its immediate neighbors;
    when a neighbor position may be skipped the neighbor identity is
    dynamic, which the static mid-\\b lowering in _to_linear can't
    express. Splitting on the optional's presence makes every branch
    statically decidable: select\\b\\s*\\( becomes select\\( | select\\s+\\(.
    """
    for i, it in enumerate(items):
        if it.anchor != "b":
            continue
        nxt = items[i + 1] if i + 1 < len(items) else None
        prv = items[i - 1] if i > 0 else None
        repl: list[list[_Item]] | None = None
        lo_i = hi_i = i
        if nxt is not None and nxt.pos is not None and \
                _leading_edge_optional(nxt):
            repl = _split_leading(nxt)
            lo_i, hi_i = i + 1, i + 2
        elif prv is not None and prv.pos is not None and \
                _trailing_edge_optional(prv):
            repl = _split_trailing(prv)
            lo_i, hi_i = i - 1, i
        if repl is not None:
            out: list[list[_Item]] = []
            for r in repl:
                out.extend(_split_boundary_optionals(
                    items[:lo_i] + r + items[hi_i:]))
                if len(out) > MAX_CROSS_PRODUCT:
                    raise Unsupported("too many alternation branches")
            return out
    return [items]


# -- internal IR before linearization ---------------------------------------
# An "item" is (Pos | marker) with quantifier applied during linearization.
# Alternatives are lists of items; _Seq holds expanded sequences.


@dataclass
class _Item:
    pos: Pos | None = None  # single position
    seq: list["_Item"] | None = None  # inlined group sequence
    alts: list[list["_Item"]] | None = None  # group alternation branches
    min_rep: int = 1
    max_rep: int = 1  # -1 = unbounded
    anchor: str | None = None  # "^" or "$"


def _to_linear(items: list[_Item]) -> LinearPattern:
    lp = LinearPattern()
    flat = _flatten(items)
    pending_mid = False
    for idx, item in enumerate(flat):
        if item.anchor in ("^", "A"):
            if idx != 0:
                raise Unsupported("^ not at pattern start")
            lp.anchor_start = True
            continue
        if item.anchor == "$":
            # Mid-pattern $ is lowered by _lower_mid_dollar before this
            # pass; reaching here mid-pattern means an unhandled suffix
            # shape (e.g. \b after $) -> host fallback.
            if idx != len(flat) - 1:
                raise Unsupported("$ not at pattern end")
            lp.anchor_end = True
            continue
        if item.anchor == "Z":
            if idx != len(flat) - 1:
                raise Unsupported("\\z not at pattern end")
            lp.anchor_end_abs = True
            continue
        if item.anchor == "b":
            # \b is "leading" before any position (e.g. ^\bfoo) and
            # "trailing" when only anchors follow (e.g. foo\b$).
            if not lp.positions:
                lp.boundary_start = True
                continue
            if all(it.anchor is not None for it in flat[idx + 1:]):
                lp.boundary_end = True
                continue
            pending_mid = True
            continue
        assert item.pos is not None
        new_positions = _expand_quant(item)
        if pending_mid and new_positions:
            # Mid-pattern \b between uniform-wordness neighbors is
            # statically decidable: opposite word-ness -> the boundary
            # always holds (drop it); same word-ness -> unsatisfiable.
            prev = lp.positions[-1]
            nxt = new_positions[0]
            if prev.quant in (Quant.OPT, Quant.STAR) or nxt.quant in (
                    Quant.OPT, Quant.STAR):
                raise Unsupported("\\b next to optional position")
            if not (_uniform_wordness(prev.bytes)
                    and _uniform_wordness(nxt.bytes)):
                raise Unsupported("\\b between mixed word/non-word classes")
            prev_word = next(iter(prev.bytes)) in _WORD
            next_word = next(iter(nxt.bytes)) in _WORD
            if prev_word == next_word:
                lp.never_match = True
            pending_mid = False
        lp.positions.extend(new_positions)
        if len(lp.positions) > MAX_POSITIONS:
            raise Unsupported(f"pattern expands to >{MAX_POSITIONS} positions")
    if pending_mid:
        raise Unsupported("dangling \\b")
    _validate_boundaries(lp)
    return lp


def _validate_boundaries(lp: LinearPattern) -> None:
    """Boundary patterns need unambiguous word-ness at the edges, and
    edge positions must be required (a skippable edge changes which
    class sits at the boundary)."""
    if not (lp.boundary_start or lp.boundary_end):
        return
    if not lp.positions:
        raise Unsupported("bare \\b")
    if lp.boundary_start:
        first = lp.positions[0]
        if first.quant != Quant.ONE and first.quant != Quant.PLUS:
            raise Unsupported("\\b before optional position")
        if not _uniform_wordness(first.bytes):
            raise Unsupported("\\b before mixed word/non-word class")
    if lp.boundary_end:
        last = lp.positions[-1]
        if last.quant != Quant.ONE and last.quant != Quant.PLUS:
            raise Unsupported("\\b after optional position")
        if not _uniform_wordness(last.bytes):
            raise Unsupported("\\b after mixed word/non-word class")


def is_word_byte(b: int) -> bool:
    return b in _WORD


def _uniform_wordness(cls: frozenset[int]) -> bool:
    kinds = {b in _WORD for b in cls}
    return len(kinds) == 1


def _flatten(items: list[_Item]) -> list[_Item]:
    out: list[_Item] = []
    for item in items:
        if item.alts is not None:
            # Alternations survive only under quantified groups; those are
            # rewritten to alternation in _parse_quant_group, so reaching
            # here means a shape we can't linearize.
            raise Unsupported("alternation inside quantified group")
        if item.seq is not None:
            # _expand_alts inlined all (1,1) groups; a quantified group
            # here was already rewritten to an alternation.
            assert (item.min_rep, item.max_rep) == (1, 1)
            out.extend(_flatten(item.seq))
        else:
            out.append(item)
    return out


def _expand_quant(item: _Item) -> list[Pos]:
    """Expand a single-position item with {min,max} into positions."""
    pos = item.pos
    assert pos is not None
    lo, hi = item.min_rep, item.max_rep
    if (lo, hi) == (1, 1):
        return [pos]
    # {m,n} repeats only attach to unquantified positions (parser invariant).
    assert pos.quant == Quant.ONE
    base = Pos(bytes=pos.bytes)
    out: list[Pos] = []
    if hi == -1:
        # x{n,} -> n-1 required + one PLUS (or STAR for n==0).
        if lo == 0:
            out.append(Pos(bytes=pos.bytes, quant=Quant.STAR))
        else:
            out.extend([base] * (lo - 1))
            out.append(Pos(bytes=pos.bytes, quant=Quant.PLUS))
    else:
        if hi < lo:
            raise Unsupported("bad repeat range")
        if hi > MAX_REPEAT_EXPANSION:
            raise Unsupported("repeat expansion too large")
        out.extend([base] * lo)
        out.extend([Pos(bytes=pos.bytes, quant=Quant.OPT)] * (hi - lo))
    return out


# -- parser ------------------------------------------------------------------

_ANY = frozenset(range(256)) - frozenset([0x0A])  # '.' excludes \n
_DIGITS = frozenset(range(0x30, 0x3A))
_WORD = (
    frozenset(range(0x30, 0x3A))
    | frozenset(range(0x41, 0x5B))
    | frozenset(range(0x61, 0x7B))
    | frozenset([0x5F])
)
_SPACE = frozenset([0x09, 0x0A, 0x0B, 0x0C, 0x0D, 0x20])
_ALL = frozenset(range(256))


MAX_WINDOW_POSITIONS = 24  # conv kernel width cap for window lowering


def to_window(lp: LinearPattern):
    """Try to express a linear pattern as a fixed-length window pattern
    for the MXU correlation matcher (ops/window_match.py). Returns a
    WindowPattern or None.

    Eligible: unanchored, no word boundaries, and — after stripping
    leading/trailing optional runs, which is exact under search
    semantics (an unanchored pattern matches iff its mandatory core
    does; optional edges can always consume nothing) — every position
    is mandatory and single-byte (or an upper/lower fold pair, or a
    truly-any byte class). Classes like `.` (everything but \\n) or
    ranges stay on the NFA path: the zero-weight window position would
    accept bytes the class excludes.
    """
    from ..ops.window_match import ANY, FOLD, RAW, WindowPattern

    if (lp.never_match or lp.anchor_start or lp.anchor_end
            or lp.anchor_end_abs or lp.boundary_start or lp.boundary_end):
        return None
    positions = list(lp.positions)
    out: list[tuple[int, int]] = []
    lo = 0
    hi = len(positions)
    while lo < hi and positions[lo].quant in (Quant.OPT, Quant.STAR):
        lo += 1
    while hi > lo and positions[hi - 1].quant in (Quant.OPT, Quant.STAR):
        hi -= 1
    for k in range(lo, hi):
        pos = positions[k]
        quant = pos.quant
        if quant == Quant.PLUS and (k == lo or k == hi - 1):
            quant = Quant.ONE  # edge x+ keeps one mandatory x; the
            # repetition extends the match without gating it
        if quant != Quant.ONE:
            return None
        cls = pos.bytes
        if len(cls) == 1:
            out.append((RAW, next(iter(cls))))
        elif len(cls) == 256:
            out.append((ANY, 0))
        elif len(cls) == 2:
            a, b = sorted(cls)
            if b == a + 0x20 and 0x41 <= a <= 0x5A:
                out.append((FOLD, b))  # store the lowercase byte
            else:
                return None
        else:
            return None
    if len(out) > MAX_WINDOW_POSITIONS:
        return None
    return WindowPattern(positions=tuple(out))


def _fold_byte(b: int) -> frozenset[int]:
    if 0x41 <= b <= 0x5A:
        return frozenset([b, b + 0x20])
    if 0x61 <= b <= 0x7A:
        return frozenset([b, b - 0x20])
    return frozenset([b])


def _fold_class(cls: frozenset[int]) -> frozenset[int]:
    out = set(cls)
    for b in cls:
        out |= _fold_byte(b)
    return frozenset(out)


class _Parser:
    def __init__(self, data: bytes, ci: bool):
        self.data = data
        self.i = 0
        self.ci = ci

    def parse_alternation(self, top: bool = False) -> list[list[_Item]]:
        """Returns list of alternative item sequences."""
        alts: list[list[_Item]] = [[]]
        while self.i < len(self.data):
            c = self.data[self.i]
            if c == ord("|"):
                self.i += 1
                alts.append([])
                continue
            if c == ord(")"):
                if top:
                    raise Unsupported("unbalanced )")
                break
            item = self.parse_item()
            if item is not None:
                alts[-1].append(item)
        if len(alts) > MAX_CROSS_PRODUCT:
            raise Unsupported("too many alternation branches")
        return alts

    def parse_item(self) -> _Item | None:
        c = self.data[self.i]
        if c == ord("^"):
            self.i += 1
            return _Item(anchor="^")
        if c == ord("$"):
            self.i += 1
            return _Item(anchor="$")
        if self.data[self.i : self.i + 2] == rb"\b":
            self.i += 2
            return _Item(anchor="b")
        if self.data[self.i : self.i + 2] == rb"\A":
            self.i += 2
            return _Item(anchor="A")
        if self.data[self.i : self.i + 2] == rb"\Z":
            # Python-re \Z: absolute end of input (no trailing-\n grace).
            # \z stays Unsupported — it is a re.error in the oracle.
            self.i += 2
            return _Item(anchor="Z")
        if c == ord("("):
            return self._parse_group()
        atom = self._parse_atom()
        return self._parse_quant(atom)

    def _parse_group(self) -> _Item:
        assert self.data[self.i] == ord("(")
        self.i += 1
        if self.data[self.i : self.i + 2] == b"?:":
            self.i += 2
        elif self.data[self.i : self.i + 1] == b"?":
            raise Unsupported("special group (?...)")
        alts = self.parse_alternation()
        if self.i >= len(self.data) or self.data[self.i] != ord(")"):
            raise Unsupported("unbalanced (")
        self.i += 1
        if len(alts) == 1:
            item = _Item(seq=alts[0])
        else:
            merged = _merge_single_char_alts(alts)
            if merged is not None:
                item = _Item(pos=merged)
            else:
                # Multi-char alternation inside a group: expanded by cross
                # product in _expand_alts (unquantified groups only).
                item = _Item(alts=alts)
        return self._parse_quant_group(item)

    def _parse_quant_group(self, item: _Item) -> _Item:
        quant = self._peek_quant()
        if quant is None:
            return item
        lo, hi, lazy = quant
        if lazy:
            raise Unsupported("lazy quantifier")
        # A group that merged to one byte class ((a|b)+) or holds a single
        # position ((x){2,4}) quantifies that position directly.
        single = item.pos if item.pos is not None else None
        if single is None and item.seq is not None and len(item.seq) == 1 \
                and item.seq[0].pos is not None \
                and item.seq[0].pos.quant == Quant.ONE \
                and (item.seq[0].min_rep, item.seq[0].max_rep) == (1, 1):
            single = item.seq[0].pos
        if single is not None and single.quant == Quant.ONE:
            if (lo, hi) == (0, 1):
                return _Item(pos=Pos(bytes=single.bytes, quant=Quant.OPT))
            if (lo, hi) == (0, -1):
                return _Item(pos=Pos(bytes=single.bytes, quant=Quant.STAR))
            if (lo, hi) == (1, -1):
                return _Item(pos=Pos(bytes=single.bytes, quant=Quant.PLUS))
            return _Item(pos=single, min_rep=lo, max_rep=hi)
        # Multi-position group X{lo,hi}: per-position quantifiers cannot
        # express "skip the whole group" ((abc)? as a?b?c? would wrongly
        # match "ac"). Keep it as a quantified sequence; _expand_alts
        # rewrites it to an alternation of exact repetition counts
        # (X{0,2} -> ( | X | XX )) with positional context — a repeat
        # with nothing before it truncates to {lo} by search equivalence.
        body = item.seq if item.seq is not None else [_Item(alts=item.alts)]
        return _Item(seq=body, min_rep=lo, max_rep=hi)

    def _parse_quant(self, pos: Pos) -> _Item:
        quant = self._peek_quant()
        if quant is None:
            return _Item(pos=pos)
        lo, hi, lazy = quant
        if lazy:
            raise Unsupported("lazy quantifier")
        if (lo, hi) == (0, 1):
            return _Item(pos=Pos(bytes=pos.bytes, quant=Quant.OPT))
        if (lo, hi) == (0, -1):
            return _Item(pos=Pos(bytes=pos.bytes, quant=Quant.STAR))
        if (lo, hi) == (1, -1):
            return _Item(pos=Pos(bytes=pos.bytes, quant=Quant.PLUS))
        return _Item(pos=pos, min_rep=lo, max_rep=hi)

    def _peek_quant(self) -> tuple[int, int, bool] | None:
        if self.i >= len(self.data):
            return None
        c = self.data[self.i]
        lo: int
        hi: int
        if c == ord("?"):
            self.i += 1
            lo, hi = 0, 1
        elif c == ord("*"):
            self.i += 1
            lo, hi = 0, -1
        elif c == ord("+"):
            self.i += 1
            lo, hi = 1, -1
        elif c == ord("{"):
            j = self.data.find(b"}", self.i)
            if j == -1:
                raise Unsupported("unbalanced {")
            body = self.data[self.i + 1 : j]
            try:
                if b"," in body:
                    lo_s, hi_s = body.split(b",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s.strip() else -1
                else:
                    lo = hi = int(body)
            except ValueError:
                raise Unsupported(f"bad repeat {body!r}")
            self.i = j + 1
        else:
            return None
        lazy = False
        if self.i < len(self.data) and self.data[self.i] == ord("?"):
            lazy = True
            self.i += 1
        if self.i < len(self.data) and self.data[self.i] in b"?*+{":
            raise Unsupported("stacked quantifiers")
        return lo, hi, lazy

    def _parse_atom(self) -> Pos:
        c = self.data[self.i]
        if c == ord("."):
            self.i += 1
            return Pos(bytes=_ANY)
        if c == ord("["):
            return self._parse_class()
        if c == ord("\\"):
            cls = self._parse_escape()
            return Pos(bytes=_fold_class(cls) if self.ci else cls)
        if c in b"*+?{":
            raise Unsupported("quantifier with nothing to repeat")
        self.i += 1
        return Pos(bytes=_fold_byte(c) if self.ci else frozenset([c]))

    def _parse_escape(self) -> frozenset[int]:
        assert self.data[self.i] == ord("\\")
        self.i += 1
        if self.i >= len(self.data):
            raise Unsupported("trailing backslash")
        c = self.data[self.i]
        self.i += 1
        simple = {
            ord("d"): _DIGITS,
            ord("D"): _ALL - _DIGITS,
            ord("w"): _WORD,
            ord("W"): _ALL - _WORD,
            ord("s"): _SPACE,
            ord("S"): _ALL - _SPACE,
            ord("n"): frozenset([0x0A]),
            ord("r"): frozenset([0x0D]),
            ord("t"): frozenset([0x09]),
            ord("f"): frozenset([0x0C]),
            ord("v"): frozenset([0x0B]),
            ord("0"): frozenset([0x00]),
        }
        if c in simple:
            return simple[c]
        if c == ord("x"):
            digits = self.data[self.i : self.i + 2]
            # int(.., 16) would accept '+1'/'-1'/' 1'; require hex digits
            # so invalid escapes reject like the re/Rust oracles do.
            if len(digits) != 2 or not all(d in b"0123456789abcdefABCDEF"
                                           for d in digits):
                raise Unsupported("bad \\x escape")
            self.i += 2
            return frozenset([int(digits, 16)])
        if c == ord("b"):
            # Only reachable from class context ([\b] is backspace in re);
            # top-level \b is handled as a boundary item in parse_item.
            return frozenset([0x08])
        if c in b"BAZz":
            raise Unsupported(f"\\{chr(c)} boundary assertion")
        if c in b"123456789":
            raise Unsupported("backreference")
        # Any other letter escape is invalid in the oracle (Python re:
        # "bad escape") or has semantics we don't implement — never treat
        # it as a literal, or device and host would diverge.
        if (0x41 <= c <= 0x5A) or (0x61 <= c <= 0x7A):
            raise Unsupported(f"escape \\{chr(c)}")
        # Escaped punctuation: literal byte.
        return frozenset([c])

    def _parse_class(self) -> Pos:
        assert self.data[self.i] == ord("[")
        self.i += 1
        negate = False
        if self.i < len(self.data) and self.data[self.i] == ord("^"):
            negate = True
            self.i += 1
        members: set[int] = set()
        first = True
        while self.i < len(self.data):
            c = self.data[self.i]
            if c == ord("]") and not first:
                self.i += 1
                cls = frozenset(members)
                # Fold BEFORE negation: (?i)[^a] excludes both cases; folding
                # after negation would re-add the excluded letters.
                if self.ci:
                    cls = _fold_class(cls)
                if negate:
                    cls = _ALL - cls
                return Pos(bytes=cls)
            first = False
            if c == ord("\\"):
                sub = self._parse_escape()
                if len(sub) == 1 and self._peek_range():
                    members |= self._finish_range(next(iter(sub)))
                else:
                    members |= sub
                continue
            if c == ord("[") and self.data[self.i : self.i + 2] == b"[:":
                raise Unsupported("POSIX class")
            self.i += 1
            if self._peek_range():
                members |= self._finish_range(c)
            else:
                members.add(c)
        raise Unsupported("unbalanced [")

    def _peek_range(self) -> bool:
        return (
            self.i + 1 < len(self.data)
            and self.data[self.i] == ord("-")
            and self.data[self.i + 1] != ord("]")
        )

    def _finish_range(self, lo: int) -> set[int]:
        self.i += 1  # consume '-'
        c = self.data[self.i]
        if c == ord("\\"):
            sub = self._parse_escape()
            if len(sub) != 1:
                raise Unsupported("class range with multi-byte escape")
            hi = next(iter(sub))
        else:
            hi = c
            self.i += 1
        if hi < lo:
            raise Unsupported("reversed class range")
        return set(range(lo, hi + 1))


def _merge_single_char_alts(alts: list[list[_Item]]) -> Pos | None:
    """(a|b|c) where each branch is one unquantified position -> one class."""
    members: set[int] = set()
    for alt in alts:
        if len(alt) != 1:
            return None
        item = alt[0]
        if item.pos is None or item.min_rep != 1 or item.max_rep != 1:
            return None
        if item.pos.quant != Quant.ONE:
            return None
        members |= item.pos.bytes
    return Pos(bytes=frozenset(members))


# -- necessary literal-factor extraction (prefilter cascade) ------------------
#
# The verdict cascade (docs/PREFILTER.md, ISSUE 4) gates the serial NFA
# scan banks behind a cheap packed shift-AND pass over *necessary
# factors*: for each pattern, a sequence of byte classes that must
# appear CONSECUTIVELY in any input the pattern matches. If the factor
# is absent from a request's field bytes, the pattern cannot match —
# the prefilter may therefore PRUNE (skip/compact the exact scan) but
# never decide, which is the whole soundness argument. Patterns with no
# sufficiently selective factor are reported None and the caller marks
# them always-scan (their bank keeps running unconditionally).
#
# Which windows of a linear pattern are necessary consecutive runs?
# Position p consumes k_p bytes of class C_p with k_p == 1 for ONE,
# k_p >= 1 for PLUS, k_p >= 0 for OPT/STAR. A window [i..j] therefore
# yields a guaranteed consecutive occurrence of C_i..C_j exactly when
# every INTERIOR position is ONE (one byte each) and the EDGES are ONE
# or PLUS (take the last byte of the left PLUS run / the first byte of
# the right PLUS run). OPT/STAR anywhere in the window breaks the
# guarantee (the position may be absent). Anchors and \b constraints
# only restrict matches further, so they never invalidate a factor.

FACTOR_MAX_LEN = 12  # positions per factor (packed into uint32 lanes)
FACTOR_MAX_CLASS = 16  # byte-class size cap per factor position
# Selectivity floor: product of 256/|class| over the window must reach
# the equivalent of two exact bytes, or the factor would fire on nearly
# every request (a 1-byte factor like "/" gates nothing and still costs
# table bits).
FACTOR_MIN_SCORE = 256.0 ** 2


def _factor_windows(positions: list[Pos]) -> list[list[Pos]]:
    """Maximal candidate windows: runs of ONE/PLUS positions, cut so
    PLUS appears only at window edges (see the rule above)."""
    segs: list[list[Pos]] = []
    cur: list[Pos] = []
    for p in positions:
        if p.quant in (Quant.ONE, Quant.PLUS):
            cur.append(p)
        elif cur:
            segs.append(cur)
            cur = []
    if cur:
        segs.append(cur)
    windows: list[list[Pos]] = []
    for seg in segs:
        start = 0
        for i, p in enumerate(seg):
            if p.quant == Quant.PLUS and i > start:
                windows.append(seg[start:i + 1])  # PLUS as right edge
                start = i
        windows.append(seg[start:])
    return windows


def _best_subwindow(win: list[Pos]):
    """Most selective contiguous subwindow of length <= FACTOR_MAX_LEN:
    (score, length, classes) or None when no position qualifies."""
    best = None
    n = len(win)
    for i in range(n):
        score = 1.0
        for j in range(i, min(i + FACTOR_MAX_LEN, n)):
            cls = win[j].bytes
            if len(cls) > FACTOR_MAX_CLASS:
                break
            score *= 256.0 / len(cls)
            cand = (score, j - i + 1,
                    tuple(p.bytes for p in win[i:j + 1]))
            if best is None or (cand[0], cand[1]) > (best[0], best[1]):
                best = cand
    return best


def necessary_factor(
        lp: LinearPattern) -> tuple[frozenset[int], ...] | None:
    """The pattern's best necessary factor: a tuple of byte classes that
    appears consecutively in EVERY input the pattern matches, chosen to
    maximize selectivity (product of 256/|class|). Returns None when the
    pattern may match without any such run — never_match (no matches to
    gate), min_len == 0 (may match empty input), or no window clearing
    the FACTOR_MIN_SCORE selectivity floor."""
    if lp.never_match or lp.min_len == 0:
        return None
    best = None
    for win in _factor_windows(lp.positions):
        cand = _best_subwindow(win)
        if cand is not None and (
                best is None or (cand[0], cand[1]) > (best[0], best[1])):
            best = cand
    if best is None or best[0] < FACTOR_MIN_SCORE:
        return None
    return best[2]


def factor_present(factor: tuple[frozenset[int], ...], data: bytes) -> bool:
    """Naive host-side factor containment (the prefilter oracle used by
    differential tests; the device kernel is ops/prefilter.py)."""
    m = len(factor)
    if m == 0:
        return True
    for i in range(len(data) - m + 1):
        if all(data[i + j] in factor[j] for j in range(m)):
            return True
    return False


# -- footprint extension (halo enablement) ------------------------------------
#
# The halo-parallel scans (ops/nfa_scan.halo_split_scan within a device,
# parallel/ring.halo_nfa_scan across devices) require BOUNDED automaton
# memory: every self-loop must be a sticky accept accumulator, which a
# true x* / x+ self-loop (Quant.STAR / Quant.PLUS rep bit) is not. This
# pass trades the unbounded loop for an EXTENDED bounded footprint: each
# repeat run is rewritten into an optional run long enough that, over
# the engine's truncated field view (every input the scan ever sees is
# at most `max_len` bytes), no match is lost — so the rewrite is exact
# by construction, not an approximation. The price is width: a run can
# need up to max_len - min_len optional positions, so the pass only
# succeeds for patterns/fields where that fits the device caps; callers
# (compiler/plan.py's halo partitioner) treat None as "keep the rep
# form and exclude from halo".


def has_unbounded_rep(lp: LinearPattern) -> bool:
    """True when the pattern carries a real (non-sticky) self-loop."""
    return any(p.quant in (Quant.STAR, Quant.PLUS) for p in lp.positions)


def extend_footprint(lp: LinearPattern, max_len: int) -> LinearPattern | None:
    """Rewrite every x*/x+ into a bounded optional run, exact for inputs
    of length <= max_len (the field's device byte cap).

    x+ becomes x x{0,r} (or x{0,r} x when the position must stay the
    pattern's last for a trailing \b); x* becomes x{0,r}; r is
    max_len - min_len, the longest any single run can be inside a
    max_len-byte window with the pattern's other required positions
    still present. Returns None when the expansion exceeds
    MAX_POSITIONS or a boundary constraint cannot be preserved.
    """
    if lp.never_match or not has_unbounded_rep(lp):
        return lp
    r = max(max_len - lp.min_len, 0)
    out: list[Pos] = []
    last_i = len(lp.positions) - 1
    for i, p in enumerate(lp.positions):
        if p.quant == Quant.STAR:
            if (i == 0 and lp.boundary_start) or \
                    (i == last_i and lp.boundary_end):
                return None  # parser rejects these; stay conservative
            out.extend(Pos(bytes=p.bytes, quant=Quant.OPT) for _ in range(r))
        elif p.quant == Quant.PLUS:
            opts = [Pos(bytes=p.bytes, quant=Quant.OPT) for _ in range(r)]
            if i == last_i and lp.boundary_end:
                if i == 0 and lp.boundary_start and r > 0:
                    # one position that must stay both first and last:
                    # no placement satisfies both boundary checks
                    return None
                out.extend(opts)
                out.append(Pos(bytes=p.bytes, quant=Quant.ONE))
            else:
                out.append(Pos(bytes=p.bytes, quant=Quant.ONE))
                out.extend(opts)
        else:
            out.append(p)
    if len(out) > MAX_POSITIONS:
        return None
    ext = LinearPattern(
        positions=out,
        anchor_start=lp.anchor_start,
        anchor_end=lp.anchor_end,
        anchor_end_abs=lp.anchor_end_abs,
        boundary_start=lp.boundary_start,
        boundary_end=lp.boundary_end,
        never_match=lp.never_match,
    )
    return ext
