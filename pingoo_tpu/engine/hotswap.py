"""Epoch-switched ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md).

The reference reloads rules by tearing the listener down; at batch
throughput that drops every in-flight request. Here a new RulesetPlan
is compiled AHEAD of the switch (through the artifact cache, off the
serving path) and each engine plane flips to it atomically at a batch
boundary: in-flight batches finish on the old plan, new admissions use
the new one, and every verdict is attributable to exactly one epoch
(`pingoo_ruleset_epoch`). The swap pause — drain-of-inflight + pointer
flip, compile excluded by construction — is the number the
PINGOO_DEADLINE_MS budget must absorb (tracked as swap_pause_p99_ms in
bench_regress).

Multi-tenant scale-out rides the same mechanism: TenantPlanStore keeps
one compiled plan per tenant key (2k-10k rules total across isolated
tenants), fingerprinted tenant-scoped in the artifact cache so one
deployment serves many rulesets and swaps any of them independently.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..compiler.plan import RulesetPlan


def note_swap(plane: str, tenant: str, result: str) -> None:
    """Count one swap attempt on the shared registry
    (pingoo_ruleset_swap_total{plane,tenant,result})."""
    from ..obs import REGISTRY
    from ..obs.schema import HOTSWAP_METRICS

    REGISTRY.counter(
        "pingoo_ruleset_swap_total",
        HOTSWAP_METRICS["pingoo_ruleset_swap_total"],
        labels={"plane": plane, "tenant": tenant or "default",
                "result": result}).inc()


def set_epoch_gauge(plane: str, epoch: int) -> None:
    from ..obs import REGISTRY
    from ..obs.schema import HOTSWAP_METRICS

    REGISTRY.gauge(
        "pingoo_ruleset_epoch",
        HOTSWAP_METRICS["pingoo_ruleset_epoch"],
        labels={"plane": plane}).set(epoch)


@dataclass
class SwapHandle:
    """One requested swap, resolved by the serving loop at the next
    batch boundary. `wait()` blocks the requester (a config-reload
    thread, never the serving loop) until the flip happened; pause_ms
    is the drain+flip wall — the admission stall the swap cost."""

    plan: RulesetPlan
    tenant: str = "default"
    lists: Optional[dict] = None
    # Pre-built engine state (plan-derived jitted fns/tables), built by
    # the requester BEFORE the handle reaches the serving loop so the
    # loop's flip is pointer assignment, not compilation.
    state: Optional[dict] = None
    done: threading.Event = field(default_factory=threading.Event)
    epoch: int = -1
    pause_ms: float = 0.0
    result: str = "pending"
    error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def resolve(self, epoch: int, pause_ms: float,
                result: str = "ok",
                error: Optional[BaseException] = None) -> None:
        self.epoch = epoch
        self.pause_ms = pause_ms
        self.result = result
        self.error = error
        self.done.set()


@dataclass
class TenantPlan:
    tenant: str
    plan: RulesetPlan
    fingerprint: str
    lists: dict
    compiled_at: float


class TenantPlanStore:
    """Compile-ahead store: tenant key -> current RulesetPlan.

    `prepare()` compiles (or loads from the artifact cache, tenant-
    scoped fingerprint) WITHOUT touching what is being served — the
    caller then hands the returned plan to VerdictService.swap_plan /
    RingSidecar.request_swap. A tenant's plan is only replaced in the
    store once prepare() fully succeeded, so a broken ruleset push can
    never take a tenant's serving plan away."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir
        self._lock = threading.Lock()
        self._plans: dict[str, TenantPlan] = {}

    def prepare(self, tenant: str, rules: list, lists: dict,
                field_specs=None, routes=None) -> TenantPlan:
        from ..compiler.cache import (compile_ruleset_cached,
                                      ruleset_fingerprint)

        fingerprint = ruleset_fingerprint(
            rules, lists, field_specs, routes=routes, tenant=tenant)
        plan = compile_ruleset_cached(
            rules, lists, cache_dir=self.cache_dir,
            field_specs=field_specs, routes=routes, tenant=tenant)
        entry = TenantPlan(tenant=tenant, plan=plan,
                           fingerprint=fingerprint, lists=dict(lists),
                           compiled_at=time.time())
        with self._lock:
            self._plans[tenant] = entry
        return entry

    def get(self, tenant: str) -> Optional[TenantPlan]:
        with self._lock:
            return self._plans.get(tenant)

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._plans)

    def total_rules(self) -> int:
        with self._lock:
            return sum(len(e.plan.rules) for e in self._plans.values())
