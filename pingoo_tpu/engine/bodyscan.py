"""Streaming request-body inspection (ISSUE 13).

Real CRS rules overwhelmingly target POST bodies; until this PR the
engine scored only metadata tuples while the native plane's
`BodyFramer` de-framed flow-controlled h1/h2 body chunks and threw
them away unscanned. This module is the engine half of the body path:
it threads per-flow NFA/DFA/prefilter carry state across bounded body
*windows* so a payload split at ANY chunk/window boundary matches
bit-identically to the contiguous scan (WAFFLED's split-payload
discrepancy class is exactly what the property tests in
tests/test_bodyscan.py fuzz).

Data model
----------
A *flow* is one request body, identified by its ring ticket (native
plane) or a transient id (Python listener). The listener slices the
body into windows of at most `PINGOO_BODY_WINDOW` bytes, each tagged
(flow_id, win_seq, final). `BodyScanner.scan_windows` batches one
window per flow per round through the chunk-carry kernels:

  * `ops/nfa_scan.scan_chunk`       — [B, W] uint32 state carry,
    per-row `t_offset` (the same primitive the sp ring and halo split
    already compose);
  * `ops/bitsplit_dfa.dfa_scan_chunk` + `dfa_finalize` — (state, H)
    carry, absolute-end accepts deferred to the FINAL window;
  * `ops/prefilter.prefilter_scan_chunk` — (S, H) shift-AND carry; S
    holds in-progress factor positions, so a literal straddling a
    window boundary completes exactly on the carry-in.

Lazy starts (the prefilter cascade, streamed)
---------------------------------------------
When every pattern in the bank has a necessary factor AND the bank is
`halo_ok` with `max_footprint <= tail_cap`, the expensive NFA scan is
deferred per flow until the cheap prefilter reports a completed factor
(no factor by position q => no match ends <= q, because a necessary
factor is contained in every match). The flow keeps the last
`tail_cap` body bytes; on first factor hit the NFA starts from the
ZERO state at `offset - len(tail)` (per-row `t_offset`), exactly the
halo warm-up argument of `ops/nfa_scan.halo_split_scan`: live runs at
the window head span at most `max_footprint` bytes, all of which are
in the retained tail, and any accept fired during warm-up is a real
match (every warm-up byte is a real body byte at its real position).
Flows that never hit a factor never run the NFA at all and finalize to
all-zero verdict bits. DFA mode always carries from byte 0 (the
lowered subset automaton has no footprint metadata).

Verdict composition
-------------------
Body rules are conceptually APPENDED to the metadata ruleset, so the
two-lane action encoding of engine/verdict.action_lanes reproduces
here: `unverified` = first matched acting body rule's first action
(0 none / 1 block / 2 captcha), `verified_block` = any matched body
rule with Block anywhere. `merge_actions` composes a metadata verdict
byte with a body verdict byte under exactly those semantics (metadata
rules come first, so a nonzero metadata lane wins the first-action
race; route bits always come from the metadata verdict).

Everything is gated behind PINGOO_BODY_INSPECT=off|on with `off` the
bit-exact status quo. docs/BODY_STREAMING.md is the operator copy.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ..compiler import repat
from ..compiler.nfa import build_bank, lower_bank_to_dfa
from ..logging_utils import get_logger

log = get_logger(__name__)

# -- knobs --------------------------------------------------------------------

ACTION_NONE = 0
ACTION_BLOCK = 1
ACTION_CAPTCHA = 2

#: Verdict-byte layout shared with the ring (pingoo_ring.h): bits 0-1
#: unverified action, bit 2 verified-block, bits 3-7 route.
_UNVERIFIED_MASK = 0x3
_VERIFIED_BLOCK_BIT = 0x4
_ROUTE_MASK = 0xF8


def body_inspect_enabled() -> bool:
    return os.environ.get("PINGOO_BODY_INSPECT", "off") == "on"


def body_window_bytes() -> int:
    return int(os.environ.get("PINGOO_BODY_WINDOW", "4096"))


def body_max_flows() -> int:
    return int(os.environ.get("PINGOO_BODY_MAX_FLOWS", "1024"))


def body_flow_ttl_ms() -> int:
    return int(os.environ.get("PINGOO_BODY_FLOW_TTL_MS", "5000"))


# -- rules --------------------------------------------------------------------


@dataclass(frozen=True)
class BodyRule:
    """One body rule: a literal or regex over the raw body bytes with a
    rule-config-style action list ("block" / "captcha")."""

    name: str
    pattern: str
    kind: str = "literal"  # literal | regex
    case_insensitive: bool = False
    actions: tuple[str, ...] = ("block",)


#: Seed ruleset: CRS-staple payload classes (SQLi / XSS / traversal /
#: RCE probes — the WAMM payload-class taxonomy, PAPERS.md), literal
#: patterns only so every rule has a necessary factor and the lazy
#: prefilter cascade stays armed by default.
DEFAULT_BODY_RULES: tuple[BodyRule, ...] = (
    BodyRule("body-sqli-union", "union select", "literal", True, ("block",)),
    BodyRule("body-sqli-tautology", "' or '1'='1", "literal", True,
             ("block",)),
    BodyRule("body-xss-script", "<script", "literal", True, ("block",)),
    BodyRule("body-traversal", "../../", "literal", False, ("block",)),
    BodyRule("body-lfi-passwd", "/etc/passwd", "literal", False, ("block",)),
    BodyRule("body-suspect-eval", "eval(", "literal", True, ("captcha",)),
)


def load_body_rules() -> tuple[BodyRule, ...]:
    """PINGOO_BODY_RULES names a JSON rule file; absent -> the seed set."""
    path = os.environ.get("PINGOO_BODY_RULES")
    if not path:
        return DEFAULT_BODY_RULES
    with open(path, "rb") as f:
        raw = json.load(f)
    rules = []
    for r in raw:
        rules.append(BodyRule(
            name=r["name"], pattern=r["pattern"],
            kind=r.get("kind", "literal"),
            case_insensitive=bool(r.get("case_insensitive", False)),
            actions=tuple(r.get("actions", ["block"]))))
    return tuple(rules)


# -- compiled plan ------------------------------------------------------------


@dataclass
class BodyPlan:
    """Compiled body ruleset: one NFA bank (optionally an exact DFA
    lowering and a prefilter bank) plus the slot -> rule map."""

    rules: tuple[BodyRule, ...]
    tables: object            # ops.nfa_scan.NfaTables
    slot_rule: np.ndarray     # [P] int32 rule index per pattern slot
    rule_first: np.ndarray    # [R] int32 first action (0/1/2)
    rule_has_block: np.ndarray  # [R] bool Block anywhere in actions
    dfa_tables: object = None  # ops.bitsplit_dfa.DfaTables | None (exact)
    pf_tables: object = None   # ops.prefilter.PrefilterTables | None
    lazy_ok: bool = False
    tail_cap: int = 0
    window: int = 4096
    oracle_res: tuple = ()     # [R] compiled `re` patterns (host oracle)


def compile_body_plan(rules: tuple[BodyRule, ...] | None = None,
                      window: int | None = None) -> BodyPlan:
    from ..ops.bitsplit_dfa import dfa_to_tables
    from ..ops.nfa_scan import bank_to_tables
    from ..ops.prefilter import bank_to_prefilter_tables, \
        build_prefilter_bank

    rules = tuple(rules) if rules is not None else load_body_rules()
    window = window if window is not None else body_window_bytes()
    patterns = []
    slot_rule: list[int] = []
    oracle_res = []
    for ri, rule in enumerate(rules):
        if rule.kind == "literal":
            lps = [repat.literal_pattern(
                rule.pattern.encode("latin-1"), rule.case_insensitive)]
            esc = re.escape(rule.pattern.encode("latin-1"))
            flags = re.I if rule.case_insensitive else 0
            oracle_res.append(re.compile(esc, flags | re.S))
        else:
            pat = rule.pattern
            if rule.case_insensitive and not pat.startswith("(?i)"):
                pat = "(?i)" + pat
            lps = repat.compile_regex(pat)
            # expr/values.py canonical byte view: latin-1, unanchored
            # search, DOTALL off by default matches `re` itself.
            oracle_res.append(re.compile(pat.encode("latin-1")))
        for lp in lps:
            patterns.append(lp)
            slot_rule.append(ri)
    bank = build_bank(patterns)
    tables = bank_to_tables(bank)

    dfa_tables = None
    dfa_bank = lower_bank_to_dfa(patterns)
    if dfa_bank is not None and dfa_bank.exact:
        # Approximate lowerings are excluded: their exact-NFA recheck
        # re-scans flagged rows from byte 0, which a streaming scanner
        # no longer has.
        dfa_tables = dfa_to_tables(dfa_bank)

    pf_tables = None
    factors = [repat.necessary_factor(lp) for lp in patterns]
    all_factored = all(f is not None for f in factors)
    if all_factored and factors:
        pf_bank = build_prefilter_bank(factors)  # factor f gates slot f
        pf_tables = bank_to_prefilter_tables(pf_bank)
    tail_cap = int(tables.max_footprint)
    lazy_ok = bool(tables.halo_ok and all_factored and pf_tables is not None
                   and 0 < tail_cap <= window)

    rule_first = np.zeros(len(rules), dtype=np.int32)
    rule_has_block = np.zeros(len(rules), dtype=bool)
    for ri, rule in enumerate(rules):
        if rule.actions:
            rule_first[ri] = (ACTION_BLOCK if rule.actions[0] == "block"
                              else ACTION_CAPTCHA)
            rule_has_block[ri] = "block" in rule.actions
    return BodyPlan(
        rules=rules, tables=tables,
        slot_rule=np.asarray(slot_rule, dtype=np.int32),
        rule_first=rule_first, rule_has_block=rule_has_block,
        dfa_tables=dfa_tables, pf_tables=pf_tables,
        lazy_ok=lazy_ok, tail_cap=tail_cap, window=window,
        oracle_res=tuple(oracle_res))


def resolve_scan_mode(plan: BodyPlan) -> str:
    """PINGOO_BODY_SCAN=auto|nfa|dfa -> the mode that will actually run
    (auto prefers the exact DFA lowering when it exists)."""
    mode = os.environ.get("PINGOO_BODY_SCAN", "auto")
    if mode == "dfa" and plan.dfa_tables is None:
        log.warning("PINGOO_BODY_SCAN=dfa but no exact lowering; using nfa")
        mode = "nfa"
    if mode == "auto":
        mode = "dfa" if plan.dfa_tables is not None else "nfa"
    return mode


# -- host oracle --------------------------------------------------------------


def body_lanes_oracle(plan: BodyPlan,
                      payload: bytes) -> tuple[int, bool, tuple[str, ...]]:
    """Interpreter oracle over the CONTIGUOUS payload: Python `re` on
    the raw bytes (expr/values.py semantics), folded through the
    two-lane action loop. Returns (unverified, verified_block,
    matched rule names)."""
    matched = [bool(cre.search(payload)) for cre in plan.oracle_res]
    unverified = ACTION_NONE
    for ri, hit in enumerate(matched):
        if hit and plan.rule_first[ri] != 0:
            unverified = int(plan.rule_first[ri])
            break
    verified_block = any(
        hit and plan.rule_has_block[ri] for ri, hit in enumerate(matched))
    names = tuple(plan.rules[ri].name for ri, hit in enumerate(matched)
                  if hit)
    return unverified, verified_block, names


def merge_actions(meta_action: int, body_unverified: int,
                  body_verified_block: bool) -> int:
    """Compose a metadata verdict byte with a body verdict under the
    rules-appended semantics: metadata rules run first, so its nonzero
    unverified lane wins the first-action race; verified-block is an
    any-rule OR; route bits ride the metadata verdict unchanged."""
    meta_unverified = meta_action & _UNVERIFIED_MASK
    unverified = meta_unverified if meta_unverified else (
        body_unverified & _UNVERIFIED_MASK)
    verified = (meta_action & _VERIFIED_BLOCK_BIT) or (
        _VERIFIED_BLOCK_BIT if body_verified_block else 0)
    return (meta_action & _ROUTE_MASK) | verified | unverified


def split_payload(payload: bytes, window: int) -> list[bytes]:
    """Slice a buffered payload into scan windows (the Python-listener
    parity path: same windows the native plane would ship)."""
    if not payload:
        return [b""]
    return [payload[i:i + window] for i in range(0, len(payload), window)]


# -- flow table ---------------------------------------------------------------


@dataclass
class FlowState:
    """Per-flow carry between windows. Arrays are host-resident numpy;
    they round-trip through the batched device scan each window."""

    flow_id: int
    offset: int = 0            # body bytes consumed so far
    next_seq: int = 0          # expected win_seq
    started: bool = True       # NFA/DFA carry live (False = lazy idle)
    nfa_state: Optional[np.ndarray] = None   # [W] uint32
    dfa_state: int = 0
    dfa_h: Optional[np.ndarray] = None       # [Wh] uint32
    pf_s: Optional[np.ndarray] = None        # [Wp] uint32
    pf_h: Optional[np.ndarray] = None        # [Wp] uint32
    tail: bytes = b""          # last tail_cap bytes (lazy warm-up)
    last_touch_ms: int = 0
    degraded: bool = False     # evicted / out-of-order -> metadata-only


@dataclass
class BodyWindow:
    """One ring body slot, de-framed payload bytes only."""

    flow_id: int
    win_seq: int
    data: bytes
    final: bool = False
    abort: bool = False


@dataclass
class BodyVerdict:
    flow_id: int
    unverified: int = ACTION_NONE
    verified_block: bool = False
    matched: tuple[str, ...] = ()
    degraded: bool = False

    def action_byte(self) -> int:
        return ((self.unverified & _UNVERIFIED_MASK)
                | (_VERIFIED_BLOCK_BIT if self.verified_block else 0))


@dataclass
class BodyStats:
    windows_total: int = 0
    bytes_total: int = 0
    flows_started: int = 0
    flows_finished: int = 0
    degrade_total: int = 0      # flows degraded to metadata-only
    lazy_skips: int = 0         # window batches that skipped the NFA/DFA
    carry_depth: int = 0        # max windows carried by any live flow
    # degrade_total split by reason (obs pingoo_body_degrade_total):
    # evict | ttl | gap (scanner-side); callers add ring_full | ladder
    # | abort | h2 through their own counters.
    degrade_reasons: dict = field(default_factory=dict)


class BodyScanner:
    """Per-flow streaming matcher. NOT thread-safe; each plane owns one
    (the sidecar drain loop, the Python listener's event loop)."""

    def __init__(self, plan: Optional[BodyPlan] = None,
                 max_flows: Optional[int] = None,
                 mode: Optional[str] = None,
                 flow_ttl_ms: Optional[int] = None,
                 now_ms: Optional[Callable[[], int]] = None):
        self.plan = plan if plan is not None else compile_body_plan()
        self.mode = mode if mode is not None else resolve_scan_mode(self.plan)
        self.max_flows = max_flows if max_flows is not None \
            else body_max_flows()
        self.flow_ttl_ms = flow_ttl_ms if flow_ttl_ms is not None \
            else body_flow_ttl_ms()
        self.lazy = self.plan.lazy_ok and self.mode == "nfa" \
            and os.environ.get("PINGOO_BODY_LAZY", "auto") != "off"
        self.flows: dict[int, FlowState] = {}
        self.stats = BodyStats()
        if now_ms is None:
            import time

            now_ms = lambda: int(time.monotonic() * 1000)  # noqa: E731
        self._now_ms = now_ms
        self._jit_cache: dict = {}
        self._carry_hist = None   # set by attach_metrics
        self._collector = None
        self._registry = None

    # -- observability (obs/schema.py BODY_METRICS) ---------------------------

    def attach_metrics(self, plane: str, registry=None) -> None:
        """Export this scanner's BODY_METRICS under {plane=}: counters
        and the flows gauge sync from BodyStats via a registry
        collector at scrape time (no hot-path overhead); the carry
        histogram observes per finished flow in `_finish`."""
        if registry is None:
            from ..obs import REGISTRY as registry
        from ..obs.schema import BODY_METRICS

        windows = registry.counter(
            "pingoo_body_windows_total",
            BODY_METRICS["pingoo_body_windows_total"],
            labels={"plane": plane})
        nbytes = registry.counter(
            "pingoo_body_bytes_total",
            BODY_METRICS["pingoo_body_bytes_total"],
            labels={"plane": plane})
        flows = registry.gauge(
            "pingoo_body_flows_active",
            BODY_METRICS["pingoo_body_flows_active"],
            labels={"plane": plane})
        self._carry_hist = registry.histogram(
            "pingoo_body_carry_depth",
            BODY_METRICS["pingoo_body_carry_depth"],
            buckets=(1, 2, 4, 8, 16, 64, 256),
            labels={"plane": plane})

        def _collect():
            windows.set_total(self.stats.windows_total)
            nbytes.set_total(self.stats.bytes_total)
            flows.set(self.flows_active)
            for reason, n in self.stats.degrade_reasons.items():
                registry.counter(
                    "pingoo_body_degrade_total",
                    BODY_METRICS["pingoo_body_degrade_total"],
                    labels={"plane": plane, "reason": reason},
                ).set_total(n)

        registry.register_collector(_collect)
        self._collector = _collect
        self._registry = registry

    def detach_metrics(self) -> None:
        if self._registry is not None and self._collector is not None:
            self._registry.unregister_collector(self._collector)
        self._collector = self._registry = None

    # -- flow lifecycle -------------------------------------------------------

    def _admit(self, flow_id: int) -> FlowState:
        fs = self.flows.get(flow_id)
        if fs is not None:
            return fs
        if len(self.flows) >= self.max_flows:
            # Table full: evict the stalest flow to metadata-only so the
            # NEW flow gets inspected (fresh traffic outranks stragglers
            # — same deadline-pressure policy as the scheduler).
            victim = min(self.flows.values(), key=lambda f: f.last_touch_ms)
            self._degrade(victim, "evict")
            del self.flows[victim.flow_id]
        fs = FlowState(flow_id=flow_id, started=not self.lazy,
                       last_touch_ms=self._now_ms())
        self.flows[flow_id] = fs
        self.stats.flows_started += 1
        return fs

    def _degrade(self, fs: FlowState, reason: str = "gap") -> None:
        if not fs.degraded:
            fs.degraded = True
            self.stats.degrade_total += 1
            self.stats.degrade_reasons[reason] = \
                self.stats.degrade_reasons.get(reason, 0) + 1

    def evict_stale(self) -> int:
        """Drop flows idle past the TTL (client stalled mid-body); the
        listener side fails them open when the verdict never arrives."""
        now = self._now_ms()
        dead = [fid for fid, fs in self.flows.items()
                if now - fs.last_touch_ms > self.flow_ttl_ms]
        for fid in dead:
            self._degrade(self.flows[fid], "ttl")
            del self.flows[fid]
        return len(dead)

    def abort_flow(self, flow_id: int) -> None:
        self.flows.pop(flow_id, None)

    @property
    def flows_active(self) -> int:
        return len(self.flows)

    # -- batched window scan --------------------------------------------------

    def scan_windows(self, windows: list[BodyWindow]) -> list[BodyVerdict]:
        """Advance every flow by its pending windows (batched one window
        per flow per round, in win_seq order) and return a BodyVerdict
        for each flow whose FINAL window was seen. Oversized windows
        (transport chunks beyond the scan cap) are re-sliced here — the
        carry makes sub-window boundaries invisible to the match."""
        now = self._now_ms()
        pending: dict[int, list[tuple[bytes, bool]]] = {}
        for w in sorted(windows, key=lambda w: (w.flow_id, w.win_seq)):
            fs = self._admit(w.flow_id)
            fs.last_touch_ms = now
            if w.abort:
                self.abort_flow(w.flow_id)
                pending.pop(w.flow_id, None)
                continue
            if w.win_seq != fs.next_seq:
                # Ring order is per-flow FIFO by construction; a gap
                # means slots were dropped — fail the flow open.
                log.warning("body flow %d: window gap (want %d got %d)",
                            w.flow_id, fs.next_seq, w.win_seq)
                self._degrade(fs, "gap")
            fs.next_seq = w.win_seq + 1
            self.stats.windows_total += 1
            pieces = (split_payload(w.data, self.plan.window)
                      if len(w.data) > self.plan.window else [w.data])
            for j, piece in enumerate(pieces):
                pending.setdefault(w.flow_id, []).append(
                    (fs, piece, w.final and j == len(pieces) - 1))
        verdicts: list[BodyVerdict] = []
        while pending:
            round_ws = []
            for fid in list(pending):
                round_ws.append(pending[fid].pop(0))
                if not pending[fid]:
                    del pending[fid]
            verdicts.extend(self._scan_round(round_ws))
        return verdicts

    def scan_buffered(self, payload: bytes,
                      flow_id: int = -1) -> BodyVerdict:
        """Python-listener parity path: slice an already-buffered body
        into the SAME windows the native plane ships and run them
        through the identical chunk-carry scan."""
        chunks = split_payload(payload, self.plan.window)
        out: list[BodyVerdict] = []
        for i, chunk in enumerate(chunks):
            out = self.scan_windows([BodyWindow(
                flow_id=flow_id, win_seq=i, data=chunk,
                final=(i == len(chunks) - 1))])
        assert out, "final window must produce a verdict"
        return out[0]

    # -- internals ------------------------------------------------------------

    def _scan_round(self, ws: list) -> list[BodyVerdict]:
        """One batched round: at most one (flow, piece, final) each."""
        import jax.numpy as jnp

        from ..ops.nfa_scan import init_scan_state
        from ..ops.prefilter import prefilter_extract

        plan = self.plan
        live: list[tuple[FlowState, bytes, bool]] = []
        verdicts: list[BodyVerdict] = []
        for fs, piece, final in ws:
            if fs.degraded:
                fs.offset += len(piece)
                if final:
                    verdicts.append(self._finish(fs, degraded=True))
                continue
            live.append((fs, piece, final))
            self.stats.bytes_total += len(piece)

        scan_rows = [(fs, piece) for fs, piece, _ in live if len(piece) > 0]
        if scan_rows:
            n = len(scan_rows)
            depth = max(fs.next_seq for fs, _ in scan_rows)
            self.stats.carry_depth = max(self.stats.carry_depth, depth)
            # Fixed row width (pow2-padded rows) keeps the jit cache to
            # a handful of entries per plan.
            width = plan.tail_cap + plan.window if self.lazy else plan.window
            npad = _pow2(n)
            data = np.zeros((npad, width), dtype=np.uint8)
            t_off = np.zeros(npad, dtype=np.int32)
            lens = np.zeros(npad, dtype=np.int32)

            hit_any = None
            if plan.pf_tables is not None:
                # Pass A: prefilter carry over the window bytes only.
                for i, (fs, piece) in enumerate(scan_rows):
                    if fs.pf_s is None:
                        wp = plan.pf_tables.init.shape[0]
                        fs.pf_s = np.zeros(wp, dtype=np.uint32)
                        fs.pf_h = np.zeros(wp, dtype=np.uint32)
                    data[i, :len(piece)] = np.frombuffer(piece, np.uint8)
                    t_off[i] = fs.offset
                    lens[i] = fs.offset + len(piece)
                S = _stack([fs.pf_s for fs, _ in scan_rows], npad)
                Hp = _stack([fs.pf_h for fs, _ in scan_rows], npad)
                S, Hp = self._jit("pf")(plan.pf_tables, jnp.asarray(data),
                                        jnp.asarray(lens), jnp.asarray(S),
                                        jnp.asarray(Hp), jnp.asarray(t_off))
                S, Hp = np.asarray(S), np.asarray(Hp)
                hit_any = np.asarray(
                    prefilter_extract(plan.pf_tables, jnp.asarray(Hp))
                ).any(axis=1)
                for i, (fs, piece) in enumerate(scan_rows):
                    fs.pf_s, fs.pf_h = S[i].copy(), Hp[i].copy()

            starting: set[int] = set()
            if self.lazy:
                for i, (fs, piece) in enumerate(scan_rows):
                    if not fs.started and hit_any[i]:
                        starting.add(i)

            active = [(i, fs, piece) for i, (fs, piece) in
                      enumerate(scan_rows) if fs.started or i in starting]
            if active:
                data[:] = 0
                for i, fs, piece in active:
                    pay = np.frombuffer(piece, np.uint8)
                    if i in starting:
                        # Lazy warm-up: zero-state scan over the retained
                        # tail reproduces the true carry (halo argument —
                        # see the module docstring).
                        tail = np.frombuffer(fs.tail, np.uint8)
                        data[i, :len(tail)] = tail
                        data[i, len(tail):len(tail) + len(pay)] = pay
                        t_off[i] = fs.offset - len(tail)
                    else:
                        data[i, :len(pay)] = pay
                        t_off[i] = fs.offset
                    lens[i] = fs.offset + len(pay)
                dj, lj, tj = (jnp.asarray(data), jnp.asarray(lens),
                              jnp.asarray(t_off))
                if self.mode == "dfa":
                    st = _stack1([np.int32(fs.dfa_state)
                                  for _, fs, _ in active], npad, active,
                                 np.int32)
                    Hd = _stack([_dfa_h(fs, plan) for _, fs, _ in active],
                                npad, rows=[i for i, _, _ in active])
                    st, Hd = self._jit("dfa")(plan.dfa_tables, dj, lj,
                                              jnp.asarray(st),
                                              jnp.asarray(Hd), tj)
                    st, Hd = np.asarray(st), np.asarray(Hd)
                    for i, fs, piece in active:
                        fs.started = True
                        fs.dfa_state, fs.dfa_h = int(st[i]), Hd[i].copy()
                else:
                    W = plan.tables.opt.shape[0]
                    stv = np.zeros((npad, W), dtype=np.uint32)
                    for i, fs, piece in active:
                        if fs.nfa_state is None:
                            fs.nfa_state = np.asarray(
                                init_scan_state(1, W))[0].copy()
                        stv[i] = fs.nfa_state
                    stv = self._jit("nfa")(plan.tables, dj, lj,
                                           jnp.asarray(stv), tj)
                    stv = np.asarray(stv)
                    for i, fs, piece in active:
                        fs.started = True
                        fs.nfa_state = stv[i].copy()
            else:
                self.stats.lazy_skips += 1

        for fs, piece, final in live:
            fs.offset += len(piece)
            if self.lazy and not fs.started and plan.tail_cap > 0:
                fs.tail = (fs.tail + piece)[-plan.tail_cap:]
            if final:
                verdicts.append(self._finish(fs))
        return verdicts

    def _finish(self, fs: FlowState, degraded: bool = False) -> BodyVerdict:
        import jax.numpy as jnp

        plan = self.plan
        self.flows.pop(fs.flow_id, None)
        self.stats.flows_finished += 1
        if self._carry_hist is not None:
            self._carry_hist.observe(float(max(1, fs.next_seq)))
        if degraded or fs.degraded:
            return BodyVerdict(fs.flow_id, degraded=True)
        lens = jnp.asarray(np.array([fs.offset], dtype=np.int32))
        if not fs.started:
            # Lazy flow with no completed factor: no match, by the
            # necessary-factor argument (and no empty/always lanes —
            # lazy_ok requires every pattern to carry a factor).
            matched = np.zeros(plan.slot_rule.shape[0], dtype=bool)
        elif self.mode == "dfa":
            from ..ops.bitsplit_dfa import dfa_finalize

            hits = dfa_finalize(
                plan.dfa_tables,
                jnp.asarray(np.array([fs.dfa_state], dtype=np.int32)),
                jnp.asarray(_dfa_h(fs, plan)[None, :]), lens)
            matched = np.asarray(hits)[0]
        else:
            from ..ops.nfa_scan import extract_slots

            if fs.nfa_state is None:  # empty body: never scanned
                fs.nfa_state = np.zeros(plan.tables.opt.shape[0],
                                        dtype=np.uint32)
            hits = extract_slots(plan.tables,
                                 jnp.asarray(fs.nfa_state[None, :]), lens)
            matched = np.asarray(hits)[0]
        return self._lanes(fs.flow_id, matched)

    def _lanes(self, flow_id: int, slot_hits: np.ndarray) -> BodyVerdict:
        plan = self.plan
        R = plan.rule_first.shape[0]
        rule_hit = np.zeros(R, dtype=bool)
        np.logical_or.at(rule_hit, plan.slot_rule, slot_hits)
        unverified = ACTION_NONE
        for ri in range(R):
            if rule_hit[ri] and plan.rule_first[ri] != 0:
                unverified = int(plan.rule_first[ri])
                break
        verified_block = bool((rule_hit & plan.rule_has_block).any())
        names = tuple(plan.rules[ri].name for ri in range(R)
                      if rule_hit[ri])
        return BodyVerdict(flow_id, unverified, verified_block, names)

    def _jit(self, kind: str):
        """Shape-polymorphic jitted chunk kernels, one per scan kind."""
        fn = self._jit_cache.get(kind)
        if fn is None:
            import jax

            if kind == "pf":
                from ..ops.prefilter import prefilter_scan_chunk

                fn = jax.jit(prefilter_scan_chunk)
            elif kind == "dfa":
                from ..ops.bitsplit_dfa import dfa_scan_chunk

                fn = jax.jit(dfa_scan_chunk)
            else:
                from ..ops.nfa_scan import scan_chunk

                fn = jax.jit(scan_chunk, static_argnames=(
                    "lookup", "backend"))
            self._jit_cache[kind] = fn
        return fn


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _stack(vecs: list[np.ndarray], npad: int,
           rows=None) -> np.ndarray:
    """Scatter per-flow carry vectors into a padded [npad, w] batch."""
    out = np.zeros((npad, vecs[0].shape[0]), dtype=vecs[0].dtype)
    if rows is None:
        rows = range(len(vecs))
    for j, i in enumerate(rows):
        out[i] = vecs[j]
    return out


def _stack1(vals, npad, active, dtype) -> np.ndarray:
    out = np.zeros(npad, dtype=dtype)
    for v, (i, _, _) in zip(vals, active):
        out[i] = v
    return out


def _dfa_h(fs: FlowState, plan: BodyPlan) -> np.ndarray:
    if fs.dfa_h is None:
        fs.dfa_h = np.zeros(plan.dfa_tables.num_words, dtype=np.uint32)
    return fs.dfa_h
