"""Request batch encoding: request tuples -> fixed-shape device tensors.

The host data plane extracts one `RequestTuple` per request — the same
tuple shape the reference builds for its bel context (pingoo/rules.rs:
17-34 RequestData + ClientData, constructed at http_listener.rs:238-249)
— and batches them into zero-padded byte tensors + numeric columns.

Truncation policy: every string field is capped at its plan capacity
(compiler/lowering.DEFAULT_FIELD_SPECS; the reference caps UA/host at
256 on the hot path, http_listener.rs:159,284-296 — the listener applies
those caps before encoding). A request whose field still exceeds its
device capacity gets its row flagged in the batch's `overflow` lane and
is re-evaluated on the host interpreter over the untruncated strings
(engine/service.py), because the reference matches full path/url and
truncated matching would let padded URLs slip past content rules.
`batch_to_contexts` rebuilds the strings the device saw for the
non-overflowing rows (the parity oracle view).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Optional

import numpy as np

from ..compiler.lowering import DEFAULT_FIELD_SPECS
from ..expr import Context, Ip
from ..ops.cidr import ip_to_words

STRING_FIELDS = ("host", "url", "path", "method", "user_agent", "country")


@dataclass
class RequestTuple:
    """One request's rule-relevant metadata (reference pingoo/rules.rs:17-34)."""

    host: str = ""
    url: str = ""
    path: str = ""
    method: str = "GET"
    user_agent: str = ""
    ip: str = "0.0.0.0"
    remote_port: int = 0
    asn: int = 0
    country: str = "XX"
    # Observability correlation id (obs/trace.py): assigned at the edge,
    # rides the tuple through batching so engine-side logs can join a
    # request to its response header / access-log line. Never encoded
    # into device arrays and never consulted by any rule.
    trace_id: str = ""


@dataclass
class RequestBatch:
    """Fixed-shape encoded batch (numpy; device transfer happens in the
    engine). A pytree-compatible dict lives in `.arrays`; `overflow` is
    host-side metadata (rows whose fields exceeded device capacity) and
    deliberately NOT part of the arrays pytree — it would otherwise ride
    every device transfer and change jit signatures for nothing."""

    size: int
    arrays: dict  # field -> np/jnp arrays
    overflow: Optional[np.ndarray] = None  # [size] bool or None

    def __getitem__(self, key: str):
        return self.arrays[key]


def _to_bytes(text: str) -> bytes:
    """Canonical byte view (latin-1, bijective); non-byte chars are
    replaced so a hostile header can't crash encoding."""
    try:
        return text.encode("latin-1")
    except UnicodeEncodeError:
        return text.encode("latin-1", errors="replace")


def encode_requests(
    requests: list[RequestTuple],
    field_specs: Optional[Mapping[str, int]] = None,
) -> RequestBatch:
    specs = dict(field_specs or DEFAULT_FIELD_SPECS)
    B = len(requests)
    arrays: dict = {}
    overflow = np.zeros(B, dtype=bool)
    for field in STRING_FIELDS:
        L = specs.get(field, 256)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, req in enumerate(requests):
            full = _to_bytes(getattr(req, field))
            if len(full) > L:
                overflow[i] = True
            raw = full[:L]
            data[i, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            lens[i] = len(raw)
        arrays[f"{field}_bytes"] = data
        arrays[f"{field}_len"] = lens

    ip_words = np.zeros((B, 4), dtype=np.uint32)
    for i, req in enumerate(requests):
        try:
            ip_words[i], _ = ip_to_words(Ip(req.ip))
        except Exception:
            ip_words[i] = 0  # unparseable -> never matches any predicate
    arrays["ip"] = ip_words
    arrays["asn"] = np.array(
        [_clamp_i64(r.asn) for r in requests], dtype=np.int64)
    arrays["remote_port"] = np.array(
        [_clamp_i64(r.remote_port) for r in requests], dtype=np.int64)
    return RequestBatch(size=B, arrays=arrays, overflow=overflow)


def _clamp_i64(v: int) -> int:
    return max(min(int(v), 2**63 - 1), -(2**63))


def bucket_arrays(arrays: dict, min_len: int = 16) -> dict:
    """Slice each field's byte matrix to the next power-of-2 >= the batch's
    longest value. The NFA scan is O(L), so not walking padding is the
    single biggest throughput lever for real traffic (URLs average tens of
    bytes against a 512-byte capacity). Produces a small set of static
    shapes, so jit recompiles at most log2(cap) times per field.
    """
    out = dict(arrays)
    for field in STRING_FIELDS:
        data = arrays[f"{field}_bytes"]
        lens = arrays[f"{field}_len"]
        cap = data.shape[1]
        longest = int(np.max(lens)) if len(lens) else 0
        L = min_len
        while L < longest:
            L *= 2
        L = min(L, cap)
        out[f"{field}_bytes"] = np.ascontiguousarray(data[:, :L])
    return out


def pow2_batch_size(n: int, max_batch: int, multiple: int = 1) -> int:
    """The engine's padded launch size for an n-row batch: the next
    power of two (floor 8, so tiny batches share one compiled shape),
    capped at `max_batch` but never below n, then rounded up to
    `multiple` — the mesh executor passes its dp extent so the batch
    axis shards evenly (sched/mesh_exec.py; 1 = single device, where
    this reproduces the historical pow2 ladder exactly)."""
    target = 1
    while target < n:
        target *= 2
    size = max(min(max(target, 8), max_batch), n)
    if multiple > 1:
        rem = size % multiple
        if rem:
            size += multiple - rem
    return size


def pad_batch(batch: RequestBatch, to_size: int) -> RequestBatch:
    """Pad a batch to a fixed size (jit shape stability); padded rows are
    inert (zero-length fields, ip 0, no overflow)."""
    B = batch.size
    if B == to_size:
        return batch
    assert to_size > B
    arrays = {}
    for key, arr in batch.arrays.items():
        pad_shape = (to_size - B,) + arr.shape[1:]
        arrays[key] = np.concatenate([arr, np.zeros(pad_shape, dtype=arr.dtype)])
    overflow = batch.overflow
    if overflow is not None:
        overflow = np.concatenate(
            [overflow, np.zeros(to_size - B, dtype=bool)])
    return RequestBatch(size=to_size, arrays=arrays, overflow=overflow)


def batch_to_contexts(
    batch: RequestBatch, lists: Mapping[str, list]
) -> list[Context]:
    """Rebuild interpreter contexts from the encoded batch — the parity
    oracle sees exactly the (truncated) bytes the device saw."""
    out = []
    B = batch.size
    for i in range(B):
        fields = {}
        for field in STRING_FIELDS:
            data = batch[f"{field}_bytes"][i]
            n = int(batch[f"{field}_len"][i])
            fields[field] = bytes(data[:n]).decode("latin-1")
        ip = _words_to_ip(batch["ip"][i])
        ctx = Context(
            {
                "http_request": {
                    "host": fields["host"],
                    "url": fields["url"],
                    "path": fields["path"],
                    "method": fields["method"],
                    "user_agent": fields["user_agent"],
                },
                "client": {
                    "ip": ip,
                    "remote_port": int(batch["remote_port"][i]),
                    "asn": int(batch["asn"][i]),
                    "country": fields["country"],
                },
                "lists": dict(lists),
            }
        )
        out.append(ctx)
    return out


def tuple_to_context(tup: RequestTuple, lists: Mapping[str, list]) -> Context:
    """Interpreter context straight from the UNTRUNCATED request tuple —
    used for overflow-row re-evaluation and route matching. The reference
    builds the same variable shape at http_listener.rs:238-249."""
    try:
        ip = Ip(tup.ip)
    except Exception:
        ip = Ip("0.0.0.0")
    return Context({
        "http_request": {
            "host": tup.host, "url": tup.url, "path": tup.path,
            "method": tup.method, "user_agent": tup.user_agent,
        },
        "client": {
            "ip": ip, "remote_port": tup.remote_port,
            "asn": tup.asn, "country": tup.country,
        },
        "lists": dict(lists),
    })


def _words_to_ip(words: np.ndarray) -> Ip:
    value = 0
    for w in words:
        value = (value << 32) | int(w)
    import ipaddress

    if (value >> 32) == 0xFFFF:  # v4-mapped
        return Ip(ipaddress.ip_address(value & 0xFFFFFFFF))
    return Ip(ipaddress.ip_address(value))


def requests_from_dicts(rows: Iterable[Mapping]) -> list[RequestTuple]:
    return [RequestTuple(**row) for row in rows]
