"""Request batch encoding: request tuples -> fixed-shape device tensors.

The host data plane extracts one `RequestTuple` per request — the same
tuple shape the reference builds for its bel context (pingoo/rules.rs:
17-34 RequestData + ClientData, constructed at http_listener.rs:238-249)
— and batches them into zero-padded byte tensors + numeric columns.

Truncation policy: every string field is capped at its plan capacity
(compiler/lowering.DEFAULT_FIELD_SPECS; the reference caps UA/host at
256 on the hot path, http_listener.rs:159,284-296 — the listener applies
those caps before encoding). A request whose field still exceeds its
device capacity gets its row flagged in the batch's `overflow` lane and
is re-evaluated on the host interpreter over the untruncated strings
(engine/service.py), because the reference matches full path/url and
truncated matching would let padded URLs slip past content rules.
`batch_to_contexts` rebuilds the strings the device saw for the
non-overflowing rows (the parity oracle view).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, Mapping, NamedTuple, Optional

import numpy as np

from ..compiler.lowering import DEFAULT_FIELD_SPECS
from ..compiler.plan import quantize_stage_cap
from ..expr import Context, Ip
from ..ops.cidr import ip_to_words

STRING_FIELDS = ("host", "url", "path", "method", "user_agent", "country")


@dataclass
class RequestTuple:
    """One request's rule-relevant metadata (reference pingoo/rules.rs:17-34)."""

    host: str = ""
    url: str = ""
    path: str = ""
    method: str = "GET"
    user_agent: str = ""
    ip: str = "0.0.0.0"
    remote_port: int = 0
    asn: int = 0
    country: str = "XX"
    # Observability correlation id (obs/trace.py): assigned at the edge,
    # rides the tuple through batching so engine-side logs can join a
    # request to its response header / access-log line. Never encoded
    # into device arrays and never consulted by any rule.
    trace_id: str = ""


@dataclass
class RequestBatch:
    """Fixed-shape encoded batch (numpy; device transfer happens in the
    engine). A pytree-compatible dict lives in `.arrays`; `overflow` is
    host-side metadata (rows whose fields exceeded device capacity) and
    deliberately NOT part of the arrays pytree — it would otherwise ride
    every device transfer and change jit signatures for nothing."""

    size: int
    arrays: dict  # field -> np/jnp arrays
    overflow: Optional[np.ndarray] = None  # [size] bool or None
    # Compact staging (ISSUE 15): the [size, layout.width] uint8 packed
    # buffer shipped to the device as ONE async copy, and its static
    # layout. None under PINGOO_STAGING=full — `arrays` is then the
    # only device view. `arrays` stays populated either way (its byte
    # matrices are strided views into `packed` when compact) for the
    # host-side consumers: host-rule lanes, parity contexts, scorer.
    packed: Optional[np.ndarray] = None
    layout: Optional["PackedLayout"] = None
    staged_bytes: int = 0  # host->device bytes this batch stages

    def __getitem__(self, key: str):
        return self.arrays[key]


def _to_bytes(text: str) -> bytes:
    """Canonical byte view (latin-1, bijective); non-byte chars are
    replaced so a hostile header can't crash encoding."""
    try:
        return text.encode("latin-1")
    except UnicodeEncodeError:
        return text.encode("latin-1", errors="replace")


def encode_requests(
    requests: list[RequestTuple],
    field_specs: Optional[Mapping[str, int]] = None,
) -> RequestBatch:
    specs = dict(field_specs or DEFAULT_FIELD_SPECS)
    B = len(requests)
    arrays: dict = {}
    overflow = np.zeros(B, dtype=bool)
    for field in STRING_FIELDS:
        L = specs.get(field, 256)
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i, req in enumerate(requests):
            full = _to_bytes(getattr(req, field))
            if len(full) > L:
                overflow[i] = True
            raw = full[:L]
            data[i, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            lens[i] = len(raw)
        arrays[f"{field}_bytes"] = data
        arrays[f"{field}_len"] = lens

    ip_words = np.zeros((B, 4), dtype=np.uint32)
    for i, req in enumerate(requests):
        try:
            ip_words[i], _ = ip_to_words(Ip(req.ip))
        except Exception:
            ip_words[i] = 0  # unparseable -> never matches any predicate
    arrays["ip"] = ip_words
    arrays["asn"] = np.array(
        [_clamp_i64(r.asn) for r in requests], dtype=np.int64)
    arrays["remote_port"] = np.array(
        [_clamp_i64(r.remote_port) for r in requests], dtype=np.int64)
    return RequestBatch(size=B, arrays=arrays, overflow=overflow)


def _clamp_i64(v: int) -> int:
    return max(min(int(v), 2**63 - 1), -(2**63))


def bucket_arrays(arrays: dict, min_len: int = 16) -> dict:
    """Slice each field's byte matrix to the next power-of-2 >= the batch's
    longest value. The NFA scan is O(L), so not walking padding is the
    single biggest throughput lever for real traffic (URLs average tens of
    bytes against a 512-byte capacity). Produces a small set of static
    shapes, so jit recompiles at most log2(cap) times per field.
    """
    out = dict(arrays)
    for field in STRING_FIELDS:
        data = arrays[f"{field}_bytes"]
        lens = arrays[f"{field}_len"]
        cap = data.shape[1]
        longest = int(np.max(lens)) if len(lens) else 0
        L = min_len
        while L < longest:
            L *= 2
        L = min(L, cap)
        out[f"{field}_bytes"] = np.ascontiguousarray(data[:, :L])
    return out


def pow2_batch_size(n: int, max_batch: int, multiple: int = 1) -> int:
    """The engine's padded launch size for an n-row batch: the next
    power of two (floor 8, so tiny batches share one compiled shape),
    capped at `max_batch` but never below n, then rounded up to
    `multiple` — the mesh executor passes its dp extent so the batch
    axis shards evenly (sched/mesh_exec.py; 1 = single device, where
    this reproduces the historical pow2 ladder exactly)."""
    target = 1
    while target < n:
        target *= 2
    size = max(min(max(target, 8), max_batch), n)
    if multiple > 1:
        rem = size % multiple
        if rem:
            size += multiple - rem
    return size


def pad_batch(batch: RequestBatch, to_size: int) -> RequestBatch:
    """Pad a batch to a fixed size (jit shape stability); padded rows are
    inert (zero-length fields, ip 0, no overflow)."""
    B = batch.size
    if B == to_size:
        return batch
    assert to_size > B
    arrays = {}
    for key, arr in batch.arrays.items():
        pad_shape = (to_size - B,) + arr.shape[1:]
        arrays[key] = np.concatenate([arr, np.zeros(pad_shape, dtype=arr.dtype)])
    overflow = batch.overflow
    if overflow is not None:
        overflow = np.concatenate(
            [overflow, np.zeros(to_size - B, dtype=bool)])
    return RequestBatch(size=to_size, arrays=arrays, overflow=overflow)


# Shm-slot length-field names per string field (native_ring
# REQUEST_SLOT_DTYPE; `country` is a fixed 2-byte code with no length
# field). Lives here, not in native_ring.py, so the zero-copy fill is
# inside the analyze-linted tree (tools/analyze/lint_config.py).
SLOT_LEN_KEYS = {
    "method": "method_len",
    "host": "host_len",
    "path": "path_len",
    "url": "url_len",
    "user_agent": "ua_len",
}


def bucket_len(longest: int, cap: int, min_len: int = 16) -> int:
    """The pow2 column count `bucket_arrays` would pick for a field
    whose longest value is `longest` under capacity `cap`."""
    L = min_len
    while L < longest:
        L *= 2
    return min(L, cap)


# -- Compact staging (ISSUE 15, docs/EXECUTOR.md "Compact staging") ----------


def resolve_staging_mode() -> str:
    """PINGOO_STAGING: `full` (default; the bit-exact oracle — every
    field stages its full spec width as separate arrays) or `compact`
    (plan-derived capped widths in ONE packed buffer per batch)."""
    mode = os.environ.get("PINGOO_STAGING", "full").strip().lower()
    return "compact" if mode == "compact" else "full"


def resolve_stage_caps(plan) -> Optional[dict[str, int]]:
    """The per-field staged widths this plan serves under, or None in
    full mode. Starts from the compile pass's quantized caps
    (plan.staging_caps; full spec on plans cached before v11), then
    applies the PINGOO_STAGING_DEPTH operator clamp (0 = off) —
    re-quantized to the rung ladder so clamped tenants still share
    XLA compiles."""
    if resolve_staging_mode() != "compact":
        return None
    specs = dict(getattr(plan, "field_specs", None)
                 or DEFAULT_FIELD_SPECS)
    caps = dict(getattr(plan, "staging_caps", None) or {})
    try:
        depth = int(os.environ.get("PINGOO_STAGING_DEPTH", "0"))
    except ValueError:
        depth = 0
    eff: dict[str, int] = {}
    for field in STRING_FIELDS:
        spec = int(specs.get(field, 256))
        cap = min(int(caps.get(field, spec)), spec)
        if depth > 0:
            cap = min(cap, quantize_stage_cap(min(depth, spec), spec))
        eff[field] = max(1, cap)
    return eff


def stage_overflow_thresholds(plan,
                              eff: Mapping[str, int]) -> dict[str, int]:
    """Per-field TRUE-length threshold beyond which a row must be
    re-interpreted from its untruncated source. With caps at or above
    the plan's required depth the threshold is the full spec (exactly
    full mode's over-capacity rule); a cap clamped BELOW the required
    depth (PINGOO_STAGING_DEPTH) drops bytes some scanner depends on,
    so any row longer than the cap reroutes through the interpreter
    backstop — which is what keeps clamped serving verdict-identical."""
    specs = dict(getattr(plan, "field_specs", None)
                 or DEFAULT_FIELD_SPECS)
    required = getattr(plan, "staging_required", None) or {}
    out: dict[str, int] = {}
    for field in STRING_FIELDS:
        spec = int(specs.get(field, 256))
        need = min(int(required.get(field, spec)), spec)
        cap = int(eff.get(field, spec))
        out[field] = cap if cap < need else spec
    return out


class PackedLayout(NamedTuple):
    """Static byte layout of one packed staging row (hashable — rides
    the jitted packed fns as a static argument, so one XLA compile per
    distinct caps rung-tuple). Per row: the capped byte region of each
    string field, then a metadata tail — u16-LE true lens, the 16
    big-endian IP bytes, and the i64-LE asn / remote_port words (full
    width: numeric predicates must stay exact)."""

    fields: tuple  # ((field, offset, width), ...) capped byte regions
    lens: tuple    # ((field, offset), ...) u16 LE true lengths
    ip_off: int    # 16 bytes, big-endian v6-mapped words
    asn_off: int   # 8 bytes, i64 LE
    port_off: int  # 8 bytes, i64 LE
    width: int     # total row stride


_LAYOUT_CACHE: dict[tuple, PackedLayout] = {}


def build_packed_layout(stage_caps: Mapping[str, int]) -> PackedLayout:
    """PackedLayout for a caps assignment; cached per widths-tuple so
    hot-swaps between plans on the same rungs return the SAME (hash-
    equal) layout and reuse the packed fns' XLA compile."""
    widths = tuple(int(stage_caps[f]) for f in STRING_FIELDS)
    cached = _LAYOUT_CACHE.get(widths)
    if cached is not None:
        return cached
    fields = []
    off = 0
    for field, w in zip(STRING_FIELDS, widths):
        fields.append((field, off, w))
        off += w
    lens = []
    for field in STRING_FIELDS:
        lens.append((field, off))
        off += 2
    ip_off = off
    off += 16
    asn_off = off
    off += 8
    port_off = off
    off += 8
    layout = PackedLayout(fields=tuple(fields), lens=tuple(lens),
                          ip_off=ip_off, asn_off=asn_off,
                          port_off=port_off, width=off)
    _LAYOUT_CACHE[widths] = layout
    return layout


class StagingEncoder:
    """Pre-allocated, reused staging buffers for the zero-copy encode
    path (ISSUE 9, docs/EXECUTOR.md).

    The legacy chain allocates per batch: `encode_requests` builds
    fresh (B, cap) matrices, `bucket_arrays` copies the pow2 column
    slice contiguous, and `pad_batch` concatenates zero rows — three
    full-batch copies before the device sees a byte. This encoder owns
    (max_batch, cap) matrices per field and fills them IN PLACE,
    handing out views already bucketed (pow2 columns) and padded (pow2
    rows), value-identical to the legacy chain (the bit-identity suite
    in tests/test_pipeline.py is the contract).

    Double-buffered: `nbuf` rotating buffer sets, so batch N+1's host
    fill cannot overwrite buffers a still-in-flight batch N hands to
    the device or reads at resolve time. Planes size `nbuf` to their
    executor depth + 1.

    Two fill paths:
      * `encode_requests` — RequestTuple list (Python listener plane);
        same per-request loop as module-level `encode_requests`, minus
        the allocations.
      * `encode_slots` — a structured shm-slot array view
        (native_ring.REQUEST_SLOT_DTYPE rows, sidecar plane): per-field
        vectorized strided copies straight out of the ring slots, no
        per-slot Python tuple materialization.
    """

    def __init__(self, max_batch: int,
                 field_specs: Optional[Mapping[str, int]] = None,
                 nbuf: int = 2,
                 stage_caps: Optional[Mapping[str, int]] = None,
                 overflow_thresholds: Optional[Mapping[str, int]] = None):
        specs = dict(field_specs or DEFAULT_FIELD_SPECS)
        self.max_batch = int(max_batch)
        self.specs = specs
        self.nbuf = max(1, int(nbuf))
        self._cursor = 0
        self._bufs: list[dict] = []
        for _ in range(self.nbuf):
            bufs: dict = {}
            for field in STRING_FIELDS:
                cap = specs.get(field, 256)
                bufs[f"{field}_bytes"] = np.zeros(
                    (self.max_batch, cap), dtype=np.uint8)
                bufs[f"{field}_len"] = np.zeros(
                    self.max_batch, dtype=np.int32)
            bufs["ip"] = np.zeros((self.max_batch, 4), dtype=np.uint32)
            bufs["asn"] = np.zeros(self.max_batch, dtype=np.int64)
            bufs["remote_port"] = np.zeros(self.max_batch, dtype=np.int64)
            bufs["overflow"] = np.zeros(self.max_batch, dtype=bool)
            self._bufs.append(bufs)
        # Compact staging (ISSUE 15): flat packed rows, FULL-spec-sized
        # once at boot so a hot-swap that widens caps never reallocates
        # — per batch only the current layout's [P, width] prefix is
        # touched and shipped.
        self.stage_caps: Optional[dict[str, int]] = None
        self._thresholds: dict[str, int] = dict(specs)
        self._layout: Optional[PackedLayout] = None
        if stage_caps is not None:
            full_w = build_packed_layout(
                {f: specs.get(f, 256) for f in STRING_FIELDS}).width
            for bufs in self._bufs:
                bufs["packed"] = np.zeros(
                    self.max_batch * full_w, dtype=np.uint8)
            self.set_stage_caps(stage_caps, overflow_thresholds)

    def set_stage_caps(
            self, stage_caps: Mapping[str, int],
            overflow_thresholds: Optional[Mapping[str, int]] = None
    ) -> None:
        """Install a plan's staged widths (hot-swap flip point: called
        only between batches, like _adopt_*_state). The packed buffers
        are spec-sized, so widening is just a new layout."""
        if "packed" not in self._bufs[0]:
            raise ValueError(
                "encoder was built without packed staging buffers")
        self.stage_caps = {f: min(int(stage_caps.get(
            f, self.specs.get(f, 256))), self.specs.get(f, 256))
            for f in STRING_FIELDS}
        self._layout = build_packed_layout(self.stage_caps)
        self._thresholds = dict(self.specs)
        if overflow_thresholds is not None:
            for f in STRING_FIELDS:
                self._thresholds[f] = min(
                    int(overflow_thresholds.get(
                        f, self.specs.get(f, 256))),
                    self.specs.get(f, 256))

    def _checkout(self) -> dict:
        buf = self._bufs[self._cursor]
        self._cursor = (self._cursor + 1) % self.nbuf
        return buf

    def encode_requests(
        self, requests: list[RequestTuple], pad_to: Optional[int] = None,
    ) -> RequestBatch:
        """RequestTuples -> bucketed+padded staging views (hot).

        Value-identical to
        `pad_batch(bucket of encode_requests(requests), pad_to)`; the
        returned arrays are views into this encoder's rotating buffers
        and stay valid until the buffer set cycles back (nbuf - 1
        later checkouts)."""
        B = len(requests)
        P = B if pad_to is None else int(pad_to)
        if not B or P < B or P > self.max_batch:
            raise ValueError(f"bad staging shape: B={B} pad_to={pad_to} "
                             f"max_batch={self.max_batch}")
        buf = self._checkout()
        if self._layout is not None:
            return self._encode_requests_packed(requests, B, P, buf)
        arrays: dict = {}
        overflow = buf["overflow"][:P]
        overflow[:] = False
        for field in STRING_FIELDS:
            cap = self.specs.get(field, 256)
            raws = []
            longest = 0
            for i, req in enumerate(requests):
                full = _to_bytes(getattr(req, field))
                if len(full) > cap:
                    overflow[i] = True
                raw = full[:cap]
                raws.append(raw)
                if len(raw) > longest:
                    longest = len(raw)
            L = bucket_len(longest, cap)
            data = buf[f"{field}_bytes"][:P, :L]
            lens = buf[f"{field}_len"][:P]
            data[:] = 0
            lens[B:] = 0
            for i, raw in enumerate(raws):
                data[i, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
                lens[i] = len(raw)
            arrays[f"{field}_bytes"] = data
            arrays[f"{field}_len"] = lens
        ip = buf["ip"][:P]
        ip[B:] = 0
        for i, req in enumerate(requests):
            try:
                ip[i], _ = ip_to_words(Ip(req.ip))
            except Exception:
                ip[i] = 0  # unparseable -> never matches any predicate
        arrays["ip"] = ip
        asn = buf["asn"][:P]
        port = buf["remote_port"][:P]
        asn[B:] = 0
        port[B:] = 0
        for i, req in enumerate(requests):
            asn[i] = _clamp_i64(req.asn)
            port[i] = _clamp_i64(req.remote_port)
        arrays["asn"] = asn
        arrays["remote_port"] = port
        staged = sum(a.nbytes for a in arrays.values())
        return RequestBatch(size=P, arrays=arrays, overflow=overflow,
                            staged_bytes=staged)

    def _encode_requests_packed(self, requests, B: int, P: int,
                                buf: dict) -> RequestBatch:
        """Compact-mode tuple encode (hot): capped field prefixes +
        metadata tail into ONE flat [P, width] packed buffer; the
        returned arrays' byte matrices are strided views into it, so
        host consumers (host-rule lanes, parity contexts, the scorer)
        read the exact bytes the device decodes."""
        layout = self._layout
        W = layout.width
        pk = buf["packed"][: P * W].reshape(P, W)
        pk[:] = 0
        arrays: dict = {}
        overflow = buf["overflow"][:P]
        overflow[:] = False
        for field, off, w in layout.fields:
            spec = self.specs.get(field, 256)
            limit = self._thresholds.get(field, spec)
            data = pk[:, off:off + w]
            lens = buf[f"{field}_len"][:P]
            lens[B:] = 0
            for i, req in enumerate(requests):
                full = _to_bytes(getattr(req, field))
                if len(full) > limit:
                    overflow[i] = True
                raw = full[:w]
                data[i, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
                # TRUE length (up to spec) regardless of the staged
                # width: device length predicates must stay exact.
                lens[i] = min(len(full), spec)
            arrays[f"{field}_bytes"] = data
            arrays[f"{field}_len"] = lens
        ip = buf["ip"][:P]
        ip[B:] = 0
        for i, req in enumerate(requests):
            try:
                ip[i], _ = ip_to_words(Ip(req.ip))
            except Exception:
                ip[i] = 0  # unparseable -> never matches any predicate
        arrays["ip"] = ip
        asn = buf["asn"][:P]
        port = buf["remote_port"][:P]
        asn[B:] = 0
        port[B:] = 0
        for i, req in enumerate(requests):
            asn[i] = _clamp_i64(req.asn)
            port[i] = _clamp_i64(req.remote_port)
        arrays["asn"] = asn
        arrays["remote_port"] = port
        self._pack_meta(pk, P, buf, layout)
        return RequestBatch(size=P, arrays=arrays, overflow=overflow,
                            packed=pk, layout=layout,
                            staged_bytes=P * W)

    def _pack_meta(self, pk: np.ndarray, P: int, buf: dict,
                   layout: PackedLayout) -> None:
        """Write the metadata tail of every packed row from the side
        arrays (hot): u16-LE lens columns, big-endian IP bytes, i64-LE
        asn/port bytes. The side arrays stay authoritative for host
        consumers; the tail is what the device decodes."""
        for field, off in layout.lens:
            lens = buf[f"{field}_len"][:P]
            pk[:, off] = lens & 0xFF
            pk[:, off + 1] = (lens >> 8) & 0xFF
        pk[:, layout.ip_off:layout.ip_off + 16] = \
            buf["ip"][:P].astype(">u4").view(np.uint8)
        pk[:, layout.asn_off:layout.asn_off + 8] = \
            buf["asn"][:P].view(np.uint8).reshape(P, 8)
        pk[:, layout.port_off:layout.port_off + 8] = \
            buf["remote_port"][:P].view(np.uint8).reshape(P, 8)

    def encode_slots(self, slots: np.ndarray,
                     pad_to: Optional[int] = None) -> RequestBatch:
        """Shm slot rows -> bucketed+padded staging views (hot).

        `slots` is a structured-array view over n REQUEST_SLOT_DTYPE
        rows (native_ring.Ring.dequeue_batch_into buffers). Per field:
        one vectorized strided copy out of the slots, lens cast in the
        same assignment — value-identical to the legacy
        slots_to_arrays -> bucket_arrays -> pad_batch chain, with no
        intermediate matrices and no per-slot tuples."""
        n = len(slots)
        P = n if pad_to is None else int(pad_to)
        if not n or P < n or P > self.max_batch:
            raise ValueError(f"bad staging shape: n={n} pad_to={pad_to} "
                             f"max_batch={self.max_batch}")
        buf = self._checkout()
        if self._layout is not None:
            return self._encode_slots_packed(slots, n, P, buf)
        arrays: dict = {}
        for field, len_key in SLOT_LEN_KEYS.items():
            cap = self.specs.get(field, 256)
            lens = buf[f"{field}_len"][:P]
            lens[:n] = slots[len_key]
            lens[n:] = 0
            longest = int(lens[:n].max()) if n else 0
            L = bucket_len(longest, cap)
            data = buf[f"{field}_bytes"][:P, :L]
            data[:n] = slots[field][:, :L]
            data[n:] = 0
            arrays[f"{field}_bytes"] = data
            arrays[f"{field}_len"] = lens
        # country: fixed 2-byte code, no slot length field (the legacy
        # path reports len 2 for live rows, 0 for padding).
        cdata = buf["country_bytes"][:P, :2]
        cdata[:n] = np.frombuffer(
            slots["country"].tobytes(), dtype=np.uint8).reshape(-1, 2)
        cdata[n:] = 0
        clens = buf["country_len"][:P]
        clens[:n] = 2
        clens[n:] = 0
        arrays["country_bytes"] = cdata
        arrays["country_len"] = clens
        ip = buf["ip"][:P]
        # big-endian slot words -> native u32 in one casting assignment.
        ip[:n] = slots["ip"].view(">u4")
        ip[n:] = 0
        arrays["ip"] = ip
        asn = buf["asn"][:P]
        asn[:n] = slots["asn"]
        asn[n:] = 0
        arrays["asn"] = asn
        port = buf["remote_port"][:P]
        port[:n] = slots["remote_port"]
        port[n:] = 0
        arrays["remote_port"] = port
        staged = sum(a.nbytes for a in arrays.values())
        return RequestBatch(size=P, arrays=arrays, overflow=None,
                            staged_bytes=staged)

    def _encode_slots_packed(self, slots: np.ndarray, n: int, P: int,
                             buf: dict) -> RequestBatch:
        """Compact-mode slot encode (hot): the capped prefix of every
        string field copied STRAIGHT from the shm slot rows into the
        packed buffer — one strided copy per field region, no
        intermediate per-field staging matrices. Depth-overflow rows
        (true slot length beyond a clamped cap) are flagged for the
        sidecar's interpreter backstop; with unclamped plan caps the
        thresholds equal the specs and no slot row can exceed them
        (over-spec requests already ride the TRUNCATED/spill flags)."""
        layout = self._layout
        W = layout.width
        pk = buf["packed"][: P * W].reshape(P, W)
        pk[:] = 0
        arrays: dict = {}
        overflow = buf["overflow"][:P]
        overflow[:] = False
        for field, off, w in layout.fields:
            data = pk[:, off:off + w]
            if field == "country":
                data[:n] = np.frombuffer(
                    slots["country"].tobytes(),
                    dtype=np.uint8).reshape(-1, 2)[:, :w]
                clens = buf["country_len"][:P]
                clens[:n] = 2
                clens[n:] = 0
                arrays["country_bytes"] = data
                arrays["country_len"] = clens
                continue
            spec = self.specs.get(field, 256)
            limit = self._thresholds.get(field, spec)
            lens = buf[f"{field}_len"][:P]
            lens[:n] = slots[SLOT_LEN_KEYS[field]]
            lens[n:] = 0
            if limit < spec:
                overflow[:n] |= lens[:n] > limit
            data[:n] = slots[field][:, :w]
            arrays[f"{field}_bytes"] = data
            arrays[f"{field}_len"] = lens
        ip = buf["ip"][:P]
        ip[:n] = slots["ip"].view(">u4")
        ip[n:] = 0
        arrays["ip"] = ip
        asn = buf["asn"][:P]
        asn[:n] = slots["asn"]
        asn[n:] = 0
        arrays["asn"] = asn
        port = buf["remote_port"][:P]
        port[:n] = slots["remote_port"]
        port[n:] = 0
        arrays["remote_port"] = port
        self._pack_meta(pk, P, buf, layout)
        return RequestBatch(size=P, arrays=arrays, overflow=overflow,
                            packed=pk, layout=layout,
                            staged_bytes=P * W)


class DeviceInputQueue:
    """Double-buffered host->device input stacks for the megastep
    (ISSUE 12, docs/EXECUTOR.md "Device-resident loop").

    The megastep (engine/verdict.make_megastep_fn) consumes K batch
    slices as ONE stacked pytree {field: [K, B, ...]} plus device-side
    n_valid/epoch words per slice. This queue owns `nbuf` rotating
    stack sets sized to the field CAPACITIES, fills slice rows IN
    PLACE as batches arrive (strided copies out of the StagingEncoder's
    views, so the staging buffers are free to rotate immediately), and
    `device_stack` issues the ASYNC `jax.device_put` copy of the filled
    window — trimmed to the used K, the window's row bucket, and each
    byte field's window-max pow2 column width — into the *next* device
    buffer while the current megastep computes. Short slices are MASKED
    by their n_valid word, never re-shaped; each slice carries the
    ruleset epoch it was encoded under, echoed back untouched by the
    device program (the hot-swap megastep-boundary proof)."""

    def __init__(self, k: int, max_batch: int,
                 field_specs: Optional[Mapping[str, int]] = None,
                 nbuf: int = 2):
        specs = dict(field_specs or DEFAULT_FIELD_SPECS)
        self.k = max(1, int(k))
        self.max_batch = int(max_batch)
        self.specs = specs
        self.nbuf = max(2, int(nbuf))
        self._bufs: list[dict] = []
        self._widths: list[dict] = []
        self._rows: list[int] = [0] * self.nbuf
        for _ in range(self.nbuf):
            stacks: dict = {}
            for field in STRING_FIELDS:
                cap = specs.get(field, 256)
                stacks[f"{field}_bytes"] = np.zeros(
                    (self.k, self.max_batch, cap), dtype=np.uint8)
                stacks[f"{field}_len"] = np.zeros(
                    (self.k, self.max_batch), dtype=np.int32)
            stacks["ip"] = np.zeros(
                (self.k, self.max_batch, 4), dtype=np.uint32)
            stacks["asn"] = np.zeros(
                (self.k, self.max_batch), dtype=np.int64)
            stacks["remote_port"] = np.zeros(
                (self.k, self.max_batch), dtype=np.int64)
            stacks["n_valid"] = np.zeros(self.k, dtype=np.int32)
            stacks["epoch"] = np.zeros(self.k, dtype=np.int32)
            self._bufs.append(stacks)
            self._widths.append({})
        self._cursor = 0

    def checkout(self) -> int:
        """Claim the next stack set for a new megastep window. With
        nbuf >= 2 the window being filled is never the one a still
        in-flight megastep is computing over (double buffering)."""
        i = self._cursor
        self._cursor = (self._cursor + 1) % self.nbuf
        self._bufs[i]["n_valid"][:] = 0
        self._widths[i].clear()
        self._rows[i] = 0
        return i

    def fill_slice(self, buf_id: int, j: int, arrays: Mapping,
                   n_valid: int, epoch: int) -> None:
        """Copy one encoded batch slice into stack row j (hot): strided
        copies into the REUSED stacks; the source views (StagingEncoder
        buffers) may rotate as soon as this returns. Byte columns may be
        narrower than capacity (bucketed views) — the remainder up to
        the running window width is zeroed so a previous window's bytes
        cannot leak into this one."""
        buf = self._bufs[buf_id]
        widths = self._widths[buf_id]
        rows = 0
        for name, arr in arrays.items():
            rows = arr.shape[0]
            if name.endswith("_bytes"):
                # Invariant: every filled slice is valid (data + zeros)
                # out to the window width, so the shipped window-max
                # trim can never expose a previous window's bytes.
                w = arr.shape[1]
                prev = widths.get(name, 0)
                dst = buf[name][j, :rows]
                dst[:, :w] = arr
                if w < prev:
                    dst[:, w:prev] = 0
                elif w > prev:
                    if prev and j:
                        buf[name][:j, :rows, prev:w] = 0
                    widths[name] = w
            else:
                buf[name][j, :rows] = arr
        if self._rows[buf_id] and rows != self._rows[buf_id]:
            raise ValueError(
                f"megastep slices must share one row bucket: "
                f"{rows} != {self._rows[buf_id]}")
        self._rows[buf_id] = rows
        buf["n_valid"][j] = n_valid
        buf["epoch"][j] = epoch

    def slice_view(self, buf_id: int, j: int, n: int) -> dict:
        """Host views of slice j's first n rows (capacity-width) — the
        resolve path's raw batch, stable until this buffer set is
        checked out again (nbuf - 1 windows later)."""
        buf = self._bufs[buf_id]
        return {name: buf[name][j, :n]
                for name in buf if name not in ("n_valid", "epoch")}

    def device_stack(self, buf_id: int, k_used: int, pad_to: int = 0):
        """Issue the ASYNC host->device copy of the filled window (hot):
        (stacked arrays, n_valid, epoch) device values, trimmed to
        `k_used` slices, the window's row bucket, and each byte field's
        window-max pow2 column width. jax.device_put only ENQUEUES the
        transfer — the caller overlaps it with the in-flight megastep's
        compute before dispatching this window.

        `pad_to` ships a LARGER leading dim than the filled count:
        every distinct K is its own XLA compile of the scan, so callers
        quantize short windows up to a pow2 rung instead of paying a
        fresh multi-second compile per arbitrary length. The padded
        slices carry whatever bytes the stacks held — checkout() zeroed
        their n_valid words, so the device program masks them out."""
        import jax

        k_ship = min(self.k, max(k_used, pad_to))
        buf = self._bufs[buf_id]
        widths = self._widths[buf_id]
        rows = self._rows[buf_id] or self.max_batch
        stacked = {}
        for name, stack in buf.items():
            if name in ("n_valid", "epoch"):
                continue
            view = stack[:k_ship, :rows]
            if name.endswith("_bytes"):
                view = view[:, :, :widths.get(name, stack.shape[2])]
            stacked[name] = view
        return (jax.device_put(stacked),
                jax.device_put(buf["n_valid"][:k_ship]),
                jax.device_put(buf["epoch"][:k_ship]))


def batch_to_contexts(
    batch: RequestBatch, lists: Mapping[str, list]
) -> list[Context]:
    """Rebuild interpreter contexts from the encoded batch — the parity
    oracle sees exactly the (truncated) bytes the device saw."""
    out = []
    B = batch.size
    for i in range(B):
        fields = {}
        for field in STRING_FIELDS:
            data = batch[f"{field}_bytes"][i]
            n = int(batch[f"{field}_len"][i])
            fields[field] = bytes(data[:n]).decode("latin-1")
        ip = _words_to_ip(batch["ip"][i])
        ctx = Context(
            {
                "http_request": {
                    "host": fields["host"],
                    "url": fields["url"],
                    "path": fields["path"],
                    "method": fields["method"],
                    "user_agent": fields["user_agent"],
                },
                "client": {
                    "ip": ip,
                    "remote_port": int(batch["remote_port"][i]),
                    "asn": int(batch["asn"][i]),
                    "country": fields["country"],
                },
                "lists": dict(lists),
            }
        )
        out.append(ctx)
    return out


def tuple_to_context(tup: RequestTuple, lists: Mapping[str, list]) -> Context:
    """Interpreter context straight from the UNTRUNCATED request tuple —
    used for overflow-row re-evaluation and route matching. The reference
    builds the same variable shape at http_listener.rs:238-249."""
    try:
        ip = Ip(tup.ip)
    except Exception:
        ip = Ip("0.0.0.0")
    return Context({
        "http_request": {
            "host": tup.host, "url": tup.url, "path": tup.path,
            "method": tup.method, "user_agent": tup.user_agent,
        },
        "client": {
            "ip": ip, "remote_port": tup.remote_port,
            "asn": tup.asn, "country": tup.country,
        },
        "lists": dict(lists),
    })


def _words_to_ip(words: np.ndarray) -> Ip:
    value = 0
    for w in words:
        value = (value << 32) | int(w)
    import ipaddress

    if (value >> 32) == 0xFFFF:  # v4-mapped
        return Ip(ipaddress.ip_address(value & 0xFFFFFFFF))
    return Ip(ipaddress.ip_address(value))


def requests_from_dicts(rows: Iterable[Mapping]) -> list[RequestTuple]:
    return [RequestTuple(**row) for row in rows]
